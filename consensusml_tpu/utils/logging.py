"""Metrics logging: stdout lines + machine-readable JSONL.

Covers the reference's metrics/logging subsystem (SURVEY.md §5; mount
empty). Writes one JSON object per round with wall-clock, loss, and
consensus-error — the headline pair — plus anything the caller adds.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, IO

__all__ = ["MetricsLogger"]


class MetricsLogger:
    def __init__(self, jsonl_path: str | None = None, stream: IO = sys.stdout, every: int = 1):
        self._file = open(jsonl_path, "a") if jsonl_path else None
        self._stream = stream
        self._every = max(1, every)
        self._t0 = time.time()

    def log(self, round_idx: int, metrics: dict[str, Any]) -> None:
        record = {
            "round": round_idx,
            "wall_s": round(time.time() - self._t0, 3),
            **{k: (float(v) if hasattr(v, "item") or isinstance(v, (int, float)) else v)
               for k, v in metrics.items()},
        }
        if self._file:
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
        if round_idx % self._every == 0:
            parts = " ".join(
                f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}"
                for k, v in record.items()
                if k != "round"
            )
            print(f"[round {round_idx}] {parts}", file=self._stream, flush=True)

    def close(self) -> None:
        if self._file:
            self._file.close()
