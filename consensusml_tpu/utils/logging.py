"""Metrics logging: stdout lines + machine-readable JSONL.

Since the obs subsystem landed this is a THIN SHIM over
:mod:`consensusml_tpu.obs.metrics`: every ``log()`` feeds the numeric
fields into the process-wide :class:`~consensusml_tpu.obs.MetricsRegistry`
as ``consensusml_<name>`` gauges (so the Prometheus exporter and the
flight recorder see the same values the JSONL gets) and keeps the original
per-round JSONL record + stdout line byte-compatible with the pre-obs
format. Kept for backward compatibility; new code should talk to the
registry directly.

``MetricsLogger`` is a context manager — use ``with`` (or
``contextlib.ExitStack``) so the JSONL handle closes on exception paths
instead of leaking to interpreter exit.
"""

from __future__ import annotations

import json
import re
import sys
import time
from typing import Any, IO

from consensusml_tpu.obs import MetricsRegistry, get_registry

__all__ = ["MetricsLogger"]

# caller metric keys are free-form ("plus anything the caller adds") but
# Prometheus names are not: one bad character would make the textfile
# collector reject the WHOLE exposition file
_PROM_SAFE = re.compile(r"[^a-zA-Z0-9_:]")


class MetricsLogger:
    def __init__(
        self,
        jsonl_path: str | None = None,
        stream: IO = sys.stdout,
        every: int = 1,
        registry: MetricsRegistry | None = None,
    ):
        self._file = open(jsonl_path, "a") if jsonl_path else None
        self._stream = stream
        self._every = max(1, every)
        self._t0 = time.time()
        self._registry = registry if registry is not None else get_registry()

    def log(self, round_idx: int, metrics: dict[str, Any]) -> None:
        record = {
            "round": round_idx,
            "wall_s": round(time.time() - self._t0, 3),
            **{k: (float(v) if hasattr(v, "item") or isinstance(v, (int, float)) else v)
               for k, v in metrics.items()},
        }
        for k, v in record.items():
            if k != "round" and isinstance(v, float):
                name = _PROM_SAFE.sub("_", f"consensusml_{k}")
                self._registry.gauge(name).set(v)
        if self._file:
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
        if round_idx % self._every == 0:
            parts = " ".join(
                f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}"
                for k, v in record.items()
                if k != "round"
            )
            print(f"[round {round_idx}] {parts}", file=self._stream, flush=True)

    def close(self) -> None:
        if self._file:
            self._file.close()
            self._file = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
