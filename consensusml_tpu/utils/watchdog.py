"""Bounded-time failure detection for multi-process training.

Reference parity: SURVEY.md §5 failure detection. When a peer process
dies mid-round, the survivors' next cross-process collective never
completes — gloo/ICI sends simply wait for a participant that is gone,
wedging the process inside a C++ call that Python exception handling
cannot reach. The watchdog guarantees a BOUNDED exit anyway: a daemon
thread watches a heartbeat the training loop taps once per round, and if
no beat lands within the timeout it prints a reasoned diagnostic and
hard-exits (``os._exit`` — the main thread is unrecoverable by
construction, so interpreter cleanup must be skipped).

Enabled via ``train.py --round-timeout SECONDS``. Pick a timeout well
above one round's wall time INCLUDING the first round's XLA compile, or
start the clock late with ``arm_on_first_beat=True`` (train.py does: the
watchdog only arms once one full round has completed, so compile time
never counts against the budget).
"""

from __future__ import annotations

import os
import sys
import threading
import time

from consensusml_tpu.analysis import guarded_by

__all__ = ["ProgressWatchdog"]


@guarded_by("_lock", "_last", "_tag", "_armed")
class ProgressWatchdog:
    """Hard-exit the process if :meth:`beat` stops arriving.

    ``beat(tag)`` is called by the owner after every unit of progress;
    the monitor thread fires when ``timeout_s`` elapses without one and
    exits the process with ``exit_code`` (distinct from normal failure
    exits so launchers can tell "peer loss" from "bad config").

    The (deadline, tag, armed) triple moves under ``_lock`` so the
    monitor always reads a CONSISTENT beat — the old lock-free beat
    could time out on a fresh ``_last`` while printing a stale ``_tag``
    in the diagnostic, or miss a ``pause()`` racing a ``beat()``. One
    uncontended lock per ROUND (not per step) is noise.
    """

    def __init__(
        self,
        timeout_s: float,
        label: str = "train round",
        exit_code: int = 3,
        arm_on_first_beat: bool = True,
        on_timeout=None,
        exit_fn=os._exit,
    ):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be positive, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.label = label
        self.exit_code = exit_code
        # ``on_timeout(reason_str)`` runs (exception-guarded) between the
        # diagnostic and the hard exit — the flight-recorder dump hook:
        # the last rounds' spans/metrics land on disk even though the
        # main thread is unrecoverable (obs.flight.FlightRecorder.dump).
        self.on_timeout = on_timeout
        # ``exit_fn`` exists for tests: the timeout path is otherwise
        # untestable in-process (os._exit skips pytest entirely)
        self._exit_fn = exit_fn
        self._lock = threading.Lock()
        self._armed = not arm_on_first_beat
        self._last = time.monotonic()
        self._tag: object = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "ProgressWatchdog":
        with self._lock:
            self._last = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name="progress-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def beat(self, tag: object = None) -> None:
        """Record progress (one uncontended lock + two stores; called
        once per round)."""
        with self._lock:
            self._last = time.monotonic()
            self._tag = tag
            self._armed = True
        # cluster-view liveness: the beat doubles as this rank's
        # heartbeat counter (obs.cluster reads staleness off the
        # heartbeat gauge the train loop sets; the counter tells a
        # straggler apart from a rank whose watchdog is simply off)
        from consensusml_tpu.obs import get_registry

        get_registry().counter(
            "consensusml_watchdog_beats_total",
            "watchdog progress beats (one per completed unit of work)",
        ).inc()

    def pause(self) -> None:
        """Suspend deadline enforcement until the next :meth:`beat` —
        for phases with a legitimately unbounded first cost (a periodic
        eval's XLA compile) that must not read as a dead peer. The clock
        restarts from the resuming beat."""
        with self._lock:
            self._armed = False

    def stop(self) -> None:
        self._stop.set()

    # -- monitor thread ----------------------------------------------------
    def _run(self) -> None:
        poll = min(1.0, self.timeout_s / 4)
        while not self._stop.wait(poll):
            with self._lock:
                if not self._armed:
                    # clock starts at first beat
                    self._last = time.monotonic()
                    continue
                stalled = time.monotonic() - self._last
                tag = self._tag
            if stalled > self.timeout_s:
                reason = (
                    f"no {self.label} progress for "
                    f"{stalled:.0f}s (timeout {self.timeout_s:.0f}s, last "
                    f"progress: {tag})"
                )
                print(
                    f"watchdog: {reason}; a peer process has likely "
                    "died mid-collective — exiting so the launcher can "
                    "reschedule (see consensusml_tpu.utils.watchdog)",
                    file=sys.stderr,
                    flush=True,
                )
                sys.stderr.flush()
                if self.on_timeout is not None:
                    try:
                        self.on_timeout(f"watchdog-timeout: {reason}")
                    except Exception as e:
                        print(
                            f"watchdog: on_timeout hook failed: {e}",
                            file=sys.stderr,
                            flush=True,
                        )
                self._exit_fn(self.exit_code)
                return  # only reached with a test exit_fn
