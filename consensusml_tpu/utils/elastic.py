"""Elastic world membership: resume a run at a different worker count.

SURVEY.md §5 flags elastic recovery as plausible in the reference (mount
empty). The TPU design makes *in-flight* membership change meaningless —
workers are mesh shards inside one compiled program, not processes that
can come and go — so elasticity lives at the CHECKPOINT boundary instead:
``train.py --resume ckpt --workers W'`` restores a checkpoint written at
any world size and resizes the stacked state to the new mesh.

Semantics (:func:`resize_state`):

- **shrink** (W -> W' < W): replicas ``0..W'-1`` keep their exact state
  (params, optimizer, rng). Departed workers' replicas are dropped — the
  same information loss a real leave event causes; the survivors'
  consensus process is unaffected because every mixing matrix row is a
  convex combination.
- **grow** (W -> W' > W): joiners bootstrap from the CONSENSUS MEAN of
  the existing replicas (what a real joiner would fetch from the network)
  with a FRESH optimizer state and a fresh rng stream (folded from the
  caller's key), then drift apart naturally through local SGD.
- **gossip state is reset for everyone**: CHOCO's ``xhat``/``s`` tracking
  and push-sum's mass conservation are invariants over a FIXED membership
  — stale tracking from a different world would silently bias the mean.
  One reset means compressed gossip re-warms its error-feedback (a few
  rounds of extra consensus error, visible in the metric), which is the
  honest cost of a membership change.
- **SlowMo restarts** (``x = params, u = 0``): slow momentum from a
  different membership is not meaningful.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from consensusml_tpu.train.local_sgd import LocalSGDConfig, TrainState, _gossiped
from consensusml_tpu.train.outer import slowmo_init
from consensusml_tpu.utils.tree import consensus_mean

__all__ = ["resize_state"]


def _take(tree: Any, n: int) -> Any:
    return jax.tree.map(lambda x: x[:n], tree)


def _grow(tree: Any, mean_tree: Any, n_new: int) -> Any:
    return jax.tree.map(
        lambda x, m: jnp.concatenate(
            [x, jnp.broadcast_to(m[None], (n_new, *m.shape)).astype(x.dtype)]
        ),
        tree,
        mean_tree,
    )


def _grow_rows(tree: Any, rows: Any) -> Any:
    """Append already-stacked ``(n_new, ...)`` joiner rows to each leaf."""
    return jax.tree.map(
        lambda x, r: jnp.concatenate([x, jnp.asarray(r).astype(x.dtype)]),
        tree,
        rows,
    )


def resize_state(
    cfg: LocalSGDConfig,
    state: TrainState,
    new_world: int,
    rng: jax.Array | None = None,
    joiner_params: Any | None = None,
    joiner_model_state: Any | None = None,
) -> TrainState:
    """Return ``state`` resized to ``new_world`` stacked replicas.

    No-op when the size already matches. ``rng`` seeds the JOINERS' data
    streams when growing (defaults to ``jax.random.key(0)``). The result
    is host-side/unsharded — re-shard with ``WorkerMesh.shard_stacked``
    for the collective backend.

    ``joiner_params`` / ``joiner_model_state`` (grow only): stacked
    ``(n_new, ...)`` rows the joiners start from INSTEAD of the
    consensus-mean broadcast — the swarm gossip-bootstrap path
    (:mod:`consensusml_tpu.swarm.bootstrap`), where a joiner has already
    reconstructed its replica from neighbor gossip and no checkpoint was
    read. Optimizer state is fresh either way (initialized on the joiner
    rows); everything else follows the same grow semantics.
    """
    old_world = int(state.step.shape[0])
    if new_world == old_world:
        return state
    if new_world < 1:
        raise ValueError(f"new_world must be positive, got {new_world}")

    from consensusml_tpu.obs import get_registry

    get_registry().counter(
        "consensusml_elastic_resizes_total",
        "elastic world-membership changes applied at resume",
    ).inc()
    get_registry().counter(
        "consensusml_elastic_joined_workers_total",
        "workers bootstrapped from the consensus mean by elastic grows",
    ).inc(max(0, new_world - old_world))
    get_registry().gauge(
        "consensusml_elastic_world_size",
        "stacked world size after the latest elastic resize "
        "(cluster-view membership)",
    ).set(new_world)

    if new_world < old_world:
        if joiner_params is not None:
            raise ValueError("joiner_params only applies when growing")
        params = _take(state.params, new_world)
        model_state = _take(state.model_state, new_world)
        opt_state = _take(state.opt_state, new_world)
        rngs = state.rng[:new_world]
        step = state.step[:new_world]
    else:
        n_new = new_world - old_world
        if joiner_params is not None:
            # gossip-bootstrapped joiners: rows come from the caller
            params = _grow_rows(state.params, joiner_params)
            model_state = (
                _grow_rows(state.model_state, joiner_model_state)
                if joiner_model_state is not None
                else _grow(state.model_state, consensus_mean(state.model_state), n_new)
            )
            new_block = jax.tree.map(jnp.asarray, joiner_params)
        else:
            mean_p = consensus_mean(state.params)
            mean_ms = consensus_mean(state.model_state)
            params = _grow(state.params, mean_p, n_new)
            model_state = _grow(state.model_state, mean_ms, n_new)
            # joiners: fresh optimizer state on their (mean) params
            new_block = jax.tree.map(
                lambda m: jnp.broadcast_to(m[None], (n_new, *m.shape)), mean_p
            )
        new_opt = jax.vmap(cfg.optimizer.init)(new_block)
        opt_state = jax.tree.map(
            lambda a, b: jnp.concatenate([a, b.astype(a.dtype)]),
            state.opt_state,
            new_opt,
        )
        base = jax.random.key(0) if rng is None else rng
        new_rngs = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
            base, jnp.arange(old_world, new_world)
        )
        rngs = jnp.concatenate([state.rng, new_rngs])
        step = jnp.concatenate(
            [state.step, jnp.broadcast_to(state.step[:1], (n_new,))]
        )

    return TrainState(
        step=step,
        params=params,
        model_state=model_state,
        opt_state=opt_state,
        # membership changed: tracking/mass invariants from the old world
        # no longer hold — reset (see module docstring for the cost)
        gossip=cfg.engine().init_state(
            _gossiped(params, model_state), world_size=new_world
        ),
        rng=rngs,
        outer=slowmo_init(params) if cfg.outer is not None else None,
    )
