"""Tiny pytree math shared across subsystems.

:func:`consensus_mean` is THE definition of "the consensus model": the
unweighted worker-mean of a stacked (leading worker axis) pytree, reduced
in f32 and cast back per-leaf. Three subsystems must agree on it bit for
bit — held-out evaluation of the mean model (``train/evaluate.py``),
elastic joiner bootstrap (``utils/elastic.py``), and the serving export
(``serve/export.py``, whose golden parity test asserts export→serve
logits match the eval path exactly) — so it lives here once instead of
as three inlined tree-maps that could drift.

Under churn the same contract extends to the ALIVE subset:
:func:`masked_worker_mean` is the one definition of the alive-weighted
leaf mean that gossip bootstrap (``swarm/bootstrap.py``), dead-row
aggregation (``swarm/harness.py``), and the masked agreement metric
(``comm/simulated.py``) all reduce with — same f32 accumulation, same
``max(sum(alive), 1)`` everyone-dead guard.

Pure ``jnp``: safe to call inside jit (evaluate does) or eagerly on host
trees (elastic resume, export).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

__all__ = ["consensus_mean", "masked_worker_mean"]


def masked_worker_mean(x, alive, n_alive=None):
    """f32 alive-weighted mean of ONE leaf over its leading stacked axis.

    ``alive``: ``(world,)`` of 0/1 floats. Rows with 0 weight contribute
    nothing; the divisor is ``max(sum(alive), 1)`` (pass ``n_alive`` to
    reuse a precomputed divisor) so an everyone-dead round yields 0, not
    NaN. Returns f32 at the leaf's trailing shape — callers cast back.
    """
    a = jnp.asarray(alive, jnp.float32)
    x32 = jnp.asarray(x, jnp.float32)
    w = a.reshape((a.shape[0],) + (1,) * (x32.ndim - 1))
    n = jnp.maximum(jnp.sum(a), 1.0) if n_alive is None else n_alive
    return jnp.sum(x32 * w, axis=0) / n


def consensus_mean(tree: Any, alive=None) -> Any:
    """Worker-mean over the leading stacked axis of every leaf.

    Reduces in f32 (bf16 accumulation would lose low bits exactly where
    replicas disagree least) and casts back to each leaf's dtype. With
    ``alive`` (a ``(world,)`` 0/1 vector) the mean is restricted to the
    alive rows — the churn-regime variant (docs/elasticity.md).
    """
    if alive is None:
        return jax.tree.map(
            lambda x: jnp.mean(jnp.asarray(x, jnp.float32), axis=0).astype(
                x.dtype
            ),
            tree,
        )
    return jax.tree.map(
        lambda x: masked_worker_mean(x, alive).astype(x.dtype), tree
    )
