"""Tiny pytree math shared across subsystems.

:func:`consensus_mean` is THE definition of "the consensus model": the
unweighted worker-mean of a stacked (leading worker axis) pytree, reduced
in f32 and cast back per-leaf. Three subsystems must agree on it bit for
bit — held-out evaluation of the mean model (``train/evaluate.py``),
elastic joiner bootstrap (``utils/elastic.py``), and the serving export
(``serve/export.py``, whose golden parity test asserts export→serve
logits match the eval path exactly) — so it lives here once instead of
as three inlined tree-maps that could drift.

Pure ``jnp``: safe to call inside jit (evaluate does) or eagerly on host
trees (elastic resume, export).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

__all__ = ["consensus_mean"]


def consensus_mean(tree: Any) -> Any:
    """Worker-mean over the leading stacked axis of every leaf.

    Reduces in f32 (bf16 accumulation would lose low bits exactly where
    replicas disagree least) and casts back to each leaf's dtype.
    """
    return jax.tree.map(
        lambda x: jnp.mean(jnp.asarray(x, jnp.float32), axis=0).astype(x.dtype),
        tree,
    )
