"""Tracing/profiling subsystem (SURVEY.md §5 aux subsystems).

Three layers, smallest first:

- :func:`annotate` — name a region of traced computation so it shows up
  as a labeled span in XLA/xprof traces (``jax.named_scope``: attaches to
  the HLO, so the label survives compilation — the TPU answer to the
  reference's NVTX-style ranges).
- :class:`RoundTimer` — honest wall-clock stats over training rounds.
  "Honest" matters on this box: the tunneled TPU backend returns from
  ``block_until_ready`` at enqueue time, so the timer fences each lap by
  fetching a scalar to the host (see bench.py for the same trick).
- :func:`trace` — a context manager around ``jax.profiler`` start/stop
  that dumps an xprof/TensorBoard trace directory for deep dives
  (per-op device timelines, HBM traffic, ICI collectives).

Wired into ``train.py`` via ``--profile-dir`` (trace of a few steady-state
rounds) and the end-of-run round-time summary.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import time
from typing import Any, Iterator

import jax
import numpy as np

__all__ = ["annotate", "RoundTimer", "RoundStats", "trace", "fence"]


def annotate(name: str):
    """Label traced computation: ``with annotate("gossip"): ...`` inside a
    jitted function tags the resulting HLO ops for xprof."""
    return jax.named_scope(name)


def fence(tree: Any) -> None:
    """True execution barrier: fetch one scalar element per leaf to host.

    ``jax.block_until_ready`` is NOT sufficient on tunneled backends
    (observed on this box's axon TPU: it returns at enqueue). A device->
    host copy cannot complete before the producing computation has, so
    fetching is the reliable fence on every backend.
    """
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "addressable_shards"):
            shard = leaf.addressable_shards[0].data
            # slice ON DEVICE first: device_get of the raw shard would copy
            # the whole buffer to host, a hidden D2H if fencing on params
            first = shard.reshape(-1)[:1] if shard.size else shard
            np.asarray(jax.device_get(first))
        else:
            np.asarray(leaf).ravel()[:1]


@dataclasses.dataclass(frozen=True)
class RoundStats:
    """Summary of per-round wall times (seconds)."""

    count: int
    mean_s: float
    p50_s: float
    p95_s: float
    min_s: float
    max_s: float
    total_s: float

    def format(self) -> str:
        return (
            f"rounds={self.count} mean={self.mean_s * 1e3:.1f}ms "
            f"p50={self.p50_s * 1e3:.1f}ms p95={self.p95_s * 1e3:.1f}ms "
            f"min={self.min_s * 1e3:.1f}ms max={self.max_s * 1e3:.1f}ms"
        )


class RoundTimer:
    """Collects per-round wall times with an honest device fence per lap.

    Usage::

        timer = RoundTimer(warmup=1)
        for batch in batches:
            with timer.lap(metrics_fn=lambda: metrics):
                state, metrics = step(state, batch)
        print(timer.stats().format())

    ``lap`` fences on whatever the ``metrics_fn`` thunk returns AFTER the
    body ran (the body rebinds ``metrics``), so the measured lap includes
    the full device execution of the step, not just its dispatch. The
    first ``warmup`` laps (compilation) are recorded separately.
    """

    def __init__(self, warmup: int = 1):
        self._warmup = warmup
        self._laps: list[float] = []
        self._warmup_laps: list[float] = []
        # how long the last lap's FENCE blocked: dispatch returned, the
        # host sat waiting for the device to drain — the stall the
        # overlap-gossip scheduling is supposed to shrink. Exposed so
        # telemetry can gauge it (consensusml_round_stall_seconds).
        self.last_fence_s: float = 0.0
        self.last_lap_s: float = 0.0

    @contextlib.contextmanager
    def lap(self, metrics_fn=None) -> Iterator[None]:
        t0 = time.time()
        yield
        if metrics_fn is not None:
            t_fence = time.time()
            fence(metrics_fn())
            self.last_fence_s = time.time() - t_fence
        else:
            self.last_fence_s = 0.0
        dt = time.time() - t0
        self.last_lap_s = dt
        if len(self._warmup_laps) < self._warmup:
            self._warmup_laps.append(dt)
        else:
            self._laps.append(dt)

    @property
    def laps(self) -> list[float]:
        return list(self._laps)

    def stats(self) -> RoundStats:
        laps = self._laps or self._warmup_laps
        if not laps:
            return RoundStats(0, math.nan, math.nan, math.nan, math.nan, math.nan, 0.0)
        a = np.asarray(laps)
        return RoundStats(
            count=len(laps),
            mean_s=float(a.mean()),
            p50_s=float(np.percentile(a, 50)),
            p95_s=float(np.percentile(a, 95)),
            min_s=float(a.min()),
            max_s=float(a.max()),
            total_s=float(a.sum()),
        )


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Dump an xprof trace of the enclosed block to ``log_dir``.

    View with TensorBoard's profile plugin or xprof. Wraps
    ``jax.profiler.start_trace``/``stop_trace`` so a mid-block exception
    still stops the trace (leaving a valid dump).
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
