"""Runtime utilities: checkpointing, metrics logging, tracing."""

from consensusml_tpu.utils.checkpoint import (  # noqa: F401
    AsyncSaver,
    checkpoint_round,
    checkpoint_world_size,
    replicated_scalar,
    restore_state,
    save_state,
)
from consensusml_tpu.utils.elastic import resize_state  # noqa: F401
from consensusml_tpu.utils.tree import (  # noqa: F401
    consensus_mean,
    masked_worker_mean,
)
from consensusml_tpu.utils.logging import MetricsLogger  # noqa: F401
from consensusml_tpu.utils.watchdog import ProgressWatchdog  # noqa: F401
from consensusml_tpu.utils.profiling import (  # noqa: F401
    RoundStats,
    RoundTimer,
    annotate,
    fence,
    trace,
)
