"""Checkpoint/resume via orbax (SURVEY.md §5 aux subsystems).

Saves the full stacked TrainState (all workers' replicas + optimizer +
CHOCO gossip state + per-worker rng), so a decentralized run resumes
bit-exactly: disagreeing replicas stay disagreeing. The reference's
per-worker checkpoint files collapse to ONE sharded checkpoint here
because workers are mesh shards, not processes.
"""

from __future__ import annotations

import json
import os
import threading
import warnings
from typing import Any

import jax
import orbax.checkpoint as ocp

__all__ = [
    "save_state",
    "restore_state",
    "checkpoint_world_size",
    "checkpoint_round",
    "replicated_scalar",
    "AsyncSaver",
]


def _is_key(x: Any) -> bool:
    return hasattr(x, "dtype") and jax.dtypes.issubdtype(
        x.dtype, jax.dtypes.prng_key
    )


def _unwrap_keys(tree: Any) -> Any:
    """Typed PRNG-key leaves -> their raw uint32 key data.

    orbax (0.7 on this box) cannot serialize typed key arrays at all:
    its shard serializer calls ``np.array(shard.data)``, which
    ``PRNGKeyArray.__array__`` refuses — the root cause of the
    long-standing ``--checkpoint-dir`` + ``--checkpoint-every`` crash
    (and of end-of-run saves of any state holding typed keys). Keys ride
    the checkpoint as data; :func:`restore_state` re-wraps them from the
    template's impl.
    """
    return jax.tree.map(
        lambda x: jax.random.key_data(x) if _is_key(x) else x, tree
    )


def _rewrap_keys(tree: Any, like: Any) -> Any:
    """Inverse of :func:`_unwrap_keys`: where ``like`` holds a typed key,
    wrap the restored uint32 data back with the template's impl."""
    import jax.numpy as jnp

    return jax.tree.map(
        lambda r, l: (
            jax.random.wrap_key_data(
                jnp.asarray(r), impl=jax.random.key_impl(l)
            )
            if _is_key(l)
            else r
        ),
        tree,
        like,
    )


def save_state(path: str, state: Any, step: int | None = None) -> str:
    """Write a checkpoint at ``path`` (optionally ``path/step_N``).

    Alongside the orbax tree a small ``cml_meta.json`` records the world
    size (leading axis of ``state.step`` when present), which lets
    elastic resume (``utils.elastic``) rebuild the right-sized restore
    template without the caller knowing the original worker count.
    Typed PRNG-key leaves are stored as raw key data (orbax cannot
    serialize key arrays — see :func:`_unwrap_keys`).
    """
    path = os.path.abspath(path)
    if step is not None:
        path = os.path.join(path, f"step_{step}")
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, _unwrap_keys(state), force=True)
    step_leaf = getattr(state, "step", None)
    if step_leaf is not None and getattr(step_leaf, "ndim", 0) == 1:
        # atomic write: a preemption mid-write must leave either no meta
        # (falls back to pre-meta behavior) or a complete one — never a
        # truncated file that poisons every later --resume
        meta = os.path.join(path, "cml_meta.json")
        tmp = meta + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "world_size": int(step_leaf.shape[0]),
                    "round": replicated_scalar(step_leaf),
                },
                f,
            )
        os.replace(tmp, meta)
    return path


def replicated_scalar(leaf) -> int:
    """First element of a replicated per-worker counter (e.g.
    ``TrainState.step``), fetched through ONE addressable shard —
    ``device_get`` of the whole leaf fails on arrays sharded across
    processes (two-controller runs)."""
    if hasattr(leaf, "addressable_shards"):
        leaf = leaf.addressable_shards[0].data
    import numpy as np

    return int(np.asarray(jax.device_get(leaf)).ravel()[0])


class AsyncSaver:
    """Overlap checkpoint writes with training.

    ``submit`` snapshots the state to host (the only device-blocking
    part) and hands the disk write to a background thread, so round
    ``r+1`` trains while round ``r``'s checkpoint serializes. One write
    in flight at a time — a new submit waits for the previous one (disk
    is the bottleneck; queueing snapshots would just grow host memory).
    Call ``wait()`` before reading results / process exit. Errors raise
    on the NEXT submit or wait, never silently.

    Multi-controller runs keep the SYNCHRONOUS path (orbax coordinates
    all processes inside save; deferring it to unsynchronized threads
    would skew the barrier), so ``submit`` falls back to a direct save
    when ``jax.process_count() > 1``.
    """

    def __init__(self):
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None
        self.last_path: str | None = None

    def submit(self, path: str, state: Any, step: int | None = None) -> None:
        self.wait()
        if jax.process_count() > 1:
            self.last_path = save_state(path, state, step=step)
            return
        snapshot = _host_snapshot(state)

        def write():
            try:
                self.last_path = save_state(path, snapshot, step=step)
            except BaseException as e:  # surfaced on next submit/wait
                self._error = e

        # NON-daemon: the interpreter joins it at shutdown, so a crash or
        # Ctrl-C in a later round still lets the in-flight write finish —
        # a checkpoint the log reported saved must never end up partial
        self._thread = threading.Thread(target=write, daemon=False)
        self._thread.start()

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(f"async checkpoint write failed: {err!r}") from err


def _host_snapshot(state: Any) -> Any:
    """Host copy of ``state`` for a background orbax write.

    Plain leaves fetch to numpy (one batched transfer — the only
    device-blocking part of an async save). Typed PRNG-key leaves must
    STAY jax Arrays: ``device_get`` hands back a key array whose base is
    a raw numpy ndarray, and orbax's ArrayHandler then crashes walking
    ``.addressable_shards`` on it (the long-standing --checkpoint-every
    background-write failure). They also must be REAL COPIES — a
    same-device ``device_put`` aliases the training buffer, which the
    next donated train step deletes out from under the background write
    — so the key data round-trips through host numpy and re-wraps.
    """
    keys, others = [], []
    flat, treedef = jax.tree_util.tree_flatten(state)
    for x in flat:
        (keys if _is_key(x) else others).append(x)
    fetched = iter(jax.device_get(others))
    import jax.numpy as jnp

    moved = iter(
        [
            # jnp.asarray, NOT the raw numpy: wrap_key_data keeps
            # whatever base it is handed, and a numpy-backed key array
            # reproduces the exact ArrayHandler crash being fixed
            jax.random.wrap_key_data(
                jnp.asarray(jax.device_get(jax.random.key_data(k))),
                impl=jax.random.key_impl(k),
            )
            for k in keys
        ]
    )
    return jax.tree_util.tree_unflatten(
        treedef, [next(moved) if _is_key(x) else next(fetched) for x in flat]
    )


def _meta_int(path: str, key: str) -> int | None:
    """One int field of ``cml_meta.json``, or None (pre-meta checkpoint or
    unreadable/corrupt meta — treated as absent, never raised)."""
    meta = os.path.join(os.path.abspath(path), "cml_meta.json")
    try:
        with open(meta) as f:
            return int(json.load(f)[key])
    except (OSError, ValueError, KeyError, TypeError):
        return None


def checkpoint_world_size(path: str) -> int | None:
    """World size recorded at save time, or None when absent."""
    return _meta_int(path, "world_size")


def checkpoint_round(path: str) -> int | None:
    """Gossip round recorded at save time, or None (older checkpoints
    predate the record). Lets the CLI extend an LR schedule across
    ``--resume`` without restoring the state first."""
    return _meta_int(path, "round")


def restore_state(path: str, like: Any) -> Any:
    """Restore a checkpoint into the structure/shardings of ``like``.

    Gossip-state layout drift is healed rather than fatal: when the
    ONLY structural mismatch is under the ``gossip`` subtree (e.g. a
    pre-``compress_filter="auto"`` checkpoint whose ChocoState covered
    ``model_state`` leaves that the current engine exact-mixes), the
    rest of the state is restored and the gossip state is RESET to
    ``like``'s freshly-initialized zeros — the same recovery
    ``utils.elastic.resize_state`` applies on a world-size change.
    CHOCO re-warms its error-feedback over the next few rounds; params,
    optimizer state, rng and step restore exactly.
    """
    path = os.path.abspath(path)
    # keys restore as raw uint32 data (see _unwrap_keys) and re-wrap at
    # the end from the template's impl
    key_template = like
    like = _unwrap_keys(like)
    try:
        return _rewrap_keys(_restore(path, like), key_template)
    except ValueError as e:
        # The drift test is STRUCTURAL, not a match on orbax's error text
        # (ADVICE r4: message wording changes across orbax versions): if
        # the on-disk tree has a gossip subtree, retry with that subtree
        # swapped for placeholders — the retry itself proves whether the
        # mismatch is confined to gossip, and re-raises the ORIGINAL
        # error when it is not.
        if not hasattr(like, "gossip"):
            raise
        disk_gossip = _disk_gossip_template(path)
        if disk_gossip is None:
            raise
        # PLACEHOLDER leaves satisfy the structural match WITHOUT reading
        # the obsolete gossip bytes (xhat+s ~ 2x model size) off disk —
        # they restore as `...` and are replaced below
        hybrid = like._replace(
            gossip=jax.tree.map(lambda _: ocp.PLACEHOLDER, disk_gossip)
        )
        try:
            restored = _restore(path, hybrid)
        except Exception:
            raise e  # more than gossip drifted — surface the real mismatch
        warnings.warn(
            "checkpoint gossip state has an old layout (it predates a "
            "compress_filter/codec change); gossip tracking state was "
            "RESET — compressed gossip re-warms its error feedback over "
            "the next few rounds, everything else restored exactly",
            stacklevel=2,
        )
        return _rewrap_keys(
            restored._replace(gossip=like.gossip), key_template
        )


def _restore(path: str, like: Any) -> Any:
    with ocp.PyTreeCheckpointer() as ckptr:
        restore_args = jax.tree.map(
            lambda x: ocp.ArrayRestoreArgs(sharding=getattr(x, "sharding", None)),
            like,
        )
        return ckptr.restore(
            path, args=ocp.args.PyTreeRestore(item=like, restore_args=restore_args)
        )


def _disk_gossip_template(path: str) -> Any | None:
    """The ON-DISK structure of the checkpoint's ``gossip`` subtree as
    abstract arrays (for a structure-matching throwaway restore), or
    None when the checkpoint has no such subtree."""
    try:
        with ocp.PyTreeCheckpointer() as ckptr:
            md = ckptr.metadata(path)
        tree = getattr(getattr(md, "item_metadata", md), "tree", None)
        if not isinstance(tree, dict) or "gossip" not in tree:
            return None
        return jax.tree.map(
            lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype), tree["gossip"]
        )
    except Exception:
        return None
