"""Checkpoint/resume via orbax (SURVEY.md §5 aux subsystems).

Saves the full stacked TrainState (all workers' replicas + optimizer +
CHOCO gossip state + per-worker rng), so a decentralized run resumes
bit-exactly: disagreeing replicas stay disagreeing. The reference's
per-worker checkpoint files collapse to ONE sharded checkpoint here
because workers are mesh shards, not processes.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import orbax.checkpoint as ocp

__all__ = ["save_state", "restore_state"]


def save_state(path: str, state: Any, step: int | None = None) -> str:
    """Write a checkpoint at ``path`` (optionally ``path/step_N``)."""
    path = os.path.abspath(path)
    if step is not None:
        path = os.path.join(path, f"step_{step}")
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)
    return path


def restore_state(path: str, like: Any) -> Any:
    """Restore a checkpoint into the structure/shardings of ``like``."""
    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        restore_args = jax.tree.map(
            lambda x: ocp.ArrayRestoreArgs(sharding=getattr(x, "sharding", None)),
            like,
        )
        return ckptr.restore(
            path, args=ocp.args.PyTreeRestore(item=like, restore_args=restore_args)
        )
