"""Checkpoint/resume via orbax (SURVEY.md §5 aux subsystems).

Saves the full stacked TrainState (all workers' replicas + optimizer +
CHOCO gossip state + per-worker rng), so a decentralized run resumes
bit-exactly: disagreeing replicas stay disagreeing. The reference's
per-worker checkpoint files collapse to ONE sharded checkpoint here
because workers are mesh shards, not processes.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import orbax.checkpoint as ocp

__all__ = ["save_state", "restore_state", "checkpoint_world_size"]


def save_state(path: str, state: Any, step: int | None = None) -> str:
    """Write a checkpoint at ``path`` (optionally ``path/step_N``).

    Alongside the orbax tree a small ``cml_meta.json`` records the world
    size (leading axis of ``state.step`` when present), which lets
    elastic resume (``utils.elastic``) rebuild the right-sized restore
    template without the caller knowing the original worker count.
    """
    path = os.path.abspath(path)
    if step is not None:
        path = os.path.join(path, f"step_{step}")
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)
    step_leaf = getattr(state, "step", None)
    if step_leaf is not None and getattr(step_leaf, "ndim", 0) == 1:
        # atomic write: a preemption mid-write must leave either no meta
        # (falls back to pre-meta behavior) or a complete one — never a
        # truncated file that poisons every later --resume
        meta = os.path.join(path, "cml_meta.json")
        tmp = meta + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"world_size": int(step_leaf.shape[0])}, f)
        os.replace(tmp, meta)
    return path


def checkpoint_world_size(path: str) -> int | None:
    """World size recorded at save time, or None (pre-meta checkpoint or
    unreadable/corrupt meta — treated as absent, never raised)."""
    meta = os.path.join(os.path.abspath(path), "cml_meta.json")
    try:
        with open(meta) as f:
            return int(json.load(f)["world_size"])
    except (OSError, ValueError, KeyError, TypeError):
        return None


def restore_state(path: str, like: Any) -> Any:
    """Restore a checkpoint into the structure/shardings of ``like``."""
    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        restore_args = jax.tree.map(
            lambda x: ocp.ArrayRestoreArgs(sharding=getattr(x, "sharding", None)),
            like,
        )
        return ckptr.restore(
            path, args=ocp.args.PyTreeRestore(item=like, restore_args=restore_args)
        )
