"""TPU-backend liveness preflight.

This box's tunneled TPU backend has been observed (rounds 1 and 3) to
WEDGE: a process's first ``jax.devices()`` call blocks forever instead of
raising, so any entry point that probes the backend in-process hangs past
the driver's budget and its artifact is lost. The fix is to never let the
*caller's* process initialize the backend speculatively: :func:`probe`
runs ``jax.devices()`` in a disposable child process under a hard timeout
and reports alive/wedged plus the device kind.

Used by ``bench.py`` (preflight before committing to axon-backend
sections), ``train.py --device tpu`` (fail fast with a clean error
instead of an infinite hang), and runnable standalone via
``python tools/tpu_health.py``.

Reference parity: no reference equivalent exists (NCCL has its own
timeout machinery); this is resilience against this environment's
tunnel, per VERDICT r3 items 1/6/7. SURVEY.md mount is empty — no
file:line can be cited.

Test hooks: ``TPU_HEALTH_CMD`` replaces the child's ``-c`` program (e.g.
an infinite sleep to fake a wedged tunnel) and ``TPU_HEALTH_TIMEOUT``
overrides the default timeout, so the hang path is testable in CI
without a TPU.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_MARK = "TPU_HEALTH "

# The child resolves the platform exactly the way any other subprocess on
# this box would (sitecustomize overrides JAX_PLATFORMS, so we don't try
# to force anything) and prints one marked JSON line.
_CHILD = (
    "import json, jax; ds = jax.devices(); "
    "print('TPU_HEALTH ' + json.dumps({"
    "'platform': jax.default_backend(), "
    "'n_devices': len(ds), "
    "'device_kind': getattr(ds[0], 'device_kind', '') if ds else ''}))"
)

TPU_PLATFORMS = ("tpu", "axon")

# the live probe child, so a caller's signal handler can reap it — a
# probe against a wedged tunnel is a python process hung in
# jax.devices() forever, and orphaning it would keep the tunnel held
_active_child: subprocess.Popen | None = None


def kill_active_probe() -> None:
    """Kill the in-flight probe child, if any (signal-handler safe)."""
    proc = _active_child
    if proc is not None:
        try:
            proc.kill()
        except Exception:
            pass


def probe(timeout: float | None = None) -> dict:
    """Probe backend liveness in a subprocess; never hangs the caller.

    Returns a dict with keys:

    - ``alive`` — the child initialized a jax backend and exited in time.
    - ``tpu`` — ``alive`` and the default platform is a TPU one.
    - ``platform`` / ``n_devices`` / ``device_kind`` — from the child.
    - ``elapsed_s`` — wall clock spent probing.
    - ``reason`` — human diagnostic when not ``alive``.
    """
    if timeout is None:
        timeout = float(os.environ.get("TPU_HEALTH_TIMEOUT", "120"))
    child = os.environ.get("TPU_HEALTH_CMD", _CHILD)
    out: dict = {
        "alive": False,
        "tpu": False,
        "platform": None,
        "n_devices": 0,
        "device_kind": "",
        "timeout_s": timeout,
    }
    global _active_child
    t0 = time.time()
    proc = subprocess.Popen(
        [sys.executable, "-c", child],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    _active_child = proc
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        out["elapsed_s"] = round(time.time() - t0, 1)
        out["reason"] = (
            f"backend probe did not return within {timeout:.0f}s — "
            "jax.devices() is hanging (wedged TPU tunnel?)"
        )
        return out
    finally:
        _active_child = None
    out["elapsed_s"] = round(time.time() - t0, 1)
    if proc.returncode != 0:
        out["reason"] = (
            f"backend probe exited rc={proc.returncode}: "
            f"{stderr.strip()[-400:]}"
        )
        return out
    for line in stdout.splitlines():
        if line.startswith(_MARK):
            try:
                info = json.loads(line[len(_MARK):])
            except json.JSONDecodeError:
                out["reason"] = f"unparseable probe output: {line[:200]}"
                return out
            out.update(info)
            out["alive"] = True
            out["tpu"] = out.get("platform") in TPU_PLATFORMS
            return out
    out["reason"] = f"probe printed no {_MARK.strip()} line: {stdout[-200:]}"
    return out


def main(argv: list[str] | None = None) -> int:
    """CLI: print the probe JSON; rc 0 = TPU alive, 1 = alive but no
    TPU (CPU-only), 2 = wedged or errored."""
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--timeout", type=float, default=None,
                   help="probe timeout in seconds (default $TPU_HEALTH_TIMEOUT or 120)")
    args = p.parse_args(argv)
    result = probe(timeout=args.timeout)
    print(json.dumps(result))
    if not result["alive"]:
        return 2
    return 0 if result["tpu"] else 1


if __name__ == "__main__":
    sys.exit(main())
