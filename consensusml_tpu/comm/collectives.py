"""Gossip collectives: per-worker code run inside ``shard_map``.

Reference parity (SURVEY.md L1/L3; file:line unavailable — mount empty):

- NCCL ``send``/``recv`` to each neighbor  -> :func:`ppermute_shift`
- NCCL ``all_reduce`` consensus step       -> ``jax.lax.pmean``
- the weighted neighbor-averaging update   -> :func:`mix` / :func:`mix_tree`

Every function here must be called from code that is being traced under a
``shard_map`` over a :class:`~consensusml_tpu.comm.mesh.WorkerMesh` whose
axis names match the topology's — they use named-axis collectives and will
raise outside that context. The mixing operator is mathematically identical
to ``W @ x`` with the topology's mixing matrix (tested against
:mod:`consensusml_tpu.comm.simulated`).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from consensusml_tpu.obs import get_registry
from consensusml_tpu.obs import span as _span
from consensusml_tpu.topology import Shift, Topology

# trace-time accounting: gossip programs are compiled once and replayed,
# so the per-ROUND ppermute count IS the per-COMPILE count (the quantity
# tests/test_bucketing.py jaxpr-asserts). Incremented while jit traces
# this module — zero steady-state cost.
_TRACED_PPERMUTES = get_registry().counter(
    "consensusml_ppermutes_traced_total",
    "ppermute collectives traced into gossip programs (per XLA compile)",
)

# per-EDGE trace-time wire accounting (obs.links / the cluster report's
# bytes-per-edge view): each traced ppermute knows its payload size and —
# via the topology's coordinate arithmetic — every directed (src, dst)
# rank pair it moves that payload across. Worlds past this cap skip the
# per-edge expansion (label cardinality), counted loudly instead.
_EDGE_ACCOUNT_MAX_WORLD = 256


def _account_edge_bytes(x, topology: Topology, shift: Shift) -> None:
    reg = get_registry()
    if topology.world_size > _EDGE_ACCOUNT_MAX_WORLD:
        reg.counter(
            "consensusml_link_unaccounted_ppermutes_total",
            "traced ppermutes skipped by per-edge wire accounting "
            f"(world_size > {_EDGE_ACCOUNT_MAX_WORLD})",
        ).inc()
        return
    nbytes = 1
    for d in x.shape:
        nbytes *= int(d)
    nbytes *= x.dtype.itemsize
    for dst in range(topology.world_size):
        reg.counter(
            "consensusml_link_wire_bytes_traced_total",
            "bytes traced onto each directed gossip edge (per XLA "
            "compile; programs replay, so also the per-round wire "
            "per edge)",
            labels={"src": topology.shift_src(dst, shift), "dst": dst},
        ).inc(nbytes)

__all__ = [
    "ppermute_shift",
    "mix",
    "mix_tree",
    "mix_buckets",
    "mix_masked",
    "mix_tree_masked",
    "consensus_error",
]


def ppermute_shift(x: jax.Array, topology: Topology, shift: Shift) -> jax.Array:
    """Receive the value a cyclic ``shift`` away along one mesh axis.

    ``offset=+1`` receives from the left neighbor (rank ``i-1``) — the
    direct analogue of the reference's paired NCCL send/recv with ring
    arithmetic, but compiled to one XLA collective-permute on ICI.
    """
    n = topology.mesh_shape[shift.axis]
    axis_name = topology.axis_names[shift.axis]
    perm = [(s, (s + shift.offset) % n) for s in range(n)]
    _TRACED_PPERMUTES.inc()
    _account_edge_bytes(x, topology, shift)
    with jax.named_scope("comm.ppermute"):
        return jax.lax.ppermute(x, axis_name, perm)


def ppermute_shift_tree(tree: Any, topology: Topology, shift: Shift) -> Any:
    return jax.tree.map(lambda x: ppermute_shift(x, topology, shift), tree)


def mix(x: jax.Array, topology: Topology) -> jax.Array:
    """One gossip averaging round: ``x_i <- sum_j W[i,j] x_j``.

    Dense topologies lower to a single ``pmean`` (exact consensus in one
    round); sparse topologies accumulate weighted ``ppermute`` shifts.
    Mixing is accumulated in float32 even for bf16 params so repeated
    rounds don't drift from the mixing-matrix oracle.
    """
    if topology.uses_psum:
        return jax.lax.pmean(x, topology.axis_names)
    acc = jnp.asarray(x, jnp.float32) * topology.self_weight
    for s in topology.shifts:
        acc = acc + s.weight * jnp.asarray(
            ppermute_shift(x, topology, s), jnp.float32
        )
    return acc.astype(x.dtype)


def mix_tree(tree: Any, topology: Topology) -> Any:
    return jax.tree.map(lambda x: mix(x, topology), tree)


def mix_buckets(
    bufs: list[jax.Array],
    topology: Topology,
    alive: jax.Array | None = None,
    alive_nbrs: list[jax.Array] | None = None,
) -> list[jax.Array]:
    """One gossip round over a list of flat bucket buffers (see
    :mod:`consensusml_tpu.consensus.bucketing`).

    Per buffer this is exactly :func:`mix` (or :func:`mix_masked` when
    ``alive`` is given) — bit-identical elementwise math — but every
    bucket's sends are issued BEFORE any bucket's weighted combine, so
    while bucket ``i`` is in flight on the ICI the scheduler is free to
    run bucket ``i+1``'s sends and bucket ``i-1``'s combine: the
    compute/comm pipeline DDP-style bucketing exists for. ``alive_nbrs``
    caches the per-shift ppermuted flags (exchange them once per round,
    not once per bucket).
    """
    if alive is not None:
        if not topology.uses_psum and alive_nbrs is None:
            alive_nbrs = [
                ppermute_shift(alive, topology, s) for s in topology.shifts
            ]
        return [mix_masked(b, topology, alive, alive_nbrs) for b in bufs]
    if topology.uses_psum:
        return [jax.lax.pmean(b, topology.axis_names) for b in bufs]
    with _span("comm.bucket_sends", buckets=len(bufs)):
        inflight = [
            [ppermute_shift(b, topology, s) for b in bufs]
            for s in topology.shifts
        ]
    with _span("comm.bucket_combine"):
        out = []
        for i, b in enumerate(bufs):
            acc = jnp.asarray(b, jnp.float32) * topology.self_weight
            for s, recvs in zip(topology.shifts, inflight):
                acc = acc + s.weight * jnp.asarray(recvs[i], jnp.float32)
            out.append(acc.astype(b.dtype))
        return out


def mix_masked(x: jax.Array, topology: Topology, alive: jax.Array,
               alive_nbrs: list[jax.Array] | None = None) -> jax.Array:
    """Alive-mask-aware gossip round (see consensus.faults for semantics):
    a dead neighbor's weight folds back onto self, a dead worker keeps its
    own value. ``alive`` is this worker's scalar 0/1 flag; ``alive_nbrs``
    caches the per-shift ppermuted flags so a pytree mix exchanges them
    once, not once per leaf.

    The reference's NCCL design would need send/recv timeouts and a
    communicator rebuild to survive this; here the dead peer's payload
    still rides the (static) collective but is zero-weighted out.
    """
    if topology.uses_psum:
        # dense: acc_i = S/n + x_i * (n - A)/n, S = sum_j a_j x_j, A = sum_j a_j
        n = float(topology.world_size)
        xf = jnp.asarray(x, jnp.float32)
        s = jax.lax.psum(alive * xf, topology.axis_names)
        a = jax.lax.psum(alive, topology.axis_names)
        acc = s / n + xf * (n - a) / n
        return jnp.where(alive > 0, acc, xf).astype(x.dtype)
    if alive_nbrs is None:
        alive_nbrs = [ppermute_shift(alive, topology, s) for s in topology.shifts]
    xf = jnp.asarray(x, jnp.float32)
    acc = xf * topology.self_weight
    for s, a_n in zip(topology.shifts, alive_nbrs):
        x_n = jnp.asarray(ppermute_shift(x, topology, s), jnp.float32)
        acc = acc + s.weight * (a_n * x_n + (1.0 - a_n) * xf)
    return jnp.where(alive > 0, acc, xf).astype(x.dtype)


def mix_tree_masked(tree: Any, topology: Topology, alive: jax.Array) -> Any:
    alive_nbrs = (
        None
        if topology.uses_psum
        else [ppermute_shift(alive, topology, s) for s in topology.shifts]
    )
    return jax.tree.map(
        lambda x: mix_masked(x, topology, alive, alive_nbrs), tree
    )


def consensus_error(
    tree: Any, topology: Topology, shard_axes: tuple[str, ...] = ()
) -> jax.Array:
    """RMS disagreement across workers: ``sqrt(mean_i ||theta_i - theta_bar||^2)``.

    Half of the reference's headline metric (BASELINE.json ``metric``:
    "imgs/sec/chip + consensus-error"). Computed entirely on-device with
    two ``pmean``s — no gather of full parameter sets to the host.

    ``shard_axes``: manual MODEL axes the tree is sharded over inside the
    current ``shard_map`` (e.g. ``("pp",)`` when each device holds its
    pipeline stage's layer slice) — the squared deviation is psum'd over
    them so the metric covers the whole model and stays replicated.

    REQUIREMENT: when ``shard_axes`` is non-empty, EVERY leaf of ``tree``
    must be sharded (disjointly partitioned) over those axes. A leaf
    replicated over a shard axis — e.g. an embedding living outside the
    per-stage ``stages/`` subtree — would have its squared deviation
    psum'd axis-size times, inflating the metric. Callers with mixed
    trees (the pipeline rules shard the whole param tree, so none exist
    today) must split replicated leaves out and sum the two results
    (replicated part with ``shard_axes=()``) rather than pass them here.
    """
    axes = topology.axis_names
    mean = jax.tree.map(lambda x: jax.lax.pmean(jnp.asarray(x, jnp.float32), axes), tree)
    sq = sum(
        jnp.sum((jnp.asarray(x, jnp.float32) - m) ** 2)
        for x, m in zip(jax.tree.leaves(tree), jax.tree.leaves(mean))
    )
    if shard_axes:
        sq = jax.lax.psum(sq, shard_axes)
    return jnp.sqrt(jax.lax.pmean(sq, axes))
