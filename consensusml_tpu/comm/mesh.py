"""Device-mesh construction for worker topologies.

Replaces the reference's process-group / communicator bootstrap (SURVEY.md
L1: NCCL rendezvous; file:line unavailable — mount empty). In JAX there is
no rendezvous: "N workers" is N devices in a named
:class:`jax.sharding.Mesh` whose axis names are the topology's gossip axes,
so every ``ppermute`` in the gossip step maps onto ICI neighbor links.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from consensusml_tpu.topology import Topology

__all__ = ["WorkerMesh", "local_device_mesh"]


def local_device_mesh(n: int, platform: str | None = None) -> list[jax.Device]:
    """Return ``n`` local devices, with a helpful error for CPU simulation.

    For multi-worker tests on a dev box: set
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` before the first
    jax import, then request ``platform="cpu"`` here (or force the default
    with ``jax.config.update("jax_platforms", "cpu")`` after import — the
    env var JAX_PLATFORMS can be overridden by TPU plugins that register at
    interpreter start).
    """
    devices = jax.devices(platform) if platform else jax.devices()
    if len(devices) < n:
        raise RuntimeError(
            f"need {n} devices for this topology but only {len(devices)} are "
            f"visible ({[d.platform for d in devices[:3]]}...). For CPU "
            "simulation set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n} before "
            'importing jax and pass platform="cpu" (or '
            'jax.config.update("jax_platforms", "cpu") after import), or use '
            "the simulated backend (consensusml_tpu.comm.simulated) which "
            "runs any world size on one device."
        )
    return list(devices[:n])


@dataclasses.dataclass(frozen=True)
class WorkerMesh:
    """A topology bound to a concrete device mesh.

    Global (host-view) arrays carry ``len(mesh_shape)`` leading worker axes
    — e.g. ``(W, ...)`` for a ring, ``(R, C, ...)`` for a torus — sharded
    one-slice-per-device via :meth:`worker_spec`. Inside ``shard_map`` each
    worker sees its slice with singleton leading axes.
    """

    topology: Topology
    mesh: Mesh

    @classmethod
    def create(
        cls,
        topology: Topology,
        devices: Sequence[jax.Device] | None = None,
        platform: str | None = None,
    ) -> "WorkerMesh":
        if devices is None:
            devices = local_device_mesh(topology.world_size, platform)
        if len(devices) != topology.world_size:
            raise ValueError(
                f"topology wants {topology.world_size} devices, got {len(devices)}"
            )
        dev_array = np.asarray(devices, dtype=object).reshape(topology.mesh_shape)
        return cls(topology=topology, mesh=Mesh(dev_array, topology.axis_names))

    @property
    def axis_names(self) -> tuple[str, ...]:
        return self.topology.axis_names

    def worker_spec(self) -> PartitionSpec:
        """PartitionSpec sharding the leading worker axes over the mesh."""
        return PartitionSpec(*self.axis_names)

    def replicated_spec(self) -> PartitionSpec:
        return PartitionSpec()

    def worker_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.worker_spec())

    def stacked_sharding(self) -> NamedSharding:
        """Sharding for FLAT-stacked arrays ``(W, ...)``: the single leading
        axis is split over ALL mesh axes (row-major), so a later reshape to
        ``mesh_shape`` leading axes is layout-preserving."""
        return NamedSharding(self.mesh, PartitionSpec(self.axis_names))

    def shard_stacked(self, tree):
        """device_put a flat-stacked pytree onto the mesh."""
        import jax as _jax

        return _jax.tree.map(
            lambda x: _jax.device_put(x, self.stacked_sharding()), tree
        )

    def stack_shape(self) -> tuple[int, ...]:
        """Leading axes a global stacked array must carry."""
        return self.topology.mesh_shape
