"""Device-mesh construction for worker topologies.

Replaces the reference's process-group / communicator bootstrap (SURVEY.md
L1: NCCL rendezvous; file:line unavailable — mount empty). In JAX there is
no rendezvous: "N workers" is N devices in a named
:class:`jax.sharding.Mesh` whose axis names are the topology's gossip axes,
so every ``ppermute`` in the gossip step maps onto ICI neighbor links.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from consensusml_tpu.topology import Topology

__all__ = ["WorkerMesh", "local_device_mesh", "slice_major_devices"]


def slice_major_devices(devices: Sequence[jax.Device] | None = None) -> list[jax.Device]:
    """Order devices slice-major: all of slice 0, then slice 1, ...

    For :class:`~consensusml_tpu.topology.HierarchicalTopology` this is
    the layout that makes the topology's axis 0 ("slices") cross slice
    boundaries — its 1-in-K outer-ring ppermutes ride DCN while the
    per-round inner-ring ppermutes stay on ICI. The sort is stable and
    keys ONLY on ``slice_index``, so devices without one (CPU,
    single-slice pods) keep their original order — safe to call
    unconditionally.
    """
    devices = list(jax.devices() if devices is None else devices)
    return sorted(devices, key=lambda d: getattr(d, "slice_index", 0) or 0)


def local_device_mesh(n: int, platform: str | None = None) -> list[jax.Device]:
    """Return ``n`` local devices, with a helpful error for CPU simulation.

    For multi-worker tests on a dev box: set
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` before the first
    jax import, then request ``platform="cpu"`` here (or force the default
    with ``jax.config.update("jax_platforms", "cpu")`` after import — the
    env var JAX_PLATFORMS can be overridden by TPU plugins that register at
    interpreter start).
    """
    devices = jax.devices(platform) if platform else jax.devices()
    if len(devices) < n:
        raise RuntimeError(
            f"need {n} devices for this topology but only {len(devices)} are "
            f"visible ({[d.platform for d in devices[:3]]}...). For CPU "
            "simulation set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n} before "
            'importing jax and pass platform="cpu" (or '
            'jax.config.update("jax_platforms", "cpu") after import), or use '
            "the simulated backend (consensusml_tpu.comm.simulated) which "
            "runs any world size on one device."
        )
    return list(devices[:n])


@dataclasses.dataclass(frozen=True)
class WorkerMesh:
    """A topology bound to a concrete device mesh.

    Global (host-view) arrays carry ``len(mesh_shape)`` leading worker axes
    — e.g. ``(W, ...)`` for a ring, ``(R, C, ...)`` for a torus — sharded
    one-slice-per-device via :meth:`worker_spec`. Inside ``shard_map`` each
    worker sees its slice with singleton leading axes.

    ``model_axes`` generalizes a worker from one device to a SUBMESH: the
    mesh becomes ``(*topology.mesh_shape, *model_axis_sizes)``. Gossip
    collectives stay manual over the worker axes (``shard_map``
    partial-manual mode) while the model axes remain in XLA *auto*
    sharding mode — annotate params with
    :mod:`consensusml_tpu.parallel.sharding` rules and the compiler
    inserts the intra-worker tensor-parallel collectives. This is how the
    Llama-2-7B torus config runs full-weights on a pod: 4x4 workers x
    tp-submesh each, something the reference's one-process-per-GPU design
    cannot express (SURVEY.md §2: no TP/PP evidence in the reference).
    """

    topology: Topology
    mesh: Mesh
    model_axes: tuple[tuple[str, int], ...] = ()
    manual_model_axes: tuple[str, ...] = ()

    @classmethod
    def create(
        cls,
        topology: Topology,
        devices: Sequence[jax.Device] | None = None,
        platform: str | None = None,
        model_axes: Sequence[tuple[str, int]] = (),
        manual_model_axes: Sequence[str] = (),
    ) -> "WorkerMesh":
        """``manual_model_axes`` marks model axes whose collectives the
        per-worker computation writes ITSELF (``shard_map`` manual mode)
        rather than leaving to XLA's auto sharding — pipeline parallelism
        needs this: ``pipeline_apply``'s stage-to-stage ``ppermute`` is a
        hand-written collective over the ``pp`` axis, unlike TP whose
        psums XLA derives from sharding annotations."""
        model_axes = tuple((str(n), int(s)) for n, s in model_axes)
        manual_model_axes = tuple(str(n) for n in manual_model_axes)
        if overlap := {n for n, _ in model_axes} & set(topology.axis_names):
            raise ValueError(f"model axes {sorted(overlap)} collide with worker axes")
        if missing := set(manual_model_axes) - {n for n, _ in model_axes}:
            raise ValueError(
                f"manual_model_axes {sorted(missing)} are not model axes"
            )
        per_worker = int(np.prod([s for _, s in model_axes])) if model_axes else 1
        need = topology.world_size * per_worker
        if devices is None:
            devices = local_device_mesh(need, platform)
        if len(devices) != need:
            raise ValueError(
                f"topology wants {topology.world_size} workers x {per_worker} "
                f"devices/worker = {need} devices, got {len(devices)}"
            )
        shape = (*topology.mesh_shape, *(s for _, s in model_axes))
        names = (*topology.axis_names, *(n for n, _ in model_axes))
        dev_array = np.asarray(devices, dtype=object).reshape(shape)
        return cls(
            topology=topology,
            mesh=Mesh(dev_array, names),
            model_axes=model_axes,
            manual_model_axes=manual_model_axes,
        )

    @property
    def axis_names(self) -> tuple[str, ...]:
        return self.topology.axis_names

    def worker_devices(self) -> list[jax.Device]:
        """One representative device per worker rank (row-major over the
        worker axes; the first device of each worker's model submesh) —
        the rank -> device map the link prober (obs.links) times its
        edge transfers across."""
        return list(
            np.asarray(self.mesh.devices, dtype=object).reshape(
                self.topology.world_size, -1
            )[:, 0]
        )

    def manual_axes(self) -> frozenset[str] | None:
        """Axes ``shard_map`` should be manual over: worker axes plus any
        manual model axes (e.g. ``pp``) when a model submesh exists
        (partial-manual), else None (fully manual)."""
        if not self.model_axes:
            return None
        return frozenset(self.axis_names) | frozenset(self.manual_model_axes)

    def worker_spec(self) -> PartitionSpec:
        """PartitionSpec sharding the leading worker axes over the mesh."""
        return PartitionSpec(*self.axis_names)

    def replicated_spec(self) -> PartitionSpec:
        return PartitionSpec()

    def worker_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.worker_spec())

    def stacked_sharding(self) -> NamedSharding:
        """Sharding for FLAT-stacked arrays ``(W, ...)``: the single leading
        axis is split over the WORKER mesh axes (row-major), so a later
        reshape to ``mesh_shape`` leading axes is layout-preserving.
        Trailing dims are replicated (over any model axes too) — use
        :meth:`stacked_shardings` with rules to also split model dims."""
        return NamedSharding(self.mesh, PartitionSpec(self.axis_names))

    def stacked_shardings(self, tree, rules=None):
        """Per-leaf NamedSharding tree for flat-stacked arrays: leading axis
        over the worker axes, trailing dims per the model-sharding
        ``rules`` (see :mod:`consensusml_tpu.parallel.sharding`)."""
        from consensusml_tpu.parallel import sharding as _sharding

        return _sharding.stacked_shardings(tree, self.mesh, self.axis_names, rules)

    def shard_stacked(self, tree, rules=None, shardings=None):
        """Place a flat-stacked pytree onto the mesh.

        Single-process: plain ``device_put``. Multi-controller
        (``jax.process_count() > 1``): ``device_put`` cannot target
        non-addressable devices, so each process contributes its
        addressable shards via ``make_array_from_callback`` — the input
        tree must hold the same GLOBAL host values on every process
        (true for seeded init and the keyed data loaders). Pass a
        precomputed ``shardings`` tree (from :meth:`stacked_shardings`)
        to skip recomputation on hot paths.
        """
        import jax as _jax
        import numpy as _np

        if shardings is None:
            shardings = self.stacked_shardings(tree, rules)

        def _placed(x, sharding) -> bool:
            # a leaf the device prefetcher (or a previous shard_stacked)
            # already committed with the target sharding is used AS IS —
            # re-putting it would be the "second transfer" the overlapped
            # feed exists to avoid
            return (
                isinstance(x, _jax.Array)
                and getattr(x, "sharding", None) == sharding
            )

        if _jax.process_count() == 1:
            return _jax.tree.map(
                lambda x, s: x if _placed(x, s) else _jax.device_put(x, s),
                tree,
                shardings,
            )

        def put(x, sharding):
            if _placed(x, sharding):
                return x
            if hasattr(x, "dtype") and _jax.dtypes.issubdtype(
                x.dtype, _jax.dtypes.prng_key
            ):
                # typed PRNG keys can't cross the numpy boundary: ship the
                # raw key data (extra trailing dim, replicated) and re-wrap
                impl = _jax.random.key_impl(x)
                raw = _np.asarray(_jax.device_get(_jax.random.key_data(x)))
                rsharding = NamedSharding(
                    sharding.mesh, PartitionSpec(*sharding.spec, None)
                )
                garr = _jax.make_array_from_callback(
                    raw.shape, rsharding, lambda idx: raw[idx]
                )
                return _jax.random.wrap_key_data(garr, impl=impl)
            host = _np.asarray(x)
            return _jax.make_array_from_callback(
                host.shape, sharding, lambda idx: host[idx]
            )

        return _jax.tree.map(put, tree, shardings)

    def stack_shape(self) -> tuple[int, ...]:
        """Leading axes a global stacked array must carry."""
        return self.topology.mesh_shape
