"""Communication backends for decentralized gossip on TPU.

Reference parity: ConsensusML's NCCL wrapper layer (SURVEY.md L1; reference
file:line unavailable — mount empty). The reference does explicit NCCL
``send``/``recv`` per neighbor and ``all_reduce`` for consensus rounds;
here the same operators are XLA collectives over a named device mesh:

- neighbor exchange  -> ``jax.lax.ppermute`` (rides ICI neighbor links),
- dense consensus    -> ``jax.lax.pmean``,
- the whole gossip round lives INSIDE one jitted ``shard_map`` program, so
  XLA's latency-hiding scheduler overlaps communication with compute —
  there is no host-driven message loop to port.

Two interchangeable backends share the mixing operator defined by a
:class:`~consensusml_tpu.topology.Topology`:

- :mod:`consensusml_tpu.comm.collectives` — per-worker code run under
  ``shard_map``; scales to real TPU meshes (and virtual CPU meshes).
- :mod:`consensusml_tpu.comm.simulated` — workers as a stacked leading
  axis on ONE device; mixing is an einsum with the mixing matrix. This is
  the CPU-reference backend (BASELINE.json configs[0], "4 simulated
  workers") and the numerical oracle the collective backend is tested
  against.
"""

from consensusml_tpu.comm.mesh import (  # noqa: F401
    WorkerMesh,
    local_device_mesh,
    slice_major_devices,
)
from consensusml_tpu.comm.collectives import (  # noqa: F401
    consensus_error,
    mix,
    mix_tree,
    ppermute_shift,
)
from consensusml_tpu.comm import simulated  # noqa: F401
