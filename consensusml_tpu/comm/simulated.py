"""Simulated-workers backend: gossip on ONE device via the mixing matrix.

Reference parity: ConsensusML's CPU-simulated multi-worker mode
(BASELINE.json configs[0], "4 simulated workers, dense gossip (CPU ref)";
SURVEY.md L7 — file:line unavailable, mount empty). Workers are a stacked
leading axis of every array; one gossip round is an einsum with the
topology's doubly-stochastic mixing matrix. Runs any world size on a single
device (CPU or one TPU chip), is exactly the operator the collective
backend implements with ``ppermute``, and therefore doubles as the test
oracle for it.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from consensusml_tpu.topology import Topology

__all__ = [
    "mixing_matrix",
    "phase_matrices",
    "mix_stacked",
    "mix_tree_stacked",
    "consensus_error_stacked",
    "consensus_error_masked",
]


def mixing_matrix(topology: Topology, dtype=jnp.float32) -> jax.Array:
    """The topology's mixing matrix as a device array (flat worker order)."""
    return jnp.asarray(np.asarray(topology.mixing_matrix()), dtype=dtype)


def phase_matrices(topology: Topology, dtype=jnp.float32) -> jax.Array:
    """``(period, n, n)`` stacked matrices of a time-varying topology; round
    ``t`` uses index ``t % period`` — the simulated-backend counterpart of
    the collective backend's ``lax.switch`` phase dispatch."""
    return jnp.asarray(topology.phase_matrices(), dtype=dtype)


def mix_stacked(x: jax.Array, w: jax.Array) -> jax.Array:
    """``x`` has a flat leading worker axis: ``x_i <- sum_j W[i,j] x_j``.

    Accumulates in float32 (matching the collective backend) then casts
    back to the input dtype.
    """
    n = w.shape[0]
    flat = jnp.asarray(x, jnp.float32).reshape(n, -1)
    mixed = jnp.asarray(w, jnp.float32) @ flat
    return mixed.reshape(x.shape).astype(x.dtype)


def mix_tree_stacked(tree: Any, w: jax.Array) -> Any:
    return jax.tree.map(lambda x: mix_stacked(x, w), tree)


def consensus_error_stacked(tree: Any, world_size: int) -> jax.Array:
    """Same metric as :func:`consensusml_tpu.comm.collectives.consensus_error`
    on stacked arrays: ``sqrt(mean_i ||theta_i - theta_bar||^2)``."""
    total = jnp.zeros((), jnp.float32)
    for x in jax.tree.leaves(tree):
        x = jnp.asarray(x, jnp.float32).reshape(world_size, -1)
        dev = x - jnp.mean(x, axis=0, keepdims=True)
        total = total + jnp.sum(dev**2) / world_size
    return jnp.sqrt(total)


def consensus_error_masked(tree: Any, alive: jax.Array) -> jax.Array:
    """:func:`consensus_error_stacked` over the ALIVE members only.

    Under churn the dead/dormant rows hold frozen (or freshly
    bootstrapped) replicas whose deviation says nothing about the live
    swarm's agreement; the membership harness reports this masked
    variant alongside the all-rows metric. ``alive``: ``(world,)`` of
    0/1 floats; both mean and deviation are restricted to the alive
    subset (``max(sum(alive), 1)`` guards the everyone-dead round).
    """
    from consensusml_tpu.utils.tree import masked_worker_mean

    a = jnp.asarray(alive, jnp.float32)
    n_alive = jnp.maximum(jnp.sum(a), 1.0)
    total = jnp.zeros((), jnp.float32)
    for x in jax.tree.leaves(tree):
        x = jnp.asarray(x, jnp.float32).reshape(a.shape[0], -1)
        mean = masked_worker_mean(x, a, n_alive=n_alive)
        dev = (x - mean[None, :]) * a[:, None]
        total = total + jnp.sum(dev**2) / n_alive
    return jnp.sqrt(total)
