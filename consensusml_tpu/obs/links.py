"""Per-link probes: measured latency/bandwidth for every gossip edge.

The gossip round itself runs inside one XLA program — per-edge timing
cannot be read out of it without poisoning the hot path with host syncs.
So link health is measured by a SIDEBAND probe: at telemetry cadence
(``train.py --link-probes`` + ``--telemetry-every``), the
:class:`LinkProber` times a small device-to-device transfer across each
directed edge of the active topology and feeds per-(src, dst) labeled
metrics:

- ``consensusml_link_latency_seconds{src,dst}`` — roundtrip histogram
  per edge (fine microsecond buckets — ICI one-hops live there);
- ``consensusml_link_bandwidth_bytes_per_sec{src,dst}`` — payload /
  latest latency;
- ``consensusml_link_wire_bytes_per_round{src,dst}`` — the STEADY-STATE
  gossip bytes each edge carries per round, from the engine's wire
  accounting (:func:`link_wire_bytes`);
- ``consensusml_link_probe_*`` — probe bookkeeping (rounds, total time
  spent probing — the bench overhead numerator).

The probe transfer is a ``jax.device_put`` of a device-resident buffer
from the source rank's device to the destination rank's device plus a
``block_until_ready`` fence — deliberate host syncs OUTSIDE jit, on the
telemetry path only (baselined in .cml-check-baseline). On the simulated
backend every rank maps to the same device and the probe degrades to a
timed self-copy: the numbers stop meaning "wire" but stay cheap,
deterministic in shape, and keep the report schema identical.

``ConsensusEngine`` seam: :func:`link_wire_bytes` distributes
``wire_bytes_per_round`` over the topology's directed edges using the
same shift arithmetic both backends execute, so the future topology
auto-tuner (ROADMAP item 3) can rank edges by measured latency *and*
carried bytes from one metrics family.
"""

from __future__ import annotations

import time
from typing import Any, Callable

from consensusml_tpu.obs.metrics import (
    DEFAULT_LINK_LATENCY_BUCKETS,
    MetricsRegistry,
    get_registry,
)

__all__ = ["LinkProber", "link_wire_bytes", "edge_sends_per_round"]


def edge_sends_per_round(topology) -> dict[tuple[int, int], float]:
    """Payload sends per round along each directed edge.

    Parallel shifts that land on the same edge (a ring of 2's +1/-1)
    count as SEPARATE sends — they are separate ppermutes on the wire —
    which is why this walks shifts rather than :meth:`Topology.edges`
    (whose merged weights lose multiplicity). Dense (psum) topologies
    count one send to every peer (the all-reduce's logical edge set);
    time-varying topologies average over the period.
    """
    if topology.is_time_varying:
        acc: dict[tuple[int, int], float] = {}
        for phase in topology.phases:
            for e, n in edge_sends_per_round(phase).items():
                acc[e] = acc.get(e, 0.0) + n / topology.period
        return acc
    n = topology.world_size
    if topology.uses_psum:
        return {
            (src, dst): 1.0
            for dst in range(n)
            for src in range(n)
            if src != dst
        }
    out: dict[tuple[int, int], float] = {}
    for shift in topology.shifts:
        for dst in range(n):
            src = topology.shift_src(dst, shift)
            if src != dst:
                out[(src, dst)] = out.get((src, dst), 0.0) + 1.0
    return out


def link_wire_bytes(engine, params: Any) -> dict[tuple[int, int], float]:
    """Steady-state gossip bytes per round on each directed edge.

    One send's payload (``telemetry()``'s ``wire_bytes_per_neighbor``)
    times that edge's sends per round times ``gossip_steps`` — summed
    over ONE worker's outgoing edges this reproduces its
    ``wire_bytes_per_round`` (up to push-sum's mass scalar) for ppermute
    topologies; the full edge dict covers every worker. Dense (psum) topologies spread
    the all-reduce payload over every logical peer edge, so their sum
    intentionally exceeds the one-send accounting. ``params`` may be
    shape structs."""
    t = engine.telemetry(params)
    per_send = t["wire_bytes_per_neighbor"] * max(
        engine.config.gossip_steps, 1
    )
    return {
        e: per_send * sends
        for e, sends in edge_sends_per_round(engine.topology).items()
    }


class LinkProber:
    """Times one transfer per directed topology edge and feeds the
    ``consensusml_link_*`` families.

    ``devices``: rank -> jax.Device list (the collective backend's mesh
    order). None => single-device mode (simulated backend): all ranks
    share ``jax.devices()[0]`` and probes are self-copies. On
    multi-controller runs each process keeps only the edges between its
    OWN devices (a local ``device_put`` cannot reach another host);
    cross-host edges land on ``consensusml_link_edges_remote``.
    ``transfer``: override ``(src, dst) -> None`` — the
    test/chaos hook (a transfer that sleeps makes that link measurably
    slow, which must surface in ``slowest()`` and the cluster report).
    ``max_edges``: probe at most this many edges per round (dense
    world-N is N*(N-1) edges); the overflow is counted loudly on
    ``consensusml_link_edges_skipped``, never silently dropped.
    """

    def __init__(
        self,
        topology,
        registry: MetricsRegistry | None = None,
        devices: list | None = None,
        payload_bytes: int = 1 << 16,
        transfer: Callable[[int, int], None] | None = None,
        max_edges: int = 512,
    ):
        self.topology = topology
        self.registry = registry if registry is not None else get_registry()
        self.payload_bytes = int(payload_bytes)
        self._devices = devices
        self._transfer = transfer
        self._bufs: dict[int, Any] = {}  # src rank -> staged device buffer
        edges = [(s, d) for s, d, _ in topology.edges()]
        # Multi-controller: a process can only device_put between devices
        # IT addresses, so with the default transfer each process keeps
        # only the edges whose BOTH endpoints are process-local. That
        # partitions intra-host edges exactly once across the fleet (an
        # edge's devices share one owning process); cross-host edges are
        # counted on consensusml_link_edges_remote rather than probed —
        # measuring them needs a collective-phased probe, not a sideband
        # device_put (future auto-tuner work). Injected transfers see
        # every edge: they define their own reachability.
        self.remote_edges = 0
        if transfer is None and devices is not None:
            import jax

            pid = jax.process_index()
            local = [
                e
                for e in edges
                if devices[e[0] % len(devices)].process_index == pid
                and devices[e[1] % len(devices)].process_index == pid
            ]
            self.remote_edges = len(edges) - len(local)
            edges = local
        self.skipped_edges = max(0, len(edges) - max_edges)
        self.edges = edges[: max_edges]
        self._stats: dict[tuple[int, int], tuple[int, float]] = {
            e: (0, 0.0) for e in self.edges
        }
        self._warmed = False
        r = self.registry
        r.gauge(
            "consensusml_link_edges",
            "directed gossip edges the link prober covers",
        ).set(len(self.edges))
        r.gauge(
            "consensusml_link_edges_skipped",
            "edges past the prober's max_edges cap (0 = full coverage)",
        ).set(self.skipped_edges)
        r.gauge(
            "consensusml_link_edges_remote",
            "cross-process edges this rank cannot probe with a local "
            "device_put (multi-controller; 0 on single-process runs)",
        ).set(self.remote_edges)
        r.gauge(
            "consensusml_link_probe_payload_bytes",
            "payload size of one link probe transfer",
        ).set(self.payload_bytes)
        self._m_rounds = r.counter(
            "consensusml_link_probe_rounds_total",
            "completed link-probe sweeps (one timing per edge each)",
        )
        self._m_spent = r.counter(
            "consensusml_link_probe_seconds_total",
            "wall time spent probing links (the probe's total overhead)",
        )

    # -- the default device-to-device transfer -----------------------------
    def _device(self, rank: int):
        import jax

        if self._devices is not None:
            return self._devices[rank % len(self._devices)]
        return jax.devices()[0]

    def _buf(self, rank: int):
        buf = self._bufs.get(rank)
        if buf is None:
            import jax
            import jax.numpy as jnp

            buf = jax.device_put(
                jnp.zeros((self.payload_bytes // 4,), jnp.float32),
                self._device(rank),
            )
            buf.block_until_ready()
            self._bufs[rank] = buf
        return buf

    def _default_transfer(self, src: int, dst: int) -> None:
        import jax

        # device-resident source buffer -> destination device, fenced:
        # the one-hop transfer cost the gossip wire pays per payload.
        # Host syncs by design (telemetry sideband, never inside jit).
        jax.device_put(self._buf(src), self._device(dst)).block_until_ready()

    # -- probing -----------------------------------------------------------
    def probe_round(self) -> dict[tuple[int, int], float]:
        """One timed transfer per edge; returns {edge: seconds} and
        feeds the labeled histograms/gauges."""
        transfer = self._transfer or self._default_transfer
        if not self._warmed:
            # throwaway sweep: first-touch allocation and dispatch-path
            # warmup must not pollute the recorded latencies
            for src, dst in self.edges:
                transfer(src, dst)
            self._warmed = True
        t_sweep = time.perf_counter()
        out: dict[tuple[int, int], float] = {}
        for src, dst in self.edges:
            t0 = time.perf_counter()
            transfer(src, dst)
            dt = time.perf_counter() - t0
            out[(src, dst)] = dt
            n, tot = self._stats[(src, dst)]
            self._stats[(src, dst)] = (n + 1, tot + dt)
            labels = {"src": src, "dst": dst}
            self.registry.histogram(
                "consensusml_link_latency_seconds",
                "one-hop probe transfer time per directed gossip edge",
                buckets=DEFAULT_LINK_LATENCY_BUCKETS,
                labels=labels,
            ).observe(dt)
            self.registry.gauge(
                "consensusml_link_bandwidth_bytes_per_sec",
                "probe payload / latest probe latency per edge",
                labels=labels,
            ).set(self.payload_bytes / dt if dt > 0 else 0.0)
        self._m_rounds.inc()
        self._m_spent.inc(time.perf_counter() - t_sweep)
        return out

    def slowest(self, k: int | None = None) -> list[dict[str, Any]]:
        """Edges ranked by mean probed latency, slowest first — the
        ordering the cluster report and the future topology auto-tuner
        consume."""
        rows = [
            {
                "src": s,
                "dst": d,
                "probes": n,
                "mean_latency_s": tot / n,
            }
            for (s, d), (n, tot) in self._stats.items()
            if n > 0
        ]
        rows.sort(key=lambda r: -r["mean_latency_s"])
        return rows if k is None else rows[:k]

    # -- steady-state wire rates ------------------------------------------
    def record_wire_rates(self, engine, params: Any) -> None:
        """Set the per-edge steady-state wire gauges from the engine's
        accounting (host-side, once at startup — shape structs fine)."""
        for (src, dst), nbytes in link_wire_bytes(engine, params).items():
            self.registry.gauge(
                "consensusml_link_wire_bytes_per_round",
                "steady-state gossip bytes per round on each directed "
                "edge (engine wire accounting)",
                labels={"src": src, "dst": dst},
            ).set(nbytes)
