"""Span tracer: host-side nested spans + device-visible named scopes.

One ``span("gossip.round")`` does two things at once:

- records a HOST span (begin/end wall-clock, thread, nesting depth) into a
  bounded ring buffer, exportable as Chrome trace-event JSON that Perfetto
  / ``chrome://tracing`` loads directly;
- enters a ``jax.named_scope`` with the same name, so when the span body
  is being TRACED by jit the resulting HLO ops carry the label and the
  host span lines up with the device timeline in an xprof dump
  (``train.py --profile-dir`` + ``tools/xprof_summary.py``).

Spans placed inside jitted code (the consensus engine's round functions)
therefore fire on the host only while the program is being traced —
typically round 0 — and are pure named scopes afterwards. That is the
design, not a limitation: steady-state rounds must not pay host work per
engine stage, while the compile-round trace still shows the full nesting
(``train.round`` -> ``gossip.round`` -> ``bucket.pack`` -> ...).

The ring buffer is bounded (``capacity`` spans, oldest dropped) so the
tracer can stay on for a week-long run and still hand the flight recorder
the LAST N rounds of evidence at crash time.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Iterator

import contextlib

__all__ = ["SpanTracer", "get_tracer", "span", "null_scope"]


def _named_scope(name: str):
    # lazy jax import: the tracer must stay importable (and cheap) from
    # host-only code like the native loader before jax is configured
    try:
        import jax

        return jax.named_scope(name)
    except Exception:
        return contextlib.nullcontext()


def null_scope():
    return contextlib.nullcontext()


class SpanTracer:
    """Bounded ring buffer of completed spans.

    ``enabled=False`` reduces :meth:`span` to the bare ``jax.named_scope``
    (no host recording, no ring append) — the path a run with no trace
    sink configured stays on.
    """

    def __init__(self, capacity: int = 8192, enabled: bool = True):
        self._events: deque[dict[str, Any]] = deque(maxlen=capacity)
        self._tls = threading.local()
        # RLock: appends and snapshots share it (a snapshot during an
        # append must not see a mutating deque), and the flight
        # recorder's signal-handler dump may interrupt an append on the
        # same thread — reentrancy keeps that from deadlocking
        self._lock = threading.RLock()
        self.enabled = enabled
        # perf_counter gives monotonic span math; the epoch anchor lets a
        # flight-recorder reader correlate spans with log timestamps
        self._anchor_perf = time.perf_counter()
        self._anchor_epoch = time.time()

    # -- recording ---------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[None]:
        """``with tracer.span("gossip.round", round=3): ...``"""
        if not self.enabled:
            with _named_scope(name):
                yield
            return
        depth = getattr(self._tls, "depth", 0)
        self._tls.depth = depth + 1
        t0 = time.perf_counter()
        try:
            with _named_scope(name):
                yield
        finally:
            dur = time.perf_counter() - t0
            self._tls.depth = depth
            ev = {
                "name": name,
                "ts_us": (t0 - self._anchor_perf) * 1e6,
                "dur_us": dur * 1e6,
                "tid": threading.get_ident(),
                "depth": depth,
            }
            if attrs:
                ev["args"] = {k: _jsonable(v) for k, v in attrs.items()}
            with self._lock:
                self._events.append(ev)

    def instant(self, name: str, **attrs) -> None:
        """Zero-duration marker event (watchdog beats, fault rounds)."""
        if not self.enabled:
            return
        ev = {
            "name": name,
            "ts_us": (time.perf_counter() - self._anchor_perf) * 1e6,
            "dur_us": 0.0,
            "tid": threading.get_ident(),
            "depth": getattr(self._tls, "depth", 0),
            "instant": True,
        }
        if attrs:
            ev["args"] = {k: _jsonable(v) for k, v in attrs.items()}
        with self._lock:
            self._events.append(ev)

    # -- export ------------------------------------------------------------
    def events(self) -> list[dict[str, Any]]:
        """Snapshot of the ring (oldest first)."""
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def trace_events(self) -> list[dict[str, Any]]:
        """Chrome trace-event ("X"/"i" phase) dicts for the current ring."""
        pid = os.getpid()
        out: list[dict[str, Any]] = [
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "args": {"name": "consensusml host"},
            }
        ]
        for ev in self.events():
            rec: dict[str, Any] = {
                "name": ev["name"],
                "pid": pid,
                "tid": ev["tid"] % 2**31,  # Perfetto wants small tids
                "ts": round(ev["ts_us"], 3),
            }
            if ev.get("instant"):
                rec["ph"] = "i"
                rec["s"] = "t"
            else:
                rec["ph"] = "X"
                rec["dur"] = round(ev["dur_us"], 3)
            if "args" in ev:
                rec["args"] = ev["args"]
            out.append(rec)
        return out

    def write_chrome_trace(self, path: str) -> str:
        """Dump the ring as a Perfetto-loadable trace-event JSON file."""
        doc = {
            "traceEvents": self.trace_events(),
            "displayTimeUnit": "ms",
            "metadata": {
                "anchor_epoch_s": self._anchor_epoch,
                "source": "consensusml_tpu.obs.tracer",
            },
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    try:
        return float(v)
    except Exception:
        return str(v)


_GLOBAL = SpanTracer(enabled=False)


def get_tracer() -> SpanTracer:
    """The process-wide tracer every instrumented module records into.

    Starts DISABLED (pure named scopes, no host recording) so importing
    instrumented modules costs nothing; ``train.py``/``bench.py`` enable
    it when a trace or flight-recorder sink is configured.
    """
    return _GLOBAL


def span(name: str, **attrs):
    """Module-level shorthand: ``with obs.span("bucket.pack"): ...``"""
    return _GLOBAL.span(name, **attrs)
