"""Span tracer: host-side nested spans + device-visible named scopes.

One ``span("gossip.round")`` does two things at once:

- records a HOST span (begin/end wall-clock, thread, nesting depth) into a
  bounded ring buffer, exportable as Chrome trace-event JSON that Perfetto
  / ``chrome://tracing`` loads directly;
- enters a ``jax.named_scope`` with the same name, so when the span body
  is being TRACED by jit the resulting HLO ops carry the label and the
  host span lines up with the device timeline in an xprof dump
  (``train.py --profile-dir`` + ``tools/xprof_summary.py``).

Spans placed inside jitted code (the consensus engine's round functions)
therefore fire on the host only while the program is being traced —
typically round 0 — and are pure named scopes afterwards. That is the
design, not a limitation: steady-state rounds must not pay host work per
engine stage, while the compile-round trace still shows the full nesting
(``train.round`` -> ``gossip.round`` -> ``bucket.pack`` -> ...).

The ring buffer is bounded (``capacity`` spans, oldest dropped) so the
tracer can stay on for a week-long run and still hand the flight recorder
the LAST N rounds of evidence at crash time.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Iterator

import contextlib

__all__ = ["SpanTracer", "get_tracer", "span", "null_scope"]


def _named_scope(name: str):
    # lazy jax import: the tracer must stay importable (and cheap) from
    # host-only code like the native loader before jax is configured
    try:
        import jax

        return jax.named_scope(name)
    except Exception:
        return contextlib.nullcontext()


def null_scope():
    return contextlib.nullcontext()


class SpanTracer:
    """Bounded ring buffer of completed spans.

    ``enabled=False`` reduces :meth:`span` to the bare ``jax.named_scope``
    (no host recording, no ring append) — the path a run with no trace
    sink configured stays on.
    """

    def __init__(self, capacity: int = 8192, enabled: bool = True):
        self._events: deque[dict[str, Any]] = deque(maxlen=capacity)
        self._tls = threading.local()
        # RLock: appends and snapshots share it (a snapshot during an
        # append must not see a mutating deque), and the flight
        # recorder's signal-handler dump may interrupt an append on the
        # same thread — reentrancy keeps that from deadlocking
        self._lock = threading.RLock()
        self.enabled = enabled
        # perf_counter gives monotonic span math; the epoch anchor lets a
        # flight-recorder reader correlate spans with log timestamps
        self._anchor_perf = time.perf_counter()
        self._anchor_epoch = time.time()

    # -- recording ---------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[None]:
        """``with tracer.span("gossip.round", round=3): ...``"""
        if not self.enabled:
            with _named_scope(name):
                yield
            return
        depth = getattr(self._tls, "depth", 0)
        self._tls.depth = depth + 1
        t0 = time.perf_counter()
        try:
            with _named_scope(name):
                yield
        finally:
            dur = time.perf_counter() - t0
            self._tls.depth = depth
            ev = {
                "name": name,
                "ts_us": (t0 - self._anchor_perf) * 1e6,
                "dur_us": dur * 1e6,
                "tid": threading.get_ident(),
                "depth": depth,
            }
            if attrs:
                ev["args"] = {k: _jsonable(v) for k, v in attrs.items()}
            with self._lock:
                self._events.append(ev)

    def complete(self, name: str, dur_s: float, **attrs) -> None:
        """Append an externally-timed completed span ending NOW.

        The train loop measures some phases itself (the feed stall is
        clocked inside the prefetcher's queue pop, the fence wait inside
        the round timer) — this records them as first-class spans so the
        per-round phase rows (``round.feed`` / ``round.fence``) ride the
        same ring, digest, and Chrome export as ``with``-recorded spans.
        """
        if not self.enabled:
            return
        dur = max(float(dur_s), 0.0)
        end = time.perf_counter() - self._anchor_perf
        ev = {
            "name": name,
            "ts_us": (end - dur) * 1e6,
            "dur_us": dur * 1e6,
            "tid": threading.get_ident(),
            "depth": getattr(self._tls, "depth", 0),
        }
        if attrs:
            ev["args"] = {k: _jsonable(v) for k, v in attrs.items()}
        with self._lock:
            self._events.append(ev)

    def instant(self, name: str, **attrs) -> None:
        """Zero-duration marker event (watchdog beats, fault rounds)."""
        if not self.enabled:
            return
        ev = {
            "name": name,
            "ts_us": (time.perf_counter() - self._anchor_perf) * 1e6,
            "dur_us": 0.0,
            "tid": threading.get_ident(),
            "depth": getattr(self._tls, "depth", 0),
            "instant": True,
        }
        if attrs:
            ev["args"] = {k: _jsonable(v) for k, v in attrs.items()}
        with self._lock:
            self._events.append(ev)

    # -- export ------------------------------------------------------------
    def events(self) -> list[dict[str, Any]]:
        """Snapshot of the ring (oldest first)."""
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def trace_events(self) -> list[dict[str, Any]]:
        """Chrome trace-event ("X"/"i" phase) dicts for the current ring."""
        pid = os.getpid()
        out: list[dict[str, Any]] = [
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "args": {"name": "consensusml host"},
            }
        ]
        for ev in self.events():
            rec: dict[str, Any] = {
                "name": ev["name"],
                "pid": pid,
                "tid": ev["tid"] % 2**31,  # Perfetto wants small tids
                "ts": round(ev["ts_us"], 3),
            }
            if ev.get("instant"):
                rec["ph"] = "i"
                rec["s"] = "t"
            else:
                rec["ph"] = "X"
                rec["dur"] = round(ev["dur_us"], 3)
            if "args" in ev:
                rec["args"] = ev["args"]
            out.append(rec)
        return out

    def digest(self, max_rounds: int = 64) -> dict[str, Any]:
        """Compact summary of the ring for cluster snapshots.

        Two parts (docs/observability.md "Cross-rank round timeline"):

        - ``spans`` — per-name count/total/max, the whole ring;
        - ``rounds`` — one row per round index found in span attrs
          (``round=`` is stamped by the train loop on ``train.round``
          and the synthetic ``round.feed`` / ``round.fence`` phase
          spans), last ``max_rounds`` rows. The aggregator merges these
          across ranks into the round timeline that attributes a
          straggler round to its phase.

        A few hundred bytes per rank per snapshot — cheap enough to ride
        every :class:`~consensusml_tpu.obs.cluster.ClusterWriter` write.
        """
        names: dict[str, dict[str, float]] = {}
        rounds: dict[int, dict[str, Any]] = {}
        per_round_key = {
            "train.round": "dur_us",
            "round.feed": "feed_us",
            "round.fence": "fence_us",
        }
        for ev in self.events():
            d = names.setdefault(
                ev["name"], {"count": 0, "total_us": 0.0, "max_us": 0.0}
            )
            d["count"] += 1
            d["total_us"] += ev["dur_us"]
            d["max_us"] = max(d["max_us"], ev["dur_us"])
            rnd = (ev.get("args") or {}).get("round")
            key = per_round_key.get(ev["name"])
            if key is not None and isinstance(rnd, (int, float)):
                row = rounds.setdefault(int(rnd), {"round": int(rnd)})
                row[key] = round(ev["dur_us"], 1)
        return {
            "anchor_epoch_s": self._anchor_epoch,
            "spans": {
                k: {
                    "count": int(v["count"]),
                    "total_us": round(v["total_us"], 1),
                    "max_us": round(v["max_us"], 1),
                }
                for k, v in sorted(names.items())
            },
            "rounds": [rounds[r] for r in sorted(rounds)][-max_rounds:],
        }

    def write_chrome_trace(self, path: str) -> str:
        """Dump the ring as a Perfetto-loadable trace-event JSON file."""
        doc = {
            "traceEvents": self.trace_events(),
            "displayTimeUnit": "ms",
            "metadata": {
                "anchor_epoch_s": self._anchor_epoch,
                "source": "consensusml_tpu.obs.tracer",
            },
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    try:
        return float(v)
    except Exception:
        return str(v)


_GLOBAL = SpanTracer(enabled=False)


def get_tracer() -> SpanTracer:
    """The process-wide tracer every instrumented module records into.

    Starts DISABLED (pure named scopes, no host recording) so importing
    instrumented modules costs nothing; ``train.py``/``bench.py`` enable
    it when a trace or flight-recorder sink is configured.
    """
    return _GLOBAL


def span(name: str, **attrs):
    """Module-level shorthand: ``with obs.span("bucket.pack"): ...``"""
    return _GLOBAL.span(name, **attrs)
