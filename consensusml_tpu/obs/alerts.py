"""Declarative SLO objectives and burn-rate alerting over metric history.

The observability plane's signals (metrics, history rings) say what IS;
this module says what is WRONG, with a firing/resolved lifecycle instead
of log lines. An :class:`AlertEngine` evaluates a list of
:class:`AlertRule` objects against a
:class:`~consensusml_tpu.obs.history.MetricsHistory` on every telemetry
tick. Three rule kinds (schema in docs/observability.md
"Alerting & history"):

- ``threshold`` — a scalar series above/below a bound, sustained for
  ``for_s`` seconds. With ``rate_window_s`` set the compared value is
  the counter's windowed per-second rate; against a histogram series
  the compared value is the windowed ``quantile`` (default p99) — "TTFT
  p99 above 500 ms for 30 s" is one rule.
- ``burn_rate`` — the Google-SRE multi-window error-budget burn: an
  :class:`SloSpec` (histogram family + latency threshold + objective)
  defines the error fraction; the rule fires when BOTH the fast and the
  slow window burn the budget faster than ``burn_factor``×. The fast
  window makes it respond in seconds, the slow window stops a single
  bad scrape from paging, and recovery clears it (no traffic = no
  errors, by the history plane's windowed-delta semantics).
- ``stale`` — a unix-timestamp gauge (heartbeats) older than
  ``max_age_s``: the liveness/watchdog rule shape.

Rules match every labeled child of their ``series`` family, so one rule
covers a labeled family fleet of children; alert identity is
``(rule, series key)``. Lifecycle events feed ``consensusml_alert_*``
metrics, tracer instant events, and one loud stderr line per
transition; :meth:`AlertEngine.snapshot` is what ``/alerts``, the
cluster snapshots, and the flight recorder embed.

``default_ruleset()`` is the bundled production posture: serving SLO
burn rates (TTFT, inter-token), queue/pool pressure, consensus health
(the :class:`~consensusml_tpu.obs.health.ConsensusHealthMonitor`'s
sustained-violation gauge — the monitor's episode log routes through
:meth:`AlertEngine.notify` when an engine is attached), hot-swap and
speculative-decode regressions, and heartbeat staleness for both the
train round loop and the serving engine loop. It must fire ZERO alerts
on a healthy bench run — ``bench.py``'s observability section checks
exactly that and ``tools/bench_diff.py`` gates it.
"""

from __future__ import annotations

import math
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any

from consensusml_tpu.analysis import guarded_by
from consensusml_tpu.obs.history import MetricsHistory
from consensusml_tpu.obs.metrics import MetricsRegistry, get_registry
from consensusml_tpu.obs.tracer import SpanTracer, get_tracer

__all__ = [
    "SloSpec",
    "AlertRule",
    "Alert",
    "AlertEngine",
    "SEVERITY_RANK",
    "default_ruleset",
    "get_alert_engine",
    "peek_alert_engine",
    "worst_first_key",
]

# shared with the cluster aggregator's fleet merge — one ordering
SEVERITY_RANK = {"page": 0, "warn": 1, "info": 2}


@dataclass(frozen=True)
class SloSpec:
    """A latency SLO: ``objective`` of observations in ``series`` must
    land at or under ``threshold_s``. ``error_budget`` is what burn
    rates are measured against. Put ``threshold_s`` on a bucket edge of
    the series' histogram for exact accounting."""

    series: str
    threshold_s: float
    objective: float = 0.99

    @property
    def error_budget(self) -> float:
        return max(1.0 - float(self.objective), 1e-9)


@dataclass(frozen=True)
class AlertRule:
    """One declarative rule; unused fields of other kinds are ignored."""

    name: str
    series: str
    kind: str = "threshold"  # threshold | burn_rate | stale
    severity: str = "warn"  # page | warn | info
    summary: str = ""
    # threshold rules
    op: str = "above"  # above | below
    threshold: float = 0.0
    for_s: float = 0.0
    rate_window_s: float | None = None  # compare the windowed rate
    quantile: float = 0.99  # compared when series is a histogram
    # burn-rate rules
    slo: SloSpec | None = None
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    burn_factor: float = 10.0
    # stale rules
    max_age_s: float = 120.0

    def __post_init__(self):
        if self.kind not in ("threshold", "burn_rate", "stale"):
            raise ValueError(f"unknown alert rule kind {self.kind!r}")
        if self.kind == "burn_rate" and self.slo is None:
            raise ValueError(f"burn_rate rule {self.name!r} needs an SloSpec")
        if self.op not in ("above", "below"):
            raise ValueError(f"unknown threshold op {self.op!r}")


class Alert:
    """One firing (or recently resolved) alert instance."""

    __slots__ = (
        "rule", "series", "severity", "summary", "state", "direction",
        "since_s", "fired_s", "resolved_s", "value",
    )

    def __init__(self, rule: AlertRule, series: str, since_s: float):
        self.rule = rule.name
        self.series = series
        self.severity = rule.severity
        self.summary = rule.summary
        self.state = "firing"
        # which way the value is bad — the cluster merge keeps the MIN
        # across ranks for "below" breaches, MAX otherwise
        self.direction = (
            "below"
            if rule.kind == "threshold" and rule.op == "below"
            else "above"
        )
        self.since_s = since_s  # breach start (before for_s elapsed)
        self.fired_s = math.nan
        self.resolved_s: float | None = None
        self.value = math.nan

    def to_dict(self) -> dict[str, Any]:
        return {
            "rule": self.rule,
            "series": self.series,
            "severity": self.severity,
            "summary": self.summary,
            "state": self.state,
            "direction": self.direction,
            "since_s": self.since_s,
            "fired_s": self.fired_s,
            "resolved_s": self.resolved_s,
            "value": self.value,
        }


class _RuleState:
    __slots__ = ("breach_since", "alert")

    def __init__(self):
        self.breach_since: float | None = None
        self.alert: Alert | None = None


@guarded_by("_lock", "_state", "_resolved", "_events", "_evals")
class AlertEngine:
    """Evaluates rules each tick; owns the alert lifecycle + exports."""

    def __init__(
        self,
        history: MetricsHistory,
        rules: list[AlertRule] | None = None,
        registry: MetricsRegistry | None = None,
        tracer: SpanTracer | None = None,
        *,
        resolved_keep: int = 64,
        events_keep: int = 256,
        quiet: bool = False,
    ):
        self.history = history
        self.rules: list[AlertRule] = (
            list(rules) if rules is not None else default_ruleset()
        )
        self.registry = registry if registry is not None else get_registry()
        self.tracer = tracer if tracer is not None else get_tracer()
        self.quiet = quiet
        self._lock = threading.Lock()
        self._state: dict[tuple[str, str], _RuleState] = {}
        self._resolved: deque[dict[str, Any]] = deque(maxlen=resolved_keep)
        # out-of-band plane events (health-monitor episode lines etc.)
        self._events: deque[dict[str, Any]] = deque(maxlen=events_keep)
        self._evals = 0
        r = self.registry
        self._g_firing = r.gauge(
            "consensusml_alerts_firing", "alerts currently firing"
        )
        self._g_firing.set(0.0)
        self._m_fired = r.counter(
            "consensusml_alert_fired_total", "alert fire transitions"
        )
        self._m_resolved = r.counter(
            "consensusml_alert_resolved_total", "alert resolve transitions"
        )
        self._g_last_eval = r.gauge(
            "consensusml_alert_last_eval_time_seconds",
            "unix time of the latest rule evaluation tick",
        )
        self._rule_gauges: dict[str, Any] = {}
        for rule in self.rules:
            self._rule_gauge(rule.name)

    def _rule_gauge(self, name: str):
        g = self._rule_gauges.get(name)
        if g is None:
            g = self.registry.gauge(
                "consensusml_alert_firing",
                "1 while this rule has a firing alert, else 0 (labeled "
                "per rule)",
                labels={"rule": name},
            )
            g.set(0.0)
            self._rule_gauges[name] = g
        return g

    def replace_rules(self, rules: list[AlertRule]) -> None:
        """Swap the rule set (tests, surface-specific postures); firing
        state of removed rules is dropped, their gauges zeroed."""
        with self._lock:
            self._state.clear()
        for g in self._rule_gauges.values():
            g.set(0.0)
        self.rules = list(rules)
        for rule in self.rules:
            self._rule_gauge(rule.name)

    # -- evaluation --------------------------------------------------------

    def _rule_value(
        self, rule: AlertRule, key: str, now: float
    ) -> tuple[bool, float]:
        """(breached_this_tick, compared_value) for one series key."""
        h = self.history
        if rule.kind == "burn_rate":
            slo = rule.slo
            fast = h.bad_fraction(
                key, slo.threshold_s, rule.fast_window_s, now
            ) / slo.error_budget
            slow = h.bad_fraction(
                key, slo.threshold_s, rule.slow_window_s, now
            ) / slo.error_budget
            return (
                fast > rule.burn_factor and slow > rule.burn_factor,
                fast,
            )
        if rule.kind == "stale":
            latest = h.latest_value(key)
            if latest is None or not math.isfinite(latest[1]):
                return False, math.nan
            age = now - latest[1]
            return age > rule.max_age_s, age
        # threshold
        if rule.rate_window_s is not None:
            v = h.rate(key, rule.rate_window_s, now)
        elif h.kind_of(key) == "histogram":
            v = h.quantile(key, rule.quantile, 300.0, now)
        else:
            latest = h.latest_value(key)
            v = latest[1] if latest is not None else math.nan
        if not math.isfinite(v):
            return False, v
        breach = v > rule.threshold if rule.op == "above" else v < rule.threshold
        return breach, v

    def evaluate(self, now: float | None = None) -> list[dict[str, Any]]:
        """One tick: evaluate every rule against every matching series,
        advance lifecycles, export, and return the firing alert dicts
        (worst first)."""
        now = time.time() if now is None else float(now)
        transitions: list[tuple[str, Alert]] = []
        firing_per_rule: dict[str, int] = {r.name: 0 for r in self.rules}
        # all window math runs BEFORE the engine lock: history locks
        # never nest under _lock (leaf-lock discipline — a /alerts
        # scrape or a notify() never waits on multi-rule evaluation)
        verdicts: list[tuple[AlertRule, str, bool, float]] = []
        for rule in self.rules:
            for key in self.history.keys_for(rule.series):
                breach, value = self._rule_value(rule, key, now)
                verdicts.append((rule, key, breach, value))
        with self._lock:
            self._evals += 1
            for rule, key, breach, value in verdicts:
                sk = (rule.name, key)
                st = self._state.get(sk)
                if st is None:
                    st = self._state[sk] = _RuleState()
                if breach:
                    if st.breach_since is None:
                        st.breach_since = now
                    if (
                        st.alert is None
                        and now - st.breach_since >= rule.for_s
                    ):
                        st.alert = Alert(rule, key, st.breach_since)
                        st.alert.fired_s = now
                        transitions.append(("fire", st.alert))
                    if st.alert is not None:
                        st.alert.value = value
                else:
                    st.breach_since = None
                    if st.alert is not None:
                        st.alert.state = "resolved"
                        st.alert.resolved_s = now
                        transitions.append(("resolve", st.alert))
                        self._resolved.append(st.alert.to_dict())
                        st.alert = None
                if st.alert is not None:
                    firing_per_rule[rule.name] = (
                        firing_per_rule.get(rule.name, 0) + 1
                    )
            firing = sorted(
                (
                    st.alert.to_dict()
                    for st in self._state.values()
                    if st.alert is not None
                ),
                key=worst_first_key,
            )
        # exports happen OUTSIDE the engine lock (metric locks nest under
        # nothing here; a /alerts scrape never waits on an evaluation)
        for name, n in firing_per_rule.items():
            self._rule_gauge(name).set(1.0 if n else 0.0)
        self._g_firing.set(float(len(firing)))
        self._g_last_eval.set(now)
        for kind, alert in transitions:
            if kind == "fire":
                self._m_fired.inc()
            else:
                self._m_resolved.inc()
            self.tracer.instant(
                f"alert.{kind}",
                rule=alert.rule,
                series=alert.series,
                severity=alert.severity,
                value=alert.value,
            )
            if not self.quiet:
                verb = "FIRING" if kind == "fire" else "resolved"
                print(
                    f"alert {verb} [{alert.severity}] {alert.rule} "
                    f"on {alert.series}: value {alert.value:.4g}"
                    + (f" — {alert.summary}" if alert.summary else ""),
                    file=sys.stderr,
                    flush=True,
                )
        return firing

    # -- views -------------------------------------------------------------

    def firing(self) -> list[dict[str, Any]]:
        with self._lock:
            return sorted(
                (
                    st.alert.to_dict()
                    for st in self._state.values()
                    if st.alert is not None
                ),
                key=worst_first_key,
            )

    def notify(
        self, source: str, message: str, *, severity: str = "warn", **attrs
    ) -> None:
        """Record an out-of-band plane event (e.g. the consensus-health
        monitor's episode log) in the snapshot-visible event ring, as a
        tracer instant, and as one stderr line — subsystems route their
        bespoke "loud" logs through here so every anomaly shows up in
        ``/alerts`` and the cluster report, not just a process's stderr."""
        row = {
            "time_s": time.time(),
            "source": source,
            "severity": severity,
            "message": message,
        }
        if attrs:
            # events land in JSON files (cluster snapshots, flight
            # dumps): bare NaN/Infinity tokens break strict parsers
            row["attrs"] = {
                k: (
                    None
                    if isinstance(v, float) and not math.isfinite(v)
                    else v
                )
                for k, v in attrs.items()
            }
        with self._lock:
            self._events.append(row)
        self.tracer.instant(f"alert.event.{source}", severity=severity)
        if not self.quiet:
            print(
                f"alert-plane event [{severity}] {source}: {message}",
                file=sys.stderr,
                flush=True,
            )

    def snapshot(self) -> dict[str, Any]:
        """JSON-able state for ``/alerts``, cluster snapshots and the
        flight recorder: firing worst-first, recent resolutions, recent
        plane events."""
        with self._lock:
            firing = sorted(
                (
                    st.alert.to_dict()
                    for st in self._state.values()
                    if st.alert is not None
                ),
                key=worst_first_key,
            )
            resolved = list(self._resolved)
            events = list(self._events)
            evals = self._evals
        return {
            "time_s": time.time(),
            "rules": [r.name for r in self.rules],
            "firing": firing,
            "firing_total": len(firing),
            "resolved_recent": resolved[-16:],
            "events_recent": events[-16:],
            "evals_total": evals,
        }


def worst_first_key(a: dict[str, Any]) -> tuple:
    """Worst-first sort key over alert dicts — shared by /alerts and the
    cluster aggregator's fleet merge so both order identically."""
    return (
        SEVERITY_RANK.get(a.get("severity"), 9),
        a.get("fired_s") or 0.0,
        a.get("rule") or "",
        a.get("series") or "",
    )


def default_ruleset(
    *,
    ttft_slo: SloSpec | None = None,
    intertoken_slo: SloSpec | None = None,
    burn_factor: float = 10.0,
    fast_window_s: float = 60.0,
    slow_window_s: float = 300.0,
) -> list[AlertRule]:
    """The bundled serving + consensus posture (see module docstring).

    Thresholds are deliberately loose enough that a HEALTHY run — the
    CPU bench, a steady train loop — fires nothing (bench_diff gates
    this); a real breach (sustained p99 blowout, zero free blocks,
    diverging replica, wedged loop) fires within one fast window.
    """
    ttft = ttft_slo or SloSpec(
        "consensusml_serve_ttft_seconds", threshold_s=1.0, objective=0.99
    )
    intertoken = intertoken_slo or SloSpec(
        "consensusml_serve_intertoken_seconds",
        threshold_s=0.25,
        objective=0.99,
    )
    burn = dict(
        kind="burn_rate",
        fast_window_s=fast_window_s,
        slow_window_s=slow_window_s,
        burn_factor=burn_factor,
    )
    return [
        AlertRule(
            "serve-ttft-burn-rate", ttft.series, severity="page",
            slo=ttft,
            summary=(
                f"TTFT error budget (p{100 * ttft.objective:g} <= "
                f"{ttft.threshold_s:g}s) burning >{burn_factor:g}x in both "
                "windows"
            ),
            **burn,
        ),
        AlertRule(
            "serve-intertoken-burn-rate", intertoken.series, severity="page",
            slo=intertoken,
            summary="inter-token latency error budget burning in both windows",
            **burn,
        ),
        AlertRule(
            "serve-queue-backlog", "consensusml_serve_queue_depth",
            severity="warn", op="above", threshold=128.0, for_s=5.0,
            summary="admission queue sustained above 128 waiting requests",
        ),
        AlertRule(
            "pool-block-exhaustion", "consensusml_pool_blocks_free",
            severity="warn", op="below", threshold=0.5, for_s=2.0,
            summary="paged KV pool out of free blocks (evictions imminent)",
        ),
        AlertRule(
            "consensus-health-violation",
            "consensusml_health_bound_violation",
            severity="page", op="above", threshold=0.5,
            summary=(
                "sustained consensus-decay violation episode "
                "(ConsensusHealthMonitor; a replica is diverging or a "
                "link is biasing the mean)"
            ),
        ),
        AlertRule(
            "swap-rejections", "consensusml_serve_swap_rejected_total",
            severity="warn", op="above", threshold=0.0,
            rate_window_s=slow_window_s,
            summary="hot-swap metas being rejected (generation regression "
                    "or params-tree mismatch)",
        ),
        AlertRule(
            "spec-acceptance-collapse", "consensusml_spec_acceptance_rate",
            severity="warn", op="below", threshold=0.2, for_s=30.0,
            summary="speculative acceptance rate collapsed — draft is "
                    "burning verify work",
        ),
        AlertRule(
            "watchdog-timeouts", "consensusml_watchdog_timeouts_total",
            severity="page", op="above", threshold=0.0,
            rate_window_s=slow_window_s,
            summary="round-progress watchdog fired (wedged collective)",
        ),
        AlertRule(
            "train-heartbeat-stale", "consensusml_heartbeat_time_seconds",
            kind="stale", severity="page", max_age_s=180.0,
            summary="train round loop heartbeat stale",
        ),
        AlertRule(
            "serve-loop-stale", "consensusml_serve_loop_heartbeat_seconds",
            kind="stale", severity="page", max_age_s=30.0,
            summary="serving engine loop heartbeat stale (engine thread "
                    "wedged or dead)",
        ),
    ]


_GLOBAL: AlertEngine | None = None
_GLOBAL_LOCK = threading.Lock()


def get_alert_engine() -> AlertEngine:
    """The process-wide engine (default ruleset over the global history
    and registry), created on first use by whichever surface arms it."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            from consensusml_tpu.obs.history import get_history

            _GLOBAL = AlertEngine(get_history())
        return _GLOBAL


def peek_alert_engine() -> AlertEngine | None:
    """The global engine if armed, else None (dump-path fallback)."""
    with _GLOBAL_LOCK:
        return _GLOBAL
