"""Request-scoped tracing: follow ONE request through the serving path.

The SLO histograms say *that* TTFT p99 spiked; nothing before this module
said *which request* and *where its time went*. A :class:`TraceContext`
(``trace_id`` minted by the client, ``request_id`` unique per attempt) is
carried from ``tools/loadgen.py`` through the line-JSON protocol,
``ServeServer`` and ``Engine.submit``, and every stage the request
crosses appends one event to its :class:`RequestTrace`:

``submit`` → (``admission.defer`` per budget/blocks-deferred tick) →
``admission`` → ``prefill`` → ``decode`` (first tick; later ticks are
counted, not stored) → ``complete``, plus ``preempt`` on pool-exhaustion
recompute-eviction and ``hotswap`` when a generation flip lands under a
resident stream. The propagation rule (docs/observability.md "Request
tracing"): a client MAY send ``trace_id``/``request_id``; anything
missing is minted server-side, so every request is traceable even from
trace-unaware clients, and client + server observations of one request
join on ``trace_id``.

The registry is BOUNDED both ways — at most ``max_active`` in-flight
traces (oldest force-completed as ``truncated``) and a ``capacity`` ring
of completed ones — so a serving process that lives for weeks holds the
*recent* story only, exactly like the span ring. It exports:

- :meth:`RequestTraceRegistry.snapshot` — JSON-able dict (cluster
  snapshots, ``tools/obs_report.py`` joins, the flight recorder's crash
  dump — in-flight streams included, which is the post-mortem payload);
- :meth:`RequestTraceRegistry.trace_events` — Chrome trace events (one
  ``X`` span per request, one ``i`` instant per stage event) that
  :func:`merged_chrome_trace` interleaves with the ``SpanTracer`` ring,
  so Perfetto shows decode steps and the requests riding them together.

Event appends are one lock + one dict append — cheap enough for every
admission; the per-decode-tick path is an integer increment.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Any

from consensusml_tpu.analysis import guarded_by

__all__ = [
    "TraceContext",
    "RequestTrace",
    "RequestTraceRegistry",
    "get_request_registry",
    "merged_chrome_trace",
    "write_merged_chrome_trace",
]

_MINT = itertools.count()

# admission.defer event rows stored per trace; defers past this are
# counted on defer_ticks only (a head-of-line request on an exhausted
# pool is deferred once per engine tick — the trace must stay bounded)
DEFER_EVENTS_KEPT = 16


class TraceContext:
    """The (trace_id, request_id) pair a request carries end to end.

    ``trace_id`` identifies the request across PROCESSES (client and
    server observations join on it); ``request_id`` identifies one
    serving attempt and is what SLO exemplars record. Both are short
    opaque strings; :meth:`mint` makes collision-safe ones, loadgen
    mints deterministic ones so fixtures replay byte-identically.
    """

    __slots__ = ("trace_id", "request_id")

    def __init__(self, trace_id: str, request_id: str | None = None):
        self.trace_id = str(trace_id)
        self.request_id = (
            str(request_id) if request_id else f"{self.trace_id}/0"
        )

    @classmethod
    def mint(cls, prefix: str = "srv") -> "TraceContext":
        tid = f"{prefix}-{uuid.uuid4().hex[:12]}-{next(_MINT):04d}"
        return cls(tid, tid + "/0")

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"TraceContext({self.trace_id!r}, {self.request_id!r})"


class RequestTrace:
    """One request's event list + rolled-up counters (registry-locked:
    the registry's lock guards every mutation, so a trace never needs
    its own)."""

    __slots__ = (
        "trace_id", "request_id", "prompt_len", "t_start_us",
        "events", "decode_ticks", "defer_ticks", "preemptions",
        "generation", "finish_reason", "t_end_us",
        "spec_proposed", "spec_accepted", "tenant",
    )

    def __init__(self, ctx: TraceContext, prompt_len: int, ts_us: float):
        self.trace_id = ctx.trace_id
        self.request_id = ctx.request_id
        self.prompt_len = int(prompt_len)
        self.t_start_us = ts_us
        self.events: list[dict[str, Any]] = []
        self.decode_ticks = 0
        self.defer_ticks = 0
        self.preemptions = 0
        self.generation = 0
        # speculative-decode accounting (serve/pool/spec.py): per-stream
        # draft tokens offered vs survivors — a stream with a bad
        # acceptance rate shows up in the slowest-request table with its
        # rejected drafts attached, not as unexplained decode ticks
        self.spec_proposed = 0
        self.spec_accepted = 0
        # per-workload attribution label (obs/events.py; the engine's
        # submit() resolves it, "default" for unlabeled clients)
        self.tenant = "default"
        self.finish_reason: str | None = None
        self.t_end_us: float | None = None

    def to_dict(self) -> dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "request_id": self.request_id,
            "prompt_len": self.prompt_len,
            "t_start_us": round(self.t_start_us, 3),
            "t_end_us": (
                None if self.t_end_us is None else round(self.t_end_us, 3)
            ),
            "finish_reason": self.finish_reason,
            "decode_ticks": self.decode_ticks,
            "defer_ticks": self.defer_ticks,
            "preemptions": self.preemptions,
            "generation": self.generation,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "tenant": self.tenant,
            # rounding happens at export, never on the hot append path
            "events": [
                dict(e, ts_us=round(e["ts_us"], 3)) for e in self.events
            ],
        }


@guarded_by("_lock", "_active", "_done", "_by_id")
class RequestTraceRegistry:
    """Bounded per-request trace store (engine thread writes, scrapers
    and the flight recorder read concurrently).

    ``capacity`` bounds the completed ring; ``max_active`` bounds the
    in-flight table — a client that opens streams and never finishes
    them (or an engine crash mid-flight) cannot grow the registry
    without bound. The anchor pair mirrors :class:`SpanTracer` so
    request events and host spans share one Chrome-trace clock.
    """

    def __init__(self, capacity: int = 1024, max_active: int = 4096):
        self._active: "OrderedDict[str, RequestTrace]" = OrderedDict()
        self._done: deque[RequestTrace] = deque(maxlen=capacity)
        # request_id -> trace, completed included while the ring holds it
        self._by_id: dict[str, RequestTrace] = {}
        # RLock: the flight recorder's signal-handler dump may land
        # inside an append on the same thread (same reason as the
        # metrics registry's locks)
        self._lock = threading.RLock()
        self.max_active = max_active
        self._anchor_perf = time.perf_counter()
        self._anchor_epoch = time.time()

    def _now_us(self) -> float:
        return (time.perf_counter() - self._anchor_perf) * 1e6

    # -- engine-side recording --------------------------------------------
    def start(self, ctx: TraceContext, prompt_len: int, **attrs) -> RequestTrace:
        """Open a trace and record its ``submit`` event. A ``tenant``
        attr additionally lands as the trace's attribution label (it
        still rides the submit event like any other attr)."""
        ts = self._now_us()
        tr = RequestTrace(ctx, prompt_len, ts)
        if "tenant" in attrs:
            tr.tenant = str(attrs["tenant"])
        ev: dict[str, Any] = {"name": "submit", "ts_us": ts}
        if attrs:
            ev.update(attrs)
        tr.events.append(ev)
        with self._lock:
            old = self._by_id.pop(ctx.request_id, None)
            if old is not None and old.finish_reason is None:
                self._finish_locked(old, "superseded")
            self._active[ctx.request_id] = tr
            self._by_id[ctx.request_id] = tr
            while len(self._active) > self.max_active:
                _rid, stale = self._active.popitem(last=False)
                self._finish_locked(stale, "truncated", evict_only=True)
        return tr

    def event(self, request_id: str | None, name: str, **attrs) -> None:
        """Append one stage event to an in-flight trace (no-op for
        unknown/finished ids, so instrumentation never raises)."""
        if not request_id:
            return
        ts = self._now_us()
        with self._lock:
            tr = self._active.get(request_id)
            if tr is None:
                return
            if name == "admission.defer":
                # a request can be deferred once per engine tick for
                # minutes on an exhausted pool — store the first few
                # rows, COUNT the rest (same stored-vs-counted split as
                # decode ticks; defer_ticks carries the true total)
                tr.defer_ticks += 1
                if tr.defer_ticks > DEFER_EVENTS_KEPT:
                    return
            ev: dict[str, Any] = {"name": name, "ts_us": ts}
            if attrs:
                ev.update(attrs)
            tr.events.append(ev)
            if name == "preempt":
                tr.preemptions += 1
            elif name == "hotswap":
                tr.generation = int(attrs.get("generation", tr.generation))

    def decode_tick(self, request_id: str | None) -> None:
        """Per-decode-tick accounting: the FIRST tick lands a ``decode``
        event, later ticks are one integer increment — a 4096-token
        stream must not store 4096 rows."""
        if not request_id:
            return
        self.decode_ticks((request_id,))

    def decode_ticks(self, request_ids) -> None:
        """Batch form for the engine's step loop: ONE lock round-trip
        covers every resident slot's tick, which is what keeps the
        per-step tracing cost in the microseconds (bench
        ``request_tracing_overhead_pct``)."""
        ts = self._now_us()
        with self._lock:
            for rid in request_ids:
                tr = self._active.get(rid) if rid else None
                if tr is None:
                    continue
                tr.decode_ticks += 1
                if tr.decode_ticks == 1:
                    tr.events.append({"name": "decode", "ts_us": ts})

    def spec_ticks(self, rows) -> None:
        """Speculative-round accounting, batch form like
        :meth:`decode_ticks`: ``rows`` is an iterable of ``(request_id,
        proposed, accepted)`` triples — ONE lock round-trip per verify
        round covers every resident lane."""
        with self._lock:
            for rid, proposed, accepted in rows:
                tr = self._active.get(rid) if rid else None
                if tr is None:
                    continue
                tr.spec_proposed += int(proposed)
                tr.spec_accepted += int(accepted)

    def finish(self, request_id: str | None, reason: str, **attrs) -> None:
        if not request_id:
            return
        ts = self._now_us()
        with self._lock:
            tr = self._active.get(request_id)
            if tr is None:
                return
            # terminal summary rides the complete event (ttft, tokens)
            tr.events.append({"name": "complete", "ts_us": ts, **attrs})
            self._finish_locked(tr, reason)

    def _finish_locked(
        self, tr: RequestTrace, reason: str, evict_only: bool = False
    ) -> None:
        # callers already hold _lock; the RLock makes this re-entry free
        # and keeps the lock-discipline lint's per-method proof local
        with self._lock:
            tr.finish_reason = reason
            tr.t_end_us = self._now_us()
            if not evict_only:
                self._active.pop(tr.request_id, None)
            self._done.append(tr)
            # _by_id keeps completed traces resolvable while the ring
            # holds them; prune ids the ring has dropped
            if len(self._by_id) > len(self._active) + self._done.maxlen:
                live = {t.request_id for t in self._done}
                live.update(self._active)
                self._by_id = {
                    rid: t for rid, t in self._by_id.items() if rid in live
                }

    # -- read side ---------------------------------------------------------
    def get(self, request_id: str) -> RequestTrace | None:
        with self._lock:
            return self._by_id.get(request_id)

    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    def completed(self) -> list[RequestTrace]:
        with self._lock:
            return list(self._done)

    def snapshot(self) -> dict[str, Any]:
        """JSON-able dump: completed ring + IN-FLIGHT traces (the part a
        crash dump must not lose) + the epoch anchor for log joins."""
        with self._lock:
            return {
                "anchor_epoch_s": self._anchor_epoch,
                "active": [t.to_dict() for t in self._active.values()],
                "completed": [t.to_dict() for t in self._done],
            }

    def clear(self) -> None:
        with self._lock:
            self._active.clear()
            self._done.clear()
            self._by_id.clear()

    # -- Chrome trace export ----------------------------------------------
    def trace_events(self) -> list[dict[str, Any]]:
        """One ``X`` span per request (submit → complete/now) plus one
        ``i`` instant per stage event, lane-per-request so Perfetto
        stacks concurrent streams."""
        pid = os.getpid()
        out: list[dict[str, Any]] = []
        with self._lock:
            traces = list(self._active.values()) + list(self._done)
            now_us = self._now_us()
        for tr in traces:
            tid = 1 + (hash(tr.request_id) % 2**20)
            end = tr.t_end_us if tr.t_end_us is not None else now_us
            out.append(
                {
                    "ph": "X",
                    "name": "request",
                    "cat": "request",
                    "pid": pid,
                    "tid": tid,
                    "ts": round(tr.t_start_us, 3),
                    "dur": round(max(end - tr.t_start_us, 0.0), 3),
                    "args": {
                        "trace_id": tr.trace_id,
                        "request_id": tr.request_id,
                        "prompt_len": tr.prompt_len,
                        "decode_ticks": tr.decode_ticks,
                        "defer_ticks": tr.defer_ticks,
                        "preemptions": tr.preemptions,
                        "finish_reason": tr.finish_reason,
                    },
                }
            )
            for ev in tr.events:
                rec = {
                    "ph": "i",
                    "s": "t",
                    "name": f"req.{ev['name']}",
                    "cat": "request",
                    "pid": pid,
                    "tid": tid,
                    "ts": ev["ts_us"],
                }
                extra = {
                    k: v for k, v in ev.items() if k not in ("name", "ts_us")
                }
                rec["args"] = {"request_id": tr.request_id, **extra}
                out.append(rec)
        return out


def merged_chrome_trace(
    tracer, registry: RequestTraceRegistry | None = None
) -> dict[str, Any]:
    """One Perfetto-loadable document: the span ring's events (decode
    steps, prefill spans) interleaved with the request lanes — the view
    that shows WHICH streams were riding the step that spiked."""
    reg = registry if registry is not None else get_request_registry()
    req_events = reg.trace_events()
    # the two rings were anchored at (slightly) different instants —
    # shift request timestamps onto the tracer's clock so the lanes line
    # up in Perfetto instead of drifting by the import-order gap
    shift_us = (reg._anchor_perf - tracer._anchor_perf) * 1e6
    for ev in req_events:
        ev["ts"] = round(ev["ts"] + shift_us, 3)
    return {
        "traceEvents": tracer.trace_events() + req_events,
        "displayTimeUnit": "ms",
        "metadata": {"source": "consensusml_tpu.obs.requests"},
    }


def write_merged_chrome_trace(
    path: str, tracer, registry: RequestTraceRegistry | None = None
) -> str:
    doc = merged_chrome_trace(tracer, registry)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


_GLOBAL = RequestTraceRegistry()


def get_request_registry() -> RequestTraceRegistry:
    """The process-wide request-trace registry the serving path feeds
    (engine, server, loadgen) and every exporter reads (cluster
    snapshots, /metrics sibling endpoints, the flight recorder)."""
    return _GLOBAL
