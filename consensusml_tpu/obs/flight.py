"""Crash flight recorder: dump the last N rounds of evidence on failure.

A hung or dying run's most valuable debugging artifact is what it was
doing JUST BEFORE: the last rounds' spans (which stage the round was in)
and the last metric snapshots (was wire traffic normal? was the loss
finite? was a peer already flagged dead?). The tracer's ring buffer and
the registry's snapshot ring hold exactly that, bounded; the
:class:`FlightRecorder` serializes both to a timestamped JSON file when
one of three triggers fires:

- **watchdog timeout** — ``utils.watchdog.ProgressWatchdog(on_timeout=
  recorder.dump)``: the dump lands before the hard ``os._exit``, so a
  wedged collective still leaves evidence (the exact scenario the
  watchdog exists for);
- **unhandled exception** — a chained ``sys.excepthook``;
- **unhandled THREAD exception** — a chained ``threading.excepthook``:
  the serve-engine loop re-raises device errors on its own thread, so a
  serving crash dumps too, and the dump's ``request_traces`` section
  (the request-trace registry snapshot, in-flight streams included) is
  what makes the dying engine's open requests visible post-mortem;
- **SIGTERM** — a chained signal handler (the launcher's preemption
  path), which re-raises the previous disposition so the process still
  terminates.

``install()`` is idempotent per recorder and restores nothing: the hooks
live for the process, like the crash handlers they are.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import traceback
from typing import Any

from consensusml_tpu.obs.metrics import MetricsRegistry, get_registry
from consensusml_tpu.obs.requests import (
    RequestTraceRegistry,
    get_request_registry,
)
from consensusml_tpu.obs.tracer import SpanTracer, get_tracer

__all__ = ["FlightRecorder"]


class FlightRecorder:
    def __init__(
        self,
        out_dir: str,
        tracer: SpanTracer | None = None,
        registry: MetricsRegistry | None = None,
        requests: RequestTraceRegistry | None = None,
        history=None,
        alerts=None,
        events=None,
    ):
        self.out_dir = out_dir
        self.tracer = tracer if tracer is not None else get_tracer()
        self.registry = registry if registry is not None else get_registry()
        self.requests = (
            requests if requests is not None else get_request_registry()
        )
        # history rings / alert engine: explicit wiring wins; a recorder
        # over the GLOBAL registry additionally PEEKS at the process
        # singletons at dump time so an armed alert plane always lands
        # in the post-mortem (never creating one as a side effect) — a
        # custom-registry recorder must not embed the global plane's
        # digests next to a different registry's metrics
        self.history = history
        self.alerts = alerts
        # wide-event log (obs.events): same explicit-or-peek rule — a
        # serving crash's dump carries the last-N terminal wide events
        # and the per-tenant rollup, never creating a log as a dump
        # side effect
        self.events = events
        self._peek_global = registry is None
        self._installed = False
        self._prev_excepthook = None
        self._prev_thread_hook = None
        self._prev_sigterm = None
        self.last_dump_path: str | None = None

    # -- the dump ----------------------------------------------------------
    def dump(self, reason: str, detail: str | None = None) -> str | None:
        """Write ``flightrec-<utc>-<reason>.json``; returns the path.

        Never raises: a failing dump must not mask the original crash.
        """
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
            slug = "".join(c if c.isalnum() else "-" for c in reason)[:40]
            path = os.path.join(
                self.out_dir, f"flightrec-{stamp}-{slug}.json"
            )
            doc: dict[str, Any] = {
                "reason": reason,
                "detail": detail,
                "time_s": time.time(),
                "pid": os.getpid(),
                "argv": sys.argv,
                "spans": self.tracer.events(),
                "trace_events": self.tracer.trace_events(),
                "metric_snapshots": self.registry.snapshots(),
                "metrics_final": self.registry.snapshot(
                    {"flight_recorder_reason": reason}
                ),
                # in-flight + recent request traces: the serve-side
                # post-mortem payload (which streams were open, how far
                # each had gotten) — see docs/observability.md
                "request_traces": self.requests.snapshot(),
            }
            alerts = self.alerts
            hist = self.history
            events = self.events
            if self._peek_global:
                from consensusml_tpu.obs.alerts import peek_alert_engine
                from consensusml_tpu.obs.events import peek_wide_event_log
                from consensusml_tpu.obs.history import peek_history

                alerts = alerts or peek_alert_engine()
                hist = hist or peek_history()
                events = events or peek_wide_event_log()
            if alerts is not None:
                # what was already WRONG when the process died
                doc["alerts"] = alerts.snapshot()
            if hist is not None:
                # the last-N trend of every series — whether the breach
                # was a cliff or a slow burn
                doc["history"] = hist.digest()
            if events is not None:
                # who was consuming what when the process died: the
                # last-N terminal wide events + per-tenant rollup
                doc["wide_events"] = events.snapshot()
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
            self.last_dump_path = path
            print(f"flight recorder: dumped {path} ({reason})",
                  file=sys.stderr, flush=True)
            return path
        except Exception as e:  # pragma: no cover - last-resort guard
            try:
                print(f"flight recorder: dump failed: {e}",
                      file=sys.stderr, flush=True)
            except Exception:
                pass
            return None

    # -- triggers ----------------------------------------------------------
    def install(self, sigterm: bool = True) -> "FlightRecorder":
        """Chain into sys.excepthook (always) and SIGTERM (when asked and
        possible — signal handlers only install from the main thread)."""
        if self._installed:
            return self
        self._installed = True

        self._prev_excepthook = sys.excepthook

        def _hook(exc_type, exc, tb):
            if not issubclass(exc_type, (KeyboardInterrupt, SystemExit)):
                self.dump(
                    "unhandled-exception",
                    detail="".join(
                        traceback.format_exception(exc_type, exc, tb)
                    )[-4000:],
                )
            (self._prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

        sys.excepthook = _hook

        # sys.excepthook never sees worker-thread deaths; the serving
        # engine re-raises on its own thread ON PURPOSE (loud death over
        # silent hang), so a serving crash must trigger through here
        self._prev_thread_hook = threading.excepthook

        def _thread_hook(args):
            if args.exc_type is not SystemExit:
                name = getattr(args.thread, "name", "?")
                self.dump(
                    f"thread-exception-{name}",
                    detail="".join(
                        traceback.format_exception(
                            args.exc_type, args.exc_value, args.exc_traceback
                        )
                    )[-4000:],
                )
            prev = self._prev_thread_hook or threading.__excepthook__
            prev(args)

        threading.excepthook = _thread_hook

        if sigterm:
            try:
                self._prev_sigterm = signal.getsignal(signal.SIGTERM)

                def _on_term(signum, frame):
                    self.dump("sigterm")
                    prev = self._prev_sigterm
                    if callable(prev):
                        prev(signum, frame)
                    elif prev != signal.SIG_IGN:
                        # default disposition: re-raise for a clean kill
                        signal.signal(signal.SIGTERM, signal.SIG_DFL)
                        os.kill(os.getpid(), signal.SIGTERM)

                signal.signal(signal.SIGTERM, _on_term)
            except ValueError:
                # not the main thread — excepthook/watchdog paths still work
                pass
        return self
