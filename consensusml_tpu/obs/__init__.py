"""Unified telemetry for the gossip stack: spans, metrics, flight data.

Three pillars (see docs/observability.md for the full schema):

- :mod:`~consensusml_tpu.obs.tracer` — host-side nested spans recorded
  into a bounded ring buffer and exportable as Chrome trace-event JSON
  (Perfetto-loadable); every span also enters a ``jax.named_scope`` so
  host spans line up with xprof device timelines.
- :mod:`~consensusml_tpu.obs.metrics` — counters / gauges / fixed-bucket
  histograms with a Prometheus textfile exporter and a JSONL sink.
- :mod:`~consensusml_tpu.obs.flight` — a crash flight recorder that dumps
  the span ring + last-K metric snapshots to a timestamped JSON file on
  watchdog timeout, unhandled exception, or SIGTERM.

Hot paths feed the process-wide singletons (``get_tracer()`` /
``get_registry()``); ``train.py`` surfaces the sinks via
``--trace-events`` / ``--metrics-prom`` / ``--flight-recorder`` /
``--telemetry-every``. With no sink configured the tracer stays disabled
(spans reduce to bare named scopes) and metric updates are dict-cheap, so
the instrumentation can stay on everywhere.
"""

from consensusml_tpu.obs.flight import FlightRecorder  # noqa: F401
from consensusml_tpu.obs.metrics import (  # noqa: F401
    Counter,
    DEFAULT_LATENCY_BUCKETS,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from consensusml_tpu.obs.tracer import (  # noqa: F401
    SpanTracer,
    get_tracer,
    span,
)
