"""Unified telemetry for the gossip stack: spans, metrics, flight data.

Three pillars (see docs/observability.md for the full schema):

- :mod:`~consensusml_tpu.obs.tracer` — host-side nested spans recorded
  into a bounded ring buffer and exportable as Chrome trace-event JSON
  (Perfetto-loadable); every span also enters a ``jax.named_scope`` so
  host spans line up with xprof device timelines.
- :mod:`~consensusml_tpu.obs.metrics` — counters / gauges / fixed-bucket
  histograms with a Prometheus textfile exporter and a JSONL sink.
- :mod:`~consensusml_tpu.obs.flight` — a crash flight recorder that dumps
  the span ring + last-K metric snapshots to a timestamped JSON file on
  watchdog timeout, unhandled exception, or SIGTERM.

The REQUEST plane makes serving explain itself per request
(docs/observability.md "Request tracing"):

- :mod:`~consensusml_tpu.obs.requests` — ``TraceContext`` propagation +
  a bounded per-request ``RequestTrace`` registry (submit → admission →
  prefill → decode → completion, with deferral/preemption/hot-swap
  events), merged into the Chrome trace and the flight-recorder dump;
- exemplar-bearing SLO histograms (``Histogram.observe(v, exemplar=)``)
  so a p99 bucket resolves to concrete request ids;
- :mod:`~consensusml_tpu.obs.httpd` — a stdlib ``ThreadingHTTPServer``
  serving ``/metrics`` (live Prometheus text), ``/traces`` and
  ``/requests`` (``train.py --metrics-port``,
  ``ServeServer(metrics_port=...)``).

The WIDE-EVENT plane joins them per request (docs/observability.md
"Wide events & tenant accounting"):

- :mod:`~consensusml_tpu.obs.events` — ONE structured record per
  terminal serving request (trace timings + token counts + pool
  block-seconds + ledger-joined FLOPs/HBM bytes + tenant label) in a
  bounded :class:`WideEventLog` ring with an optional JSONL sink;
  per-tenant :meth:`~WideEventLog.rollup` aggregates, labeled
  ``consensusml_tenant_*`` families (per-tenant burn-rate SLOs ride
  the alert plane's labeled-children matching), and ``GET /events`` /
  ``/tenants`` on the live HTTP plane.

The COST plane attributes time and memory (docs/observability.md "Cost
attribution", docs/memory.md "Reconciliation"):

- :mod:`~consensusml_tpu.obs.costs` — per-executable compiled
  cost/memory ledger (``lower().compile()`` FLOPs / bytes-accessed /
  buffer sizes / compile wall time in ``consensusml_cost_*`` /
  ``consensusml_compile_*`` families) with roofline expected-vs-measured
  attribution;
- :mod:`~consensusml_tpu.obs.memviz` — live HBM accounting
  (``jax.live_arrays()`` + runtime memory stats) and the three-way
  analytic / compiled / live reconciliation (``consensusml_hbm_*``);
- ``GET /profile?ms=N`` on the live HTTP plane — an on-demand
  ``jax.profiler`` capture of a RUNNING train loop or serving engine
  (single-flight, bounded dir rotation).

The ALERTING plane turns retained signals into fire/clear objectives
(docs/observability.md "Alerting & history"):

- :mod:`~consensusml_tpu.obs.history` — bounded in-process time-series
  rings over the registry (``rate``/``increase``/windowed percentiles
  from histogram deltas/last-N dumps, ``consensusml_history_*``
  accounting);
- :mod:`~consensusml_tpu.obs.alerts` — declarative ``SloSpec`` /
  ``AlertRule`` engine (thresholds, Google-SRE multi-window burn rates,
  heartbeat staleness) with firing/resolved lifecycle,
  ``consensusml_alert_*`` families, and a bundled default ruleset;
- ``GET /alerts`` / ``/query`` / ``/healthz`` on the live HTTP plane;
  alert state + history digests ride cluster snapshots and
  flight-recorder dumps.

The CLUSTER plane builds on them (docs/observability.md "Cluster view"):

- :mod:`~consensusml_tpu.obs.links` — per-link probes feeding
  ``consensusml_link_*`` latency/bandwidth/wire families per
  (src, dst) edge (``train.py --link-probes``);
- :mod:`~consensusml_tpu.obs.health` — online measured-vs-spectral-bound
  consensus decay with sustained-anomaly detection
  (``consensusml_health_*``);
- :mod:`~consensusml_tpu.obs.cluster` — per-rank snapshot writer +
  cross-rank aggregator (``--obs-cluster-dir`` + ``tools/obs_report.py``).

Hot paths feed the process-wide singletons (``get_tracer()`` /
``get_registry()``); ``train.py`` surfaces the sinks via
``--trace-events`` / ``--metrics-prom`` / ``--flight-recorder`` /
``--telemetry-every``. With no sink configured the tracer stays disabled
(spans reduce to bare named scopes) and metric updates are dict-cheap, so
the instrumentation can stay on everywhere.
"""

from consensusml_tpu.obs.cluster import (  # noqa: F401
    ClusterWriter,
    aggregate,
    read_snapshots,
)
from consensusml_tpu.obs.costs import (  # noqa: F401
    CostLedger,
    ExecutableCost,
    get_cost_ledger,
)
from consensusml_tpu.obs.alerts import (  # noqa: F401
    Alert,
    AlertEngine,
    AlertRule,
    SloSpec,
    default_ruleset,
    get_alert_engine,
    peek_alert_engine,
)
from consensusml_tpu.obs.events import (  # noqa: F401
    WideEventLog,
    get_wide_event_log,
    peek_wide_event_log,
    sanitize_tenant,
)
from consensusml_tpu.obs.flight import FlightRecorder  # noqa: F401
from consensusml_tpu.obs.history import (  # noqa: F401
    MetricsHistory,
    get_history,
    peek_history,
)
from consensusml_tpu.obs.httpd import MetricsServer  # noqa: F401
from consensusml_tpu.obs.memviz import (  # noqa: F401
    HbmAccountant,
    compiled_footprint,
    live_array_bytes,
)
from consensusml_tpu.obs.health import (  # noqa: F401
    ConsensusHealthMonitor,
    decay_bound,
)
from consensusml_tpu.obs.links import (  # noqa: F401
    LinkProber,
    link_wire_bytes,
)
from consensusml_tpu.obs.metrics import (  # noqa: F401
    Counter,
    DEFAULT_LATENCY_BUCKETS,
    DEFAULT_LINK_LATENCY_BUCKETS,
    DEFAULT_ROUND_COUNT_BUCKETS,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    parse_metric_key,
)
from consensusml_tpu.obs.requests import (  # noqa: F401
    RequestTrace,
    RequestTraceRegistry,
    TraceContext,
    get_request_registry,
    merged_chrome_trace,
)
from consensusml_tpu.obs.tracer import (  # noqa: F401
    SpanTracer,
    get_tracer,
    span,
)
