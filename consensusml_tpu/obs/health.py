"""Consensus-health monitor: measured decay vs the spectral bound, live.

Gossip theory gives every topology a per-round worst-case contraction of
the disagreement: ``d_{t+1} <= rho * d_t`` with ``rho = 1 - spectral_gap``
(per-PERIOD for time-varying schedules, reported here as the per-round
geometric rate ``rho_period^(1/period)``). The benches check this
offline (BENCH_DETAIL: world-32 ring decay 0.9409 vs bound 0.9872, torus
0.8471 vs 0.8828); :class:`ConsensusHealthMonitor` checks it ONLINE —
every round's consensus distance feeds ``observe()``, which maintains a
windowed measured decay rate and trips a loud anomaly on sustained
violation.

Two regimes, because training is not pure gossip:

- ``strict=True`` — pure-consensus runs (decay probes, eval harnesses):
  any sustained ``d_t / d_{t-1} > rho + tolerance`` is a bound
  violation. Local SGD would false-positive here (each round injects
  fresh drift), so strict mode is for runs where gossip is the only
  force.
- ``strict=False`` (training default) — the inner loop legitimately
  re-inflates disagreement up to a plateau, so the only certain
  anomaly is sustained GROWTH: ``d_t / d_{t-1} > 1 + tolerance`` for
  ``sustain`` consecutive rounds means a replica is diverging (NaN-adjacent
  params, a poisoned codec, a dead link biasing the mean) — growth always
  violates the bound too, since ``rho < 1``.

Feeds the ``consensusml_health_*`` gauge family (measured decay, bound,
distance, violation flag) and ``consensusml_health_anomalies_total``;
anomalies also land as tracer instant events and a stderr log line that
names the round, the measured rate and the bound — the "loud" part.
With an :class:`~consensusml_tpu.obs.alerts.AlertEngine` attached
(``alerts=``, the train loop wires it when telemetry is on) the episode
log routes through :meth:`AlertEngine.notify` instead of a bespoke
``print``, so the episode shows up in ``/alerts`` and the cluster
report's event stream; the fire/clear LIFECYCLE rides the
``consensusml_health_bound_violation`` gauge via the default ruleset's
``consensus-health-violation`` rule (docs/observability.md
"Alerting & history").
"""

from __future__ import annotations

import math
import sys
from collections import deque
from typing import Any

from consensusml_tpu.obs.metrics import MetricsRegistry, get_registry
from consensusml_tpu.obs.tracer import SpanTracer, get_tracer

__all__ = ["ConsensusHealthMonitor", "decay_bound"]


def decay_bound(topology) -> float:
    """Per-ROUND worst-case consensus contraction rate ``rho`` for a
    topology: ``1 - spectral_gap()``, with time-varying schedules' per-
    period gap folded to the round-wise geometric rate."""
    rho = 1.0 - topology.spectral_gap()
    rho = min(max(rho, 0.0), 1.0)
    period = getattr(topology, "period", 1) if topology.is_time_varying else 1
    if period > 1:
        rho = rho ** (1.0 / period)
    return rho


class ConsensusHealthMonitor:
    """Online measured-vs-bound consensus decay with anomaly detection.

    ``observe(round, distance)`` per round; returns an anomaly record
    dict when a sustained violation starts (and on every following round
    while it persists), else None. ``anomalies`` keeps every record.
    """

    def __init__(
        self,
        topology,
        registry: MetricsRegistry | None = None,
        tracer: SpanTracer | None = None,
        *,
        strict: bool = False,
        tolerance: float = 0.02,
        sustain: int = 3,
        window: int = 16,
        floor: float = 1e-9,
        alerts=None,
    ):
        if sustain < 1:
            raise ValueError(f"sustain must be >= 1, got {sustain}")
        self.topology = topology
        self.registry = registry if registry is not None else get_registry()
        self.tracer = tracer if tracer is not None else get_tracer()
        # optional AlertEngine: episode logs route through its event
        # stream instead of a bespoke stderr print (module docstring)
        self.alerts = alerts
        self.strict = strict
        self.tolerance = float(tolerance)
        self.sustain = int(sustain)
        self.floor = float(floor)
        self.bound = decay_bound(topology)
        # the per-round ratio that counts as a violation: the spectral
        # bound itself in strict mode, growth otherwise (see module doc)
        self.threshold = (
            self.bound + self.tolerance
            if strict
            else max(self.bound, 1.0) + self.tolerance
        )
        self._last: float | None = None
        self._ratios: deque[float] = deque(maxlen=window)
        self._streak = 0
        self.anomalies: list[dict[str, Any]] = []
        r = self.registry
        r.gauge(
            "consensusml_health_decay_bound",
            "spectral per-round consensus contraction bound rho = 1 - gap",
        ).set(self.bound)
        self._g_measured = r.gauge(
            "consensusml_health_decay_measured",
            "windowed geometric-mean measured consensus decay per round",
        )
        self._g_distance = r.gauge(
            "consensusml_health_consensus_distance",
            "latest consensus distance the health monitor observed",
        )
        self._g_violation = r.gauge(
            "consensusml_health_bound_violation",
            "1 while consensus decay is in sustained violation "
            "(divergence, or strict-mode bound breach), else 0",
        )
        self._g_violation.set(0.0)
        self._m_anomalies = r.counter(
            "consensusml_health_anomalies_total",
            "sustained consensus-decay anomaly episodes",
        )

    @property
    def measured_decay(self) -> float:
        """Geometric mean of the windowed per-round decay ratios (NaN
        until two observations land)."""
        if not self._ratios:
            return math.nan
        log_sum = sum(math.log(max(x, 1e-300)) for x in self._ratios)
        return math.exp(log_sum / len(self._ratios))

    def observe(self, rnd: int, distance: float) -> dict[str, Any] | None:
        d = float(distance)
        self._g_distance.set(d)
        record = None
        violated_this_round = False
        if not math.isfinite(d):
            # a NaN/Inf distance IS the diverged-replica signature —
            # count it as a violating round directly
            violated_this_round = True
            ratio = math.inf
        elif self._last is not None and self._last > self.floor:
            ratio = d / self._last
            self._ratios.append(ratio)
            self._g_measured.set(self.measured_decay)
            violated_this_round = ratio > self.threshold
        else:
            ratio = math.nan
        if violated_this_round:
            self._streak += 1
        else:
            if self._streak >= self.sustain:
                self._g_violation.set(0.0)  # episode ended
            self._streak = 0
        if self._streak >= self.sustain:
            self._g_violation.set(1.0)
            record = {
                "round": int(rnd),
                "kind": (
                    "divergence"
                    if not math.isfinite(ratio) or ratio > 1.0
                    else "bound-violation"
                ),
                "ratio": float(ratio),
                "measured_decay": float(self.measured_decay),
                "bound": float(self.bound),
                "threshold": float(self.threshold),
                "streak": int(self._streak),
                "distance": d,
            }
            self.anomalies.append(record)
            if self._streak == self.sustain:  # episode start: be loud
                self._m_anomalies.inc()
                msg = (
                    "consensus-health ANOMALY: "
                    f"{record['kind']} at round {rnd} — consensus distance "
                    f"{d:.4g} decayed at {ratio:.4f}/round for "
                    f"{self._streak} rounds (threshold {self.threshold:.4f}"
                    f", spectral bound {self.bound:.4f}, topology "
                    f"{self.topology.name}); a replica is likely diverging "
                    "or a link is biasing the mean "
                    "(consensusml_tpu.obs.health)"
                )
                if self.alerts is not None:
                    self.alerts.notify(
                        "consensus-health", msg, severity="page",
                        round=int(rnd), kind=record["kind"],
                        ratio=record["ratio"], bound=record["bound"],
                    )
                else:
                    print(msg, file=sys.stderr, flush=True)
            self.tracer.instant(
                "health.anomaly",
                round=rnd,
                kind=record["kind"],
                ratio=record["ratio"],
            )
        if math.isfinite(d):
            self._last = d
        return record
