"""Live HBM accounting + three-way memory reconciliation.

``tools/hbm_model.py`` PREDICTS per-device bytes from first principles;
the cost ledger (:mod:`consensusml_tpu.obs.costs`) records what XLA
COMPILED; the runtime knows what is actually LIVE. Until now only the
first existed as a number anyone could alert on — this module makes all
three first-class gauges and reconciles them:

- **analytic** — ``hbm_model.predict()``'s peak model (state + batch +
  max(activations, codec transients) + payloads). Authoritative BEFORE a
  run exists: capacity planning, "does llama_lora fit a v5e".
- **compiled** — the ledger's ``memory_analysis()`` live footprint
  (arguments + temps + outputs − aliases). Authoritative for ONE
  executable: what XLA will reserve when that program runs.
- **live** — ``jax.live_arrays()`` totals plus the runtime's
  ``device.memory_stats()`` peak where the backend exposes one (CPU and
  this box's tunneled TPU do not: there the live-array total is a FLOOR
  — it cannot see XLA temps — and the compiled number is the peak
  authority). Authoritative for the PROCESS: leaks, fragmentation,
  serving headroom.

Pairwise drift lands on ``consensusml_hbm_drift_pct{pair=...}`` so a
model that stops matching reality pages someone instead of rotting in a
doc table (docs/memory.md "Reconciliation"). The serving engine
additionally tags its big resident consumers — block-pool pages
(``consensusml_pool_hbm_bytes`` / ``consensusml_pool_hbm_free_bytes``)
and the params tree (``consensusml_serve_params_bytes``) — so per-engine
KV headroom is a gauge the fleet router can place traffic on, and the
prefetcher reports its staged window (``consensusml_feed_staged_bytes``).
"""

from __future__ import annotations

import math
import os
import time
from typing import Any

from consensusml_tpu.obs.metrics import MetricsRegistry, get_registry

__all__ = [
    "live_array_bytes",
    "device_memory_stats",
    "compiled_footprint",
    "load_tool",
    "HbmAccountant",
    "reconcile_config",
]


def live_array_bytes() -> dict[str, Any]:
    """Sum of all live jax array buffers in this process.

    Walks ``jax.live_arrays()`` — host-side bookkeeping, no device sync,
    cheap enough for a telemetry tick. Deleted-but-unreleased buffers
    (donated inputs mid-dispatch) may still count for one tick; that
    jitter is why the reconciliation tolerance is a band, not equality.
    """
    import jax

    total = 0
    count = 0
    for a in jax.live_arrays():
        try:
            total += int(a.nbytes)
        except Exception:  # deleted under us mid-walk
            continue
        count += 1
    return {"bytes": total, "arrays": count}


def device_memory_stats(device: Any = None) -> dict[str, float] | None:
    """The runtime's own accounting (``peak_bytes_in_use`` etc.), or
    None where the backend hides it (CPU, tunneled TPU runtimes)."""
    import jax

    dev = device if device is not None else jax.local_devices()[0]
    try:
        stats = dev.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return {k: float(v) for k, v in stats.items()}


def compiled_footprint(ma: Any) -> int:
    """XLA's live device footprint from a ``memory_analysis()`` result:
    arguments + temps + outputs − aliases (donated state aliases its
    outputs, so this is what the device actually holds at once). The
    ONE definition shared by the cost ledger, ``tools/hbm_model.py
    --measure`` and the reconciliation below."""
    return int(
        ma.argument_size_in_bytes
        + ma.temp_size_in_bytes
        + ma.output_size_in_bytes
        - ma.alias_size_in_bytes
    )


def _drift_pct(a: float, b: float) -> float:
    """Signed drift of ``a`` relative to ``b`` in percent."""
    if not b:
        return math.nan
    return 100.0 * (a - b) / b


class HbmAccountant:
    """Live HBM gauges + the three-way reconciliation writer."""

    def __init__(
        self, registry: MetricsRegistry | None = None, device: Any = None
    ):
        self.registry = registry if registry is not None else get_registry()
        self.device = device
        reg = self.registry
        self._g_live = reg.gauge(
            "consensusml_hbm_live_bytes",
            "bytes held by live jax arrays in this process (floor on "
            "runtimes without memory_stats: XLA temps are invisible)",
        )
        self._g_arrays = reg.gauge(
            "consensusml_hbm_live_arrays", "live jax array count"
        )
        self._g_peak = reg.gauge(
            "consensusml_hbm_peak_bytes",
            "runtime peak_bytes_in_use (NaN when the backend hides "
            "memory_stats)",
        )
        self._g_limit = reg.gauge(
            "consensusml_hbm_limit_bytes",
            "runtime bytes_limit (NaN when unavailable)",
        )
        self._live_peak = 0.0  # high-water mark of our own live samples

    def tick(self) -> dict[str, Any]:
        """One sample: refresh the live gauges (telemetry-tick cadence;
        the bench attribution section prices this under the <1%-of-a-
        round budget)."""
        live = live_array_bytes()
        self._live_peak = max(self._live_peak, float(live["bytes"]))
        self._g_live.set(live["bytes"])
        self._g_arrays.set(live["arrays"])
        stats = device_memory_stats(self.device)
        peak = (stats or {}).get("peak_bytes_in_use", math.nan)
        limit = (stats or {}).get("bytes_limit", math.nan)
        self._g_peak.set(peak)
        self._g_limit.set(limit)
        return {
            "time_s": time.time(),
            "live_bytes": live["bytes"],
            "live_arrays": live["arrays"],
            "runtime_peak_bytes": peak,
            "runtime_limit_bytes": limit,
        }

    @property
    def live_peak_bytes(self) -> float:
        """Best live peak this accountant knows: the runtime's
        ``peak_bytes_in_use`` when exposed, else the high-water mark of
        the live-array samples taken so far."""
        stats = device_memory_stats(self.device)
        if stats and stats.get("peak_bytes_in_use"):
            return float(stats["peak_bytes_in_use"])
        return self._live_peak

    def reconcile(
        self,
        analytic_bytes: float | None,
        compiled_bytes: float | None,
        live_peak_bytes: float | None = None,
    ) -> dict[str, Any]:
        """Set the three absolute gauges + pairwise drift gauges and
        return the reconciliation doc. ``None`` sides render as NaN and
        drop out of the drift pairs rather than faking a zero."""
        if live_peak_bytes is None:
            live_peak_bytes = self.live_peak_bytes
        reg = self.registry
        vals = {
            "analytic": analytic_bytes,
            "compiled": compiled_bytes,
            "live": live_peak_bytes,
        }
        reg.gauge(
            "consensusml_hbm_analytic_bytes",
            "tools/hbm_model.py predicted per-device peak",
        ).set(math.nan if analytic_bytes is None else analytic_bytes)
        reg.gauge(
            "consensusml_hbm_compiled_bytes",
            "XLA memory_analysis live footprint (args+temps+outputs-aliases)",
        ).set(math.nan if compiled_bytes is None else compiled_bytes)
        reg.gauge(
            "consensusml_hbm_live_peak_bytes",
            "observed live peak (runtime peak_bytes_in_use, or the "
            "live-array high-water mark where the runtime hides stats)",
        ).set(math.nan if live_peak_bytes is None else live_peak_bytes)
        drift: dict[str, float] = {}
        for a, b in (
            ("analytic", "compiled"),
            ("compiled", "live"),
            ("analytic", "live"),
        ):
            if vals[a] is None or vals[b] is None:
                continue
            pct = _drift_pct(float(vals[a]), float(vals[b]))
            drift[f"{a}_vs_{b}"] = pct
            reg.gauge(
                "consensusml_hbm_drift_pct",
                "signed drift between two HBM accountings "
                "(100*(first-second)/second per pair label)",
                labels={"pair": f"{a}_vs_{b}"},
            ).set(pct)
        return {
            "analytic_bytes": analytic_bytes,
            "compiled_bytes": compiled_bytes,
            "live_peak_bytes": live_peak_bytes,
            "drift_pct": drift,
        }


def load_tool(name: str):
    """Import a ``tools/<name>.py`` script by path (tools/ is a script
    dir next to the package, not a package itself — the repo layout
    pins it two levels up from obs/). None when absent (installed
    package without the repo checkout). The ONE loader every obs
    module shares — the /profile endpoint and the reconciliation both
    use it, so a tools/ relocation breaks in exactly one place."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
        "tools",
        f"{name}.py",
    )
    if not os.path.exists(path):
        return None
    spec = importlib.util.spec_from_file_location(f"_cml_tool_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_hbm_model():
    return load_tool("hbm_model")


def reconcile_config(
    name: str,
    scale: str = "smoke",
    rounds: int = 2,
    registry: MetricsRegistry | None = None,
    ledger: Any = None,
) -> dict[str, Any]:
    """The full three-way for one config at world=1 (the per-device
    layout the analytic model predicts): analytic ``predict()`` vs the
    compiled train step's ``memory_analysis()`` (through the cost
    ledger, so the row lands in ``consensusml_cost_*`` too) vs the live
    peak after actually running ``rounds`` rounds.

    CPU note (the ``pytest -m profiling`` tier runs this): the runtime
    hides memory_stats, so "live" is the live-array high-water mark — a
    floor missing XLA temps — and the analytic model's activation
    coefficients were fit against TPU scheduling; the drift assertion
    is correspondingly a loose band, not a tight tolerance.
    """
    import jax

    from consensusml_tpu import configs
    from consensusml_tpu.obs.costs import get_cost_ledger
    from consensusml_tpu.train import (
        init_stacked_state,
        make_simulated_train_step,
    )

    hbm_model = _load_hbm_model()
    if hbm_model is None:
        raise RuntimeError(
            "tools/hbm_model.py not found next to the package — the "
            "three-way reconciliation needs the analytic side"
        )
    analytic = hbm_model.predict(name, scale, world=1)

    if ledger is None:
        ledger = get_cost_ledger()
    acct = HbmAccountant(registry=registry)
    bundle = configs.build(name, scale, world=1)
    step = make_simulated_train_step(bundle.cfg, bundle.loss_fn)
    state = init_stacked_state(
        bundle.cfg, bundle.init_params, jax.random.key(0), 1
    )
    batch = next(iter(bundle.batches(1, 0)))
    row = ledger.register(
        f"train.step.{name}", step, state, batch,
        meta={"config": name, "scale": scale, "world": 1},
    )
    acct.tick()
    metrics = None
    for b in bundle.batches(rounds, 0):
        state, metrics = step(state, b)
        acct.tick()
    if metrics is not None:  # execute for real; fence on the loss
        float(metrics["loss"])
    acct.tick()
    doc = acct.reconcile(
        analytic_bytes=float(analytic["predicted_peak_bytes"]),
        compiled_bytes=float(row.peak_bytes),
    )
    doc.update(
        {
            "config": name,
            "scale": scale,
            "executable": row.name,
            "compile_s": row.compile_s,
            "analytic_detail": analytic["per_device"],
        }
    )
    return doc
