"""Cross-rank aggregation: one cluster view from per-rank snapshots.

PR 2's telemetry is strictly per-process; a swarm needs the merged
picture. The shared-directory sideband keeps it dependency-free and
multi-controller-correct:

- every rank runs a :class:`ClusterWriter` (``train.py
  --obs-cluster-dir DIR``): at telemetry cadence it rewrites its OWN
  file ``obs-<role>-<rank>.json`` atomically (tmp + rename, the same
  textfile-collector contract the Prometheus exporter uses) with its
  current registry values, round progress, and a heartbeat timestamp.
  One file per rank, latest wins — no append-log compaction problem,
  no cross-process locking (ranks never touch each other's files). The
  directory can be a shared filesystem mount (multi-host pods) or a
  local dir that a sidecar rsyncs — the aggregator only sees files.
- :func:`aggregate` merges every snapshot in the directory into one
  cluster document: per-rank round/latency skew, merged per-link
  latency histograms with a slowest-link ranking, measured-vs-bound
  consensus health, straggler detection (stale heartbeat or round
  lag), churn counters, and an index of any flight-recorder dumps that
  landed next to the snapshots.
- ``tools/obs_report.py`` renders that document as JSON or text.

Non-rank roles ride the same channel: ``tools/loadgen.py
--obs-snapshot`` writes an ``obs-loadgen-*.json`` with its
client-observed ``consensusml_loadgen_*`` SLOs, so the serving client
and server sides of an SLO story merge into the same report.
"""

from __future__ import annotations

import glob
import json
import math
import os
import time
from collections import deque
from typing import Any

from consensusml_tpu.obs.metrics import MetricsRegistry, get_registry, parse_metric_key

__all__ = [
    "ClusterWriter",
    "read_snapshots",
    "aggregate",
    "hist_stats",
    "SNAP_PREFIX",
]

SNAP_PREFIX = "obs-"


class ClusterWriter:
    """Atomically (re)writes this process's cluster snapshot file."""

    def __init__(
        self,
        out_dir: str,
        rank: int = 0,
        role: str = "rank",
        registry: MetricsRegistry | None = None,
        world_size: int | None = None,
    ):
        self.out_dir = out_dir
        self.rank = int(rank)
        self.role = role
        self.world_size = world_size
        self.registry = registry if registry is not None else get_registry()
        os.makedirs(out_dir, exist_ok=True)
        self.path = os.path.join(
            out_dir, f"{SNAP_PREFIX}{role}-{self.rank:05d}.json"
        )
        # membership-event timeline (swarm churn): bounded ring, rewritten
        # whole into every snapshot — latest-wins like the rest of the file
        self._events: deque = deque(maxlen=256)

    def record_event(self, event: dict[str, Any]) -> None:
        """Append a membership/churn event row (``{"round": .., "kind":
        "join|drop|rejoin|straggle", "workers": [..], ...}``) to the
        timeline this writer's snapshots carry; the aggregator merges
        every rank's rows into the cluster report's membership timeline."""
        self._events.append(dict(event))

    def write(
        self, round: int | None = None, extra: dict[str, Any] | None = None
    ) -> str:
        doc: dict[str, Any] = {
            "rank": self.rank,
            "role": self.role,
            "pid": os.getpid(),
            "world_size": self.world_size,
            "round": round,
            "heartbeat_s": time.time(),
            "metrics": {
                m.key: m.value_dict() for m in self.registry.metrics()
            },
        }
        if self._events:
            doc["swarm_events"] = list(self._events)
        if extra:
            doc.update(extra)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self.path)
        return self.path


def read_snapshots(cluster_dir: str) -> list[dict[str, Any]]:
    """Every parseable ``obs-*.json`` in the directory, rank-sorted.
    Unparseable files (a writer died mid-rename on a non-POSIX mount)
    are reported in-band under ``_errors``, never raised."""
    out: list[dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(cluster_dir, f"{SNAP_PREFIX}*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
            doc["_file"] = os.path.basename(path)
            out.append(doc)
        except (OSError, ValueError) as e:
            out.append(
                {"_file": os.path.basename(path), "_error": f"{type(e).__name__}: {e}"}
            )
    out.sort(key=lambda d: (d.get("role") or "", d.get("rank") or 0))
    return out


def hist_stats(vd: dict[str, Any]) -> dict[str, float]:
    """mean/p50/p99 from a histogram ``value_dict`` (cumulative-bucket
    linear interpolation — the standard textfile-collector estimate)."""
    count = vd.get("count", 0)
    if not count:
        return {"count": 0, "mean": math.nan, "p50": math.nan, "p99": math.nan}
    total = vd.get("sum", 0.0)
    edges = sorted(((float(le), c) for le, c in vd.get("buckets", {}).items()))

    def quantile(q: float) -> float:
        target = q * count
        cum = 0.0
        lo = 0.0
        for le, c in edges:
            if cum + c >= target:
                frac = (target - cum) / c if c else 0.0
                return lo + frac * (le - lo)
            cum += c
            lo = le
        return lo  # landed in the +Inf bucket: report the last edge

    return {
        "count": count,
        "mean": total / count,
        "p50": quantile(0.50),
        "p99": quantile(0.99),
    }


def _merge_hist(a: dict[str, Any] | None, b: dict[str, Any]) -> dict[str, Any]:
    if a is None:
        return {
            "count": b.get("count", 0),
            "sum": b.get("sum", 0.0),
            "buckets": dict(b.get("buckets", {})),
            "inf": b.get("inf", 0),
        }
    out = dict(a)
    out["count"] = a.get("count", 0) + b.get("count", 0)
    out["sum"] = a.get("sum", 0.0) + b.get("sum", 0.0)
    out["inf"] = a.get("inf", 0) + b.get("inf", 0)
    buckets = dict(a.get("buckets", {}))
    for le, c in b.get("buckets", {}).items():
        buckets[le] = buckets.get(le, 0) + c
    out["buckets"] = buckets
    return out


def _metric(doc: dict, name: str, default=None):
    v = doc.get("metrics", {}).get(name, default)
    return default if v is None else v


def _finite(v) -> float | None:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


def aggregate(
    cluster_dir: str,
    *,
    now: float | None = None,
    straggler_age_s: float = 120.0,
    straggler_round_lag: int = 3,
    top_links: int = 16,
) -> dict[str, Any]:
    """Merge a cluster directory into one report document.

    ``now`` is injectable so tests (and replays of an old directory)
    get deterministic heartbeat ages. The report is plain JSON-able
    data; ``tools/obs_report.py`` renders it.
    """
    now = time.time() if now is None else now
    snaps = read_snapshots(cluster_dir)
    errors = [s for s in snaps if "_error" in s]
    ranks = [s for s in snaps if "_error" not in s and s.get("role") == "rank"]
    others = [
        s for s in snaps if "_error" not in s and s.get("role") != "rank"
    ]

    # ---- per-rank rows ---------------------------------------------------
    rank_rows: list[dict[str, Any]] = []
    link_hists: dict[tuple[str, str], dict] = {}
    link_wire: dict[tuple[str, str], float] = {}
    link_traced: dict[tuple[str, str], float] = {}
    for s in ranks:
        lat = _metric(s, "consensusml_round_latency_seconds")
        row = {
            "rank": s.get("rank"),
            "file": s.get("_file"),
            "round": s.get("round"),
            "heartbeat_age_s": round(now - s.get("heartbeat_s", now), 3),
            "rounds_total": _metric(s, "consensusml_rounds_total", 0.0),
            "wire_bytes_total": _metric(s, "consensusml_wire_bytes_total", 0.0),
            "round_latency": (
                hist_stats(lat) if isinstance(lat, dict) else None
            ),
            "consensus_distance": _finite(
                _metric(s, "consensusml_consensus_distance")
            ),
            "alive_frac": _finite(_metric(s, "consensusml_alive_frac")),
            "health": {
                "decay_measured": _finite(
                    _metric(s, "consensusml_health_decay_measured")
                ),
                "decay_bound": _finite(
                    _metric(s, "consensusml_health_decay_bound")
                ),
                "bound_violation": _finite(
                    _metric(s, "consensusml_health_bound_violation")
                ),
                "anomalies_total": _metric(
                    s, "consensusml_health_anomalies_total", 0.0
                ),
            },
        }
        rank_rows.append(row)
        # merge every rank's per-edge families (a rank sees its own
        # probes; in single-controller runs rank 0 sees every edge)
        for key, vd in s.get("metrics", {}).items():
            name, labels = parse_metric_key(key)
            if "src" not in labels or "dst" not in labels:
                continue
            edge = (labels["src"], labels["dst"])
            if name == "consensusml_link_latency_seconds" and isinstance(
                vd, dict
            ):
                link_hists[edge] = _merge_hist(link_hists.get(edge), vd)
            elif name in (
                "consensusml_link_wire_bytes_per_round",
                "consensusml_link_wire_bytes_traced_total",
            ):
                f = _finite(vd)
                if f is not None:
                    # max, not sum: every process traces/records the same
                    # full edge set, so summing would multiply by ranks.
                    # The two families stay SEPARATE report fields: the
                    # gauge is the engine's per-round accounting, the
                    # traced counter ACCUMULATES per compile (a retrace
                    # doubles it) and must never masquerade as bytes/round
                    tgt = (
                        link_wire
                        if name == "consensusml_link_wire_bytes_per_round"
                        else link_traced
                    )
                    tgt[edge] = max(tgt.get(edge, 0.0), f)

    # ---- skew ------------------------------------------------------------
    rounds = [r["round"] for r in rank_rows if r["round"] is not None]
    lat_means = [
        r["round_latency"]["mean"]
        for r in rank_rows
        if r["round_latency"] and r["round_latency"]["count"]
    ]
    skew = {
        "ranks": len(rank_rows),
        "round_min": min(rounds) if rounds else None,
        "round_max": max(rounds) if rounds else None,
        "round_lag": (max(rounds) - min(rounds)) if rounds else None,
        "round_latency_mean_min_s": min(lat_means) if lat_means else None,
        "round_latency_mean_max_s": max(lat_means) if lat_means else None,
        "round_latency_skew": (
            max(lat_means) / min(lat_means)
            if lat_means and min(lat_means) > 0
            else None
        ),
    }

    # ---- slowest links ---------------------------------------------------
    links = []

    def link_row(src: str, dst: str, st: dict | None) -> dict[str, Any]:
        return {
            "src": int(src),
            "dst": int(dst),
            "probes": st["count"] if st else 0,
            "mean_latency_s": st["mean"] if st else None,
            "p99_latency_s": st["p99"] if st else None,
            "wire_bytes_per_round": link_wire.get((src, dst)),
            "wire_bytes_traced_total": link_traced.get((src, dst)),
        }

    for (src, dst), vd in link_hists.items():
        links.append(link_row(src, dst, hist_stats(vd)))
    links.sort(key=lambda r: -(r["mean_latency_s"] or 0.0))
    # edges with wire accounting but no probes still belong in the map
    probed = {(r["src"], r["dst"]) for r in links}
    for src, dst in sorted(set(link_wire) | set(link_traced)):
        if (int(src), int(dst)) not in probed:
            links.append(link_row(src, dst, None))

    # ---- stragglers / churn ---------------------------------------------
    max_round = skew["round_max"]
    stragglers = []
    for r in rank_rows:
        reasons = []
        if r["heartbeat_age_s"] > straggler_age_s:
            reasons.append(f"heartbeat stale {r['heartbeat_age_s']:.0f}s")
        if (
            max_round is not None
            and r["round"] is not None
            and max_round - r["round"] >= straggler_round_lag
        ):
            reasons.append(f"{max_round - r['round']} rounds behind")
        if reasons:
            stragglers.append({"rank": r["rank"], "reasons": reasons})
    churn = {
        "elastic_resizes_total": sum(
            _metric(s, "consensusml_elastic_resizes_total", 0.0) for s in ranks
        ),
        "joined_workers_total": sum(
            _metric(s, "consensusml_elastic_joined_workers_total", 0.0)
            for s in ranks
        ),
        "fault_rounds_total": sum(
            _metric(s, "consensusml_fault_rounds_total", 0.0) for s in ranks
        ),
        "worker_drops_total": sum(
            _metric(s, "consensusml_worker_drops_total", 0.0) for s in ranks
        ),
        "watchdog_timeouts_total": sum(
            _metric(s, "consensusml_watchdog_timeouts_total", 0.0)
            for s in ranks
        ),
        # swarm counters are REPLICATED, not per-rank: every rank's
        # controller replays the same schedule (same reason the event
        # timeline below dedups), so merge with max, not sum
        "bootstrapped_joiners_total": max(
            (
                _metric(s, "consensusml_swarm_bootstrapped_joiners_total", 0.0)
                for s in ranks
            ),
            default=0.0,
        ),
        "recovery_rounds_total": max(
            (
                _metric(s, "consensusml_swarm_recovery_rounds_total", 0.0)
                for s in ranks
            ),
            default=0.0,
        ),
    }

    # ---- membership (swarm) ---------------------------------------------
    # per-kind event counters (labeled family) + the merged event timeline
    # the ClusterWriter snapshots carry — what obs_report renders as the
    # join/drop/straggler-vs-round view
    event_counts: dict[str, float] = {}
    timeline: list[dict[str, Any]] = []
    seen_events = set()
    swarm_epoch = None
    swarm_members = None
    for s in ranks:
        for key, vd in s.get("metrics", {}).items():
            name, labels = parse_metric_key(key)
            if name == "consensusml_swarm_events_total" and "kind" in labels:
                f = _finite(vd)
                if f is not None:
                    # replicated across ranks (same schedule) — max, like
                    # the timeline dedup below, not a rank-count inflation
                    k = labels["kind"]
                    event_counts[k] = max(event_counts.get(k, 0.0), f)
        e = _finite(_metric(s, "consensusml_swarm_epoch"))
        if e is not None:
            swarm_epoch = max(swarm_epoch or 0, e)
        m = _finite(_metric(s, "consensusml_swarm_members"))
        if m is not None:
            swarm_members = m if swarm_members is None else max(swarm_members, m)
        for row in s.get("swarm_events", []):
            key = (
                row.get("round"), row.get("kind"),
                tuple(row.get("workers") or ()),
            )
            if key in seen_events:  # every rank replays the same schedule
                continue
            seen_events.add(key)
            timeline.append(dict(row, rank=s.get("rank")))
    timeline.sort(key=lambda r: (r.get("round") or 0, r.get("kind") or ""))
    membership = {
        "epoch": swarm_epoch,
        "active_members": swarm_members,
        "event_counts": event_counts,
        "timeline": timeline,
    }

    # ---- cluster-level health -------------------------------------------
    measured = [
        r["health"]["decay_measured"]
        for r in rank_rows
        if r["health"]["decay_measured"] is not None
    ]
    bounds = [
        r["health"]["decay_bound"]
        for r in rank_rows
        if r["health"]["decay_bound"] is not None
    ]
    health = {
        "decay_bound": bounds[0] if bounds else None,
        "decay_measured_worst": max(measured) if measured else None,
        "ranks_in_violation": sum(
            1 for r in rank_rows if (r["health"]["bound_violation"] or 0) > 0
        ),
        "anomalies_total": sum(
            r["health"]["anomalies_total"] or 0 for r in rank_rows
        ),
    }

    # ---- flight-recorder index ------------------------------------------
    flightrecs = []
    for path in sorted(
        glob.glob(os.path.join(cluster_dir, "**", "flightrec-*.json"),
                  recursive=True)
    ):
        st = os.stat(path)
        flightrecs.append(
            {
                "file": os.path.relpath(path, cluster_dir),
                "bytes": st.st_size,
                "mtime_s": st.st_mtime,
            }
        )

    # ---- non-rank roles (loadgen etc.) ----------------------------------
    other_rows = []
    for s in others:
        row = {
            "role": s.get("role"),
            "rank": s.get("rank"),
            "file": s.get("_file"),
            "heartbeat_age_s": round(now - s.get("heartbeat_s", now), 3),
            "metrics": {},
        }
        for key, vd in s.get("metrics", {}).items():
            if isinstance(vd, dict):
                row["metrics"][key] = hist_stats(vd)
            else:
                f = _finite(vd)
                if f is not None:
                    row["metrics"][key] = f
        other_rows.append(row)

    return {
        "time_s": now,
        "cluster_dir": os.path.abspath(cluster_dir),
        "skew": skew,
        "ranks": rank_rows,
        "links": links[: max(top_links, 0)] if top_links else links,
        "links_total": len(links),
        "health": health,
        "stragglers": stragglers,
        "churn": churn,
        "membership": membership,
        "flight_recorders": flightrecs,
        "clients": other_rows,
        "errors": errors,
    }
