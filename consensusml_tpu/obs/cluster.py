"""Cross-rank aggregation: one cluster view from per-rank snapshots.

PR 2's telemetry is strictly per-process; a swarm needs the merged
picture. The shared-directory sideband keeps it dependency-free and
multi-controller-correct:

- every rank runs a :class:`ClusterWriter` (``train.py
  --obs-cluster-dir DIR``): at telemetry cadence it rewrites its OWN
  file ``obs-<role>-<rank>.json`` atomically (tmp + rename, the same
  textfile-collector contract the Prometheus exporter uses) with its
  current registry values, round progress, and a heartbeat timestamp.
  One file per rank, latest wins — no append-log compaction problem,
  no cross-process locking (ranks never touch each other's files). The
  directory can be a shared filesystem mount (multi-host pods) or a
  local dir that a sidecar rsyncs — the aggregator only sees files.
- :func:`aggregate` merges every snapshot in the directory into one
  cluster document: per-rank round/latency skew, merged per-link
  latency histograms with a slowest-link ranking, measured-vs-bound
  consensus health, straggler detection (stale heartbeat or round
  lag), churn counters, and an index of any flight-recorder dumps that
  landed next to the snapshots.
- ``tools/obs_report.py`` renders that document as JSON or text.

Non-rank roles ride the same channel: ``tools/loadgen.py
--obs-snapshot`` writes an ``obs-loadgen-*.json`` with its
client-observed ``consensusml_loadgen_*`` SLOs, so the serving client
and server sides of an SLO story merge into the same report.
"""

from __future__ import annotations

import glob
import json
import math
import os
import time
from collections import deque
from typing import Any

from consensusml_tpu.obs.metrics import MetricsRegistry, get_registry, parse_metric_key

__all__ = [
    "ClusterWriter",
    "read_snapshots",
    "aggregate",
    "hist_stats",
    "SNAP_PREFIX",
]

SNAP_PREFIX = "obs-"


class ClusterWriter:
    """Atomically (re)writes this process's cluster snapshot file."""

    def __init__(
        self,
        out_dir: str,
        rank: int = 0,
        role: str = "rank",
        registry: MetricsRegistry | None = None,
        world_size: int | None = None,
        tracer=None,
        history=None,
        alerts=None,
        events=None,
    ):
        from consensusml_tpu.obs.tracer import get_tracer

        self.out_dir = out_dir
        self.rank = int(rank)
        self.role = role
        self.world_size = world_size
        self.registry = registry if registry is not None else get_registry()
        # alert/history digest sources: explicit wiring wins; a writer
        # over the GLOBAL registry falls back to peeking the process
        # singletons (so the train loop's armed plane lands in snapshots
        # without threading two more handles through every call site) —
        # a custom registry never picks up the global plane's digests
        self.history = history
        self.alerts = alerts
        # wide-event log (obs.events): same explicit-or-peek rule — a
        # serving rank's snapshot carries its per-tenant rollup so the
        # aggregator can merge fleet-wide tenant spend
        self.events = events
        self._peek_global = registry is None
        # span-ring digest source: per-round phase rows for the merged
        # round timeline (tracer disabled => no digest in the snapshot)
        self.tracer = tracer if tracer is not None else get_tracer()
        os.makedirs(out_dir, exist_ok=True)
        self.path = os.path.join(
            out_dir, f"{SNAP_PREFIX}{role}-{self.rank:05d}.json"
        )
        # membership-event timeline (swarm churn): bounded ring, rewritten
        # whole into every snapshot — latest-wins like the rest of the file
        self._events: deque = deque(maxlen=256)

    def record_event(self, event: dict[str, Any]) -> None:
        """Append a membership/churn event row (``{"round": .., "kind":
        "join|drop|rejoin|straggle", "workers": [..], ...}``) to the
        timeline this writer's snapshots carry; the aggregator merges
        every rank's rows into the cluster report's membership timeline."""
        self._events.append(dict(event))

    def write(
        self, round: int | None = None, extra: dict[str, Any] | None = None
    ) -> str:
        doc: dict[str, Any] = {
            "rank": self.rank,
            "role": self.role,
            "pid": os.getpid(),
            "world_size": self.world_size,
            "round": round,
            "heartbeat_s": time.time(),
            "metrics": {
                m.key: m.value_dict() for m in self.registry.metrics()
            },
        }
        if self._events:
            doc["swarm_events"] = list(self._events)
        if self.tracer is not None and self.tracer.enabled:
            digest = self.tracer.digest()
            if digest["spans"]:
                doc["span_digest"] = digest
        alerts = self.alerts
        history = self.history
        events = self.events
        if self._peek_global:
            from consensusml_tpu.obs.alerts import peek_alert_engine
            from consensusml_tpu.obs.events import peek_wide_event_log
            from consensusml_tpu.obs.history import peek_history

            alerts = alerts or peek_alert_engine()
            history = history or peek_history()
            events = events or peek_wide_event_log()
        if alerts is not None:
            doc["alerts"] = alerts.snapshot()
        if history is not None:
            doc["history"] = history.digest(points=32)
        if events is not None:
            # rollup only (events_recent capped small): a snapshot is
            # rewritten at cadence, the full ring stays in-process
            doc["wide_events"] = events.snapshot(last_n=16)
        if extra:
            doc.update(extra)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self.path)
        return self.path


def read_snapshots(cluster_dir: str) -> list[dict[str, Any]]:
    """Every parseable ``obs-*.json`` in the directory, rank-sorted.
    Unparseable files (a writer died mid-rename on a non-POSIX mount)
    are reported in-band under ``_errors``, never raised."""
    out: list[dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(cluster_dir, f"{SNAP_PREFIX}*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
            doc["_file"] = os.path.basename(path)
            out.append(doc)
        except (OSError, ValueError) as e:
            out.append(
                {"_file": os.path.basename(path), "_error": f"{type(e).__name__}: {e}"}
            )
    out.sort(key=lambda d: (d.get("role") or "", d.get("rank") or 0))
    return out


def hist_stats(vd: dict[str, Any]) -> dict[str, float]:
    """mean/p50/p99 from a histogram ``value_dict`` (cumulative-bucket
    linear interpolation — the standard textfile-collector estimate)."""
    count = vd.get("count", 0)
    if not count:
        return {"count": 0, "mean": math.nan, "p50": math.nan, "p99": math.nan}
    total = vd.get("sum", 0.0)
    edges = sorted(((float(le), c) for le, c in vd.get("buckets", {}).items()))

    def quantile(q: float) -> float:
        target = q * count
        cum = 0.0
        lo = 0.0
        for le, c in edges:
            if cum + c >= target:
                frac = (target - cum) / c if c else 0.0
                return lo + frac * (le - lo)
            cum += c
            lo = le
        return lo  # landed in the +Inf bucket: report the last edge

    return {
        "count": count,
        "mean": total / count,
        "p50": quantile(0.50),
        "p99": quantile(0.99),
    }


def _merge_hist(a: dict[str, Any] | None, b: dict[str, Any]) -> dict[str, Any]:
    if a is None:
        out = {
            "count": b.get("count", 0),
            "sum": b.get("sum", 0.0),
            "buckets": dict(b.get("buckets", {})),
            "inf": b.get("inf", 0),
        }
        if b.get("exemplars"):
            out["exemplars"] = list(b["exemplars"])
        return out
    out = dict(a)
    out["count"] = a.get("count", 0) + b.get("count", 0)
    out["sum"] = a.get("sum", 0.0) + b.get("sum", 0.0)
    out["inf"] = a.get("inf", 0) + b.get("inf", 0)
    buckets = dict(a.get("buckets", {}))
    for le, c in b.get("buckets", {}).items():
        buckets[le] = buckets.get(le, 0) + c
    out["buckets"] = buckets
    # worst exemplars survive the merge, capped like the per-histogram
    # retention
    ex = list(a.get("exemplars", [])) + list(b.get("exemplars", []))
    if ex:
        from consensusml_tpu.obs.metrics import EXEMPLAR_KEEP

        ex.sort(key=lambda e: -e.get("value", 0.0))
        out["exemplars"] = ex[:EXEMPLAR_KEEP]
    return out


def _metric(doc: dict, name: str, default=None):
    v = (doc.get("metrics") or {}).get(name, default)
    return default if v is None else v


def _age_s(doc: dict, now: float) -> float:
    """Heartbeat age, tolerant of a partial snapshot with the field
    missing or malformed (treated as just-written: age 0)."""
    hb = _finite(doc.get("heartbeat_s"))
    return round(now - (hb if hb is not None else now), 3)


def _finite(v) -> float | None:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


_SLO_SIDES = {
    "consensusml_serve_ttft_seconds": "server",
    "consensusml_serve_prefill_seconds": "server",
    "consensusml_serve_intertoken_seconds": "server",
    "consensusml_loadgen_ttft_seconds": "client",
    "consensusml_loadgen_latency_seconds": "client",
}


def _requests_section(snaps: list[dict[str, Any]], top: int = 8) -> dict[str, Any]:
    """The serving-request view: merge every snapshot's request-trace
    dump into one id index, then resolve the SLO histograms' exemplars
    against it — the "slowest requests" table where a p99 bucket's
    request_id points at a concrete recorded trace (client and server
    observations of one request join on trace_id)."""
    index: dict[str, dict[str, Any]] = {}
    for s in snaps:
        rt = s.get("request_traces") or {}
        for tr in list(rt.get("active", [])) + list(rt.get("completed", [])):
            rid = tr.get("request_id")
            if rid:
                index[rid] = {
                    "trace_id": tr.get("trace_id"),
                    "finish_reason": tr.get("finish_reason"),
                    "decode_ticks": tr.get("decode_ticks", 0),
                    "defer_ticks": tr.get("defer_ticks", 0),
                    "preemptions": tr.get("preemptions", 0),
                    "events": [e.get("name") for e in tr.get("events", [])],
                    "in_flight": tr.get("finish_reason") is None,
                }
    rows: list[dict[str, Any]] = []
    for s in snaps:
        for key, vd in (s.get("metrics") or {}).items():
            name, _labels = parse_metric_key(key)
            side = _SLO_SIDES.get(name)
            if side is None or not isinstance(vd, dict):
                continue
            for ex in vd.get("exemplars", []):
                rid = ex.get("id")
                tr = index.get(rid)
                rows.append(
                    {
                        "metric": name,
                        "side": side,
                        "value_s": ex.get("value"),
                        "request_id": rid,
                        "trace_id": tr["trace_id"] if tr else None,
                        "resolved": tr is not None,
                        "role": s.get("role"),
                        "rank": s.get("rank"),
                        "trace": tr,
                    }
                )
    rows.sort(
        key=lambda r: (
            r["metric"], -(r["value_s"] or 0.0), r["request_id"] or ""
        )
    )
    slowest: list[dict[str, Any]] = []
    per_metric: dict[str, int] = {}
    for r in rows:
        n = per_metric.get(r["metric"], 0)
        if n < top:
            per_metric[r["metric"]] = n + 1
            slowest.append(r)
    return {
        "traces_indexed": len(index),
        "in_flight": sum(1 for t in index.values() if t["in_flight"]),
        "slowest": slowest,
    }


def _round_timeline(ranks: list[dict[str, Any]], max_rounds: int = 64) -> list[dict[str, Any]]:
    """Cross-rank per-round phase rows from the span digests.

    Each rank's ``span_digest.rounds`` carries measured ``train.round``
    duration plus the ``round.feed`` / ``round.fence`` phase spans; the
    merged timeline shows, per round, every rank's split and attributes
    the straggler's EXTRA time (vs the fastest rank) to a phase:
    ``feed`` when the feed-stall delta dominates, else ``gossip`` /
    ``compute`` split by the rank's compile-round span ratio (an
    estimate — the steady-state jitted round is one program; marked
    ``_est`` accordingly)."""
    per_round: dict[int, list[dict[str, Any]]] = {}
    ratios: dict[Any, float] = {}
    for s in ranks:
        digest = s.get("span_digest") or {}
        spans = digest.get("spans") or {}
        gossip_us = (spans.get("gossip.round") or {}).get("total_us", 0.0)
        inner_us = (spans.get("train.inner_loop") or {}).get("total_us", 0.0)
        ratios[s.get("rank")] = (
            gossip_us / (gossip_us + inner_us)
            if gossip_us + inner_us > 0
            else None
        )
        for row in digest.get("rounds", []):
            rnd = row.get("round")
            if rnd is None:
                continue
            per_round.setdefault(int(rnd), []).append(
                {
                    "rank": s.get("rank"),
                    "dur_ms": round(row.get("dur_us", 0.0) / 1e3, 3),
                    "feed_ms": round(row.get("feed_us", 0.0) / 1e3, 3),
                    "fence_ms": round(row.get("fence_us", 0.0) / 1e3, 3),
                }
            )
    timeline: list[dict[str, Any]] = []
    for rnd in sorted(per_round)[-max_rounds:]:
        rows = sorted(per_round[rnd], key=lambda r: (r["rank"] is None, r["rank"]))
        slow = max(rows, key=lambda r: r["dur_ms"])
        fast = min(rows, key=lambda r: r["dur_ms"])
        entry: dict[str, Any] = {"round": rnd, "ranks": rows}
        if len(rows) > 1 and slow["dur_ms"] > fast["dur_ms"]:
            extra = slow["dur_ms"] - fast["dur_ms"]
            feed_delta = max(slow["feed_ms"] - fast["feed_ms"], 0.0)
            feed_delta = min(feed_delta, extra)
            rest = extra - feed_delta
            ratio = ratios.get(slow["rank"])
            gossip_est = rest * ratio if ratio is not None else None
            compute_est = rest - gossip_est if gossip_est is not None else None
            parts = {"feed": feed_delta}
            if gossip_est is not None:
                parts["gossip"] = gossip_est
                parts["compute"] = compute_est
            else:
                parts["step"] = rest  # no compile ratio: unattributed
            entry["straggler"] = {
                "rank": slow["rank"],
                "extra_ms": round(extra, 3),
                "feed_ms": round(feed_delta, 3),
                "gossip_ms_est": (
                    None if gossip_est is None else round(gossip_est, 3)
                ),
                "compute_ms_est": (
                    None if compute_est is None else round(compute_est, 3)
                ),
                "phase": max(parts, key=lambda k: parts[k]),
            }
        timeline.append(entry)
    return timeline


_COST_FAMILIES = {
    # labeled consensusml_cost_*/compile family -> attribution-row field
    "consensusml_cost_flops": "flops",
    "consensusml_cost_bytes_accessed": "bytes_accessed",
    "consensusml_cost_peak_bytes": "peak_bytes",
    "consensusml_compile_seconds": "compile_s",
    "consensusml_cost_expected_seconds": "expected_s",
    "consensusml_cost_measured_seconds": "measured_s",
    "consensusml_cost_floor_ratio": "floor_ratio",
}


def _attribution_section(snaps: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """Per-executable cost-ledger rows merged across ranks.

    The ledger's gauges are labeled ``executable=``; every rank lowers
    the same programs, so values merge with max (same convention as the
    replicated swarm counters). Rows come back sorted by expected cost,
    costliest first — the render order of obs_report's attribution
    table. Empty when no rank ran with a cost ledger.
    """
    rows: dict[str, dict[str, Any]] = {}
    for s in snaps:
        for key, vd in (s.get("metrics") or {}).items():
            name, labels = parse_metric_key(key)
            field = _COST_FAMILIES.get(name)
            if field is None or "executable" not in labels:
                continue
            f = _finite(vd)
            if f is None:
                continue
            row = rows.setdefault(
                labels["executable"], {"executable": labels["executable"]}
            )
            row[field] = max(row.get(field, float("-inf")), f)
    out = list(rows.values())
    out.sort(key=lambda r: -(r.get("expected_s") or 0.0))
    return out


def _alerts_section(snaps: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Fleet-wide alert view: every snapshot's firing alerts merged,
    deduplicated by (rule, series-with-labels) — the same breach seen
    from N ranks is ONE row naming all N — ordered worst-first (the
    alert engine's own ordering: severity, then longest-firing). None
    when no snapshot carries an alert plane (partial/old snapshots stay
    renderable)."""
    from consensusml_tpu.obs.alerts import worst_first_key

    rows: dict[tuple[str, str], dict[str, Any]] = {}
    reporting = 0
    events: list[dict[str, Any]] = []
    resolved_total = 0
    for s in snaps:
        al = s.get("alerts")
        if not isinstance(al, dict):
            continue
        reporting += 1
        resolved_total += len(al.get("resolved_recent") or [])
        who = f"{s.get('role') or 'rank'}-{s.get('rank')}"
        for ev in al.get("events_recent") or []:
            events.append(dict(ev, reporter=who))
        for a in al.get("firing") or []:
            key = (a.get("rule") or "", a.get("series") or "")
            row = rows.get(key)
            if row is None:
                row = rows[key] = dict(a, reporters=[])
            else:
                # keep the worst view of the shared breach: earliest
                # fire time, and the value on the bad side of the
                # rule's direction (min for "below" breaches)
                if (a.get("fired_s") or math.inf) < (
                    row.get("fired_s") or math.inf
                ):
                    row["fired_s"] = a.get("fired_s")
                    row["since_s"] = a.get("since_s")
                v, rv = a.get("value"), row.get("value")
                if v is not None and (
                    rv is None
                    or (v < rv if row.get("direction") == "below" else v > rv)
                ):
                    row["value"] = v
            row["reporters"].append(who)
    if not reporting:
        return None
    firing = sorted(rows.values(), key=worst_first_key)
    events.sort(key=lambda e: e.get("time_s") or 0.0)
    return {
        "ranks_reporting": reporting,
        "firing": firing,
        "firing_total": len(firing),
        "resolved_recent_total": resolved_total,
        "events_recent": events[-16:],
    }


def _history_section(snaps: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Per-series sparkline rows from every snapshot's history digest:
    one row per (series, role, rank), carrying the digest's derived
    points (gauge value / counter rate / histogram interval-p99) so the
    report can render client-vs-server trends side by side. None when
    no snapshot carries a digest."""
    rows: list[dict[str, Any]] = []
    reporting = 0
    for s in snaps:
        digest = s.get("history")
        if not isinstance(digest, dict):
            continue
        reporting += 1
        for row in digest.get("series") or []:
            if not isinstance(row, dict) or not row.get("series"):
                continue
            rows.append(
                {
                    "series": row["series"],
                    "kind": row.get("kind"),
                    "role": s.get("role"),
                    "rank": s.get("rank"),
                    "points": row.get("points") or [],
                    "last": row.get("last"),
                    "min": row.get("min"),
                    "max": row.get("max"),
                }
            )
    if not reporting:
        return None
    rows.sort(
        key=lambda r: (
            r["series"], str(r.get("role") or ""), r.get("rank") or 0
        )
    )
    return {
        "ranks_reporting": reporting,
        "series": rows,
        "series_total": len(rows),
    }


def _tenants_section(snaps: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Fleet-wide per-tenant spend: every snapshot's wide-event rollup
    merged by tenant — counters sum across ranks (each rank's events
    are its own requests, disjoint by construction), worst-TTFT
    exemplar lists merge and re-cap. None when no snapshot carries a
    wide-event section (pre-accounting snapshots keep aggregating)."""
    from consensusml_tpu.obs.events import WORST_TTFT_KEEP

    tenants: dict[str, dict[str, Any]] = {}
    reporting = 0
    events_total = 0
    for s in snaps:
        we = s.get("wide_events")
        if not isinstance(we, dict):
            continue
        reporting += 1
        events_total += int(we.get("emitted_total") or 0)
        for t, agg in (we.get("tenants") or {}).items():
            row = tenants.setdefault(t, {"worst_ttft": []})
            for k, v in agg.items():
                if k == "worst_ttft":
                    row["worst_ttft"].extend(v or [])
                elif isinstance(v, (int, float)):
                    row[k] = row.get(k, 0) + v
        for row in tenants.values():
            row["worst_ttft"] = sorted(
                row["worst_ttft"], key=lambda r: -(r.get("ttft_s") or 0.0)
            )[:WORST_TTFT_KEEP]
    if not reporting:
        return None
    return {
        "ranks_reporting": reporting,
        "events_total": events_total,
        "tenants": tenants,
    }


def _fleet_section(snaps: list[dict[str, Any]]) -> dict[str, Any] | None:
    """The fleet plane: router/controller state written as a ``fleet``
    snapshot extra (``tools/fleetctl.py --obs-snapshot``, the fleet
    bench). Routers are disjoint front-ends, so their stream counters
    SUM across snapshots; the per-replica table and canary state merge
    last-writer-wins by replica name. None when no snapshot carries a
    fleet section (non-fleet directories keep aggregating)."""
    routers = 0
    counts: dict[str, float] = {}
    replicas: dict[str, dict[str, Any]] = {}
    canary = None
    events: list[dict] = []
    for s in snaps:
        fl = s.get("fleet")
        if not isinstance(fl, dict):
            continue
        routers += 1
        for k, v in (fl.get("router") or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                counts[k] = counts.get(k, 0) + v
            elif k not in counts:
                counts[k] = v
        for name, row in (fl.get("replicas") or {}).items():
            replicas[name] = row
        if isinstance(fl.get("canary"), dict):
            canary = fl["canary"]
        events.extend(fl.get("events") or [])
    if not routers:
        return None
    return {
        "routers_reporting": routers,
        "router": counts,
        "replicas": replicas,
        "canary": canary,
        "events": sorted(events, key=lambda e: e.get("time_s", 0.0))[-32:],
    }


def _hbm_section(snaps: list[dict[str, Any]]) -> dict[str, Any] | None:
    """The three-way HBM reconciliation gauges (obs/memviz.py), worst
    rank per side — plus per-pair drift. None when no rank reconciled."""
    sides = {
        "analytic_bytes": "consensusml_hbm_analytic_bytes",
        "compiled_bytes": "consensusml_hbm_compiled_bytes",
        "live_peak_bytes": "consensusml_hbm_live_peak_bytes",
        "live_bytes": "consensusml_hbm_live_bytes",
    }
    doc: dict[str, Any] = {}
    drift: dict[str, float] = {}
    for s in snaps:
        for field, fam in sides.items():
            f = _finite(_metric(s, fam))
            if f is not None:
                doc[field] = max(doc.get(field, float("-inf")), f)
        for key, vd in (s.get("metrics") or {}).items():
            name, labels = parse_metric_key(key)
            if name == "consensusml_hbm_drift_pct" and "pair" in labels:
                f = _finite(vd)
                if f is not None:
                    pair = labels["pair"]
                    # keep the worst-magnitude drift across ranks
                    if abs(f) >= abs(drift.get(pair, 0.0)):
                        drift[pair] = f
    if not doc and not drift:
        return None
    doc["drift_pct"] = drift
    return doc


def aggregate(
    cluster_dir: str,
    *,
    now: float | None = None,
    straggler_age_s: float = 120.0,
    straggler_round_lag: int = 3,
    top_links: int = 16,
) -> dict[str, Any]:
    """Merge a cluster directory into one report document.

    ``now`` is injectable so tests (and replays of an old directory)
    get deterministic heartbeat ages. The report is plain JSON-able
    data; ``tools/obs_report.py`` renders it.
    """
    now = time.time() if now is None else now
    snaps = read_snapshots(cluster_dir)
    errors = [s for s in snaps if "_error" in s]
    ranks = [s for s in snaps if "_error" not in s and s.get("role") == "rank"]
    others = [
        s for s in snaps if "_error" not in s and s.get("role") != "rank"
    ]

    # ---- per-rank rows ---------------------------------------------------
    rank_rows: list[dict[str, Any]] = []
    link_hists: dict[tuple[str, str], dict] = {}
    link_wire: dict[tuple[str, str], float] = {}
    link_traced: dict[tuple[str, str], float] = {}
    for s in ranks:
        lat = _metric(s, "consensusml_round_latency_seconds")
        row = {
            "rank": s.get("rank"),
            "file": s.get("_file"),
            "round": s.get("round"),
            "heartbeat_age_s": _age_s(s, now),
            "rounds_total": _metric(s, "consensusml_rounds_total", 0.0),
            "wire_bytes_total": _metric(s, "consensusml_wire_bytes_total", 0.0),
            "round_latency": (
                hist_stats(lat) if isinstance(lat, dict) else None
            ),
            "consensus_distance": _finite(
                _metric(s, "consensusml_consensus_distance")
            ),
            "alive_frac": _finite(_metric(s, "consensusml_alive_frac")),
            "health": {
                "decay_measured": _finite(
                    _metric(s, "consensusml_health_decay_measured")
                ),
                "decay_bound": _finite(
                    _metric(s, "consensusml_health_decay_bound")
                ),
                "bound_violation": _finite(
                    _metric(s, "consensusml_health_bound_violation")
                ),
                "anomalies_total": _metric(
                    s, "consensusml_health_anomalies_total", 0.0
                ),
            },
        }
        rank_rows.append(row)
        # merge every rank's per-edge families (a rank sees its own
        # probes; in single-controller runs rank 0 sees every edge)
        for key, vd in (s.get("metrics") or {}).items():
            name, labels = parse_metric_key(key)
            if "src" not in labels or "dst" not in labels:
                continue
            edge = (labels["src"], labels["dst"])
            if name == "consensusml_link_latency_seconds" and isinstance(
                vd, dict
            ):
                link_hists[edge] = _merge_hist(link_hists.get(edge), vd)
            elif name in (
                "consensusml_link_wire_bytes_per_round",
                "consensusml_link_wire_bytes_traced_total",
            ):
                f = _finite(vd)
                if f is not None:
                    # max, not sum: every process traces/records the same
                    # full edge set, so summing would multiply by ranks.
                    # The two families stay SEPARATE report fields: the
                    # gauge is the engine's per-round accounting, the
                    # traced counter ACCUMULATES per compile (a retrace
                    # doubles it) and must never masquerade as bytes/round
                    tgt = (
                        link_wire
                        if name == "consensusml_link_wire_bytes_per_round"
                        else link_traced
                    )
                    tgt[edge] = max(tgt.get(edge, 0.0), f)

    # ---- skew ------------------------------------------------------------
    rounds = [r["round"] for r in rank_rows if r["round"] is not None]
    lat_means = [
        r["round_latency"]["mean"]
        for r in rank_rows
        if r["round_latency"] and r["round_latency"]["count"]
    ]
    skew = {
        "ranks": len(rank_rows),
        "round_min": min(rounds) if rounds else None,
        "round_max": max(rounds) if rounds else None,
        "round_lag": (max(rounds) - min(rounds)) if rounds else None,
        "round_latency_mean_min_s": min(lat_means) if lat_means else None,
        "round_latency_mean_max_s": max(lat_means) if lat_means else None,
        "round_latency_skew": (
            max(lat_means) / min(lat_means)
            if lat_means and min(lat_means) > 0
            else None
        ),
    }

    # ---- slowest links ---------------------------------------------------
    links = []

    def link_row(src: str, dst: str, st: dict | None) -> dict[str, Any]:
        return {
            "src": int(src),
            "dst": int(dst),
            "probes": st["count"] if st else 0,
            "mean_latency_s": st["mean"] if st else None,
            "p99_latency_s": st["p99"] if st else None,
            "wire_bytes_per_round": link_wire.get((src, dst)),
            "wire_bytes_traced_total": link_traced.get((src, dst)),
        }

    for (src, dst), vd in link_hists.items():
        links.append(link_row(src, dst, hist_stats(vd)))
    links.sort(key=lambda r: -(r["mean_latency_s"] or 0.0))
    # edges with wire accounting but no probes still belong in the map
    probed = {(r["src"], r["dst"]) for r in links}
    for src, dst in sorted(set(link_wire) | set(link_traced)):
        if (int(src), int(dst)) not in probed:
            links.append(link_row(src, dst, None))

    # ---- stragglers / churn ---------------------------------------------
    max_round = skew["round_max"]
    stragglers = []
    for r in rank_rows:
        reasons = []
        if r["heartbeat_age_s"] > straggler_age_s:
            reasons.append(f"heartbeat stale {r['heartbeat_age_s']:.0f}s")
        if (
            max_round is not None
            and r["round"] is not None
            and max_round - r["round"] >= straggler_round_lag
        ):
            reasons.append(f"{max_round - r['round']} rounds behind")
        if reasons:
            stragglers.append({"rank": r["rank"], "reasons": reasons})
    churn = {
        "elastic_resizes_total": sum(
            _metric(s, "consensusml_elastic_resizes_total", 0.0) for s in ranks
        ),
        "joined_workers_total": sum(
            _metric(s, "consensusml_elastic_joined_workers_total", 0.0)
            for s in ranks
        ),
        "fault_rounds_total": sum(
            _metric(s, "consensusml_fault_rounds_total", 0.0) for s in ranks
        ),
        "worker_drops_total": sum(
            _metric(s, "consensusml_worker_drops_total", 0.0) for s in ranks
        ),
        "watchdog_timeouts_total": sum(
            _metric(s, "consensusml_watchdog_timeouts_total", 0.0)
            for s in ranks
        ),
        # swarm counters are REPLICATED, not per-rank: every rank's
        # controller replays the same schedule (same reason the event
        # timeline below dedups), so merge with max, not sum
        "bootstrapped_joiners_total": max(
            (
                _metric(s, "consensusml_swarm_bootstrapped_joiners_total", 0.0)
                for s in ranks
            ),
            default=0.0,
        ),
        "recovery_rounds_total": max(
            (
                _metric(s, "consensusml_swarm_recovery_rounds_total", 0.0)
                for s in ranks
            ),
            default=0.0,
        ),
    }

    # ---- membership (swarm) ---------------------------------------------
    # per-kind event counters (labeled family) + the merged event timeline
    # the ClusterWriter snapshots carry — what obs_report renders as the
    # join/drop/straggler-vs-round view
    event_counts: dict[str, float] = {}
    timeline: list[dict[str, Any]] = []
    seen_events = set()
    swarm_epoch = None
    swarm_members = None
    for s in ranks:
        for key, vd in (s.get("metrics") or {}).items():
            name, labels = parse_metric_key(key)
            if name == "consensusml_swarm_events_total" and "kind" in labels:
                f = _finite(vd)
                if f is not None:
                    # replicated across ranks (same schedule) — max, like
                    # the timeline dedup below, not a rank-count inflation
                    k = labels["kind"]
                    event_counts[k] = max(event_counts.get(k, 0.0), f)
        e = _finite(_metric(s, "consensusml_swarm_epoch"))
        if e is not None:
            swarm_epoch = max(swarm_epoch or 0, e)
        m = _finite(_metric(s, "consensusml_swarm_members"))
        if m is not None:
            swarm_members = m if swarm_members is None else max(swarm_members, m)
        for row in s.get("swarm_events", []):
            key = (
                row.get("round"), row.get("kind"),
                tuple(row.get("workers") or ()),
            )
            if key in seen_events:  # every rank replays the same schedule
                continue
            seen_events.add(key)
            timeline.append(dict(row, rank=s.get("rank")))
    timeline.sort(key=lambda r: (r.get("round") or 0, r.get("kind") or ""))
    membership = {
        "epoch": swarm_epoch,
        "active_members": swarm_members,
        "event_counts": event_counts,
        "timeline": timeline,
    }

    # ---- cluster-level health -------------------------------------------
    measured = [
        r["health"]["decay_measured"]
        for r in rank_rows
        if r["health"]["decay_measured"] is not None
    ]
    bounds = [
        r["health"]["decay_bound"]
        for r in rank_rows
        if r["health"]["decay_bound"] is not None
    ]
    health = {
        "decay_bound": bounds[0] if bounds else None,
        "decay_measured_worst": max(measured) if measured else None,
        "ranks_in_violation": sum(
            1 for r in rank_rows if (r["health"]["bound_violation"] or 0) > 0
        ),
        "anomalies_total": sum(
            r["health"]["anomalies_total"] or 0 for r in rank_rows
        ),
    }

    # ---- flight-recorder index ------------------------------------------
    flightrecs = []
    for path in sorted(
        glob.glob(os.path.join(cluster_dir, "**", "flightrec-*.json"),
                  recursive=True)
    ):
        st = os.stat(path)
        flightrecs.append(
            {
                "file": os.path.relpath(path, cluster_dir),
                "bytes": st.st_size,
                "mtime_s": st.st_mtime,
            }
        )

    # ---- non-rank roles (loadgen etc.) ----------------------------------
    other_rows = []
    for s in others:
        row = {
            "role": s.get("role"),
            "rank": s.get("rank"),
            "file": s.get("_file"),
            "heartbeat_age_s": _age_s(s, now),
            "metrics": {},
        }
        for key, vd in (s.get("metrics") or {}).items():
            if isinstance(vd, dict):
                row["metrics"][key] = hist_stats(vd)
            else:
                f = _finite(vd)
                if f is not None:
                    row["metrics"][key] = f
        other_rows.append(row)

    return {
        "time_s": now,
        "cluster_dir": os.path.abspath(cluster_dir),
        "skew": skew,
        "ranks": rank_rows,
        "links": links[: max(top_links, 0)] if top_links else links,
        "links_total": len(links),
        "health": health,
        "stragglers": stragglers,
        "churn": churn,
        "membership": membership,
        # the request plane: slowest-request exemplar table resolved
        # against the merged trace index (docs/observability.md
        # "Request tracing")
        "requests": _requests_section(ranks + others),
        # cross-rank per-round phase rows from the span digests
        "round_timeline": _round_timeline(ranks),
        # the cost plane: per-executable compiled cost/attribution rows
        # + the three-way HBM reconciliation (docs/observability.md
        # "Cost attribution"; empty/None without --cost-ledger)
        "attribution": _attribution_section(ranks + others),
        "hbm": _hbm_section(ranks + others),
        # the alert plane: fleet-wide firing alerts deduped by
        # (rule, series), worst-first, with per-series history
        # sparkline rows (docs/observability.md "Alerting & history");
        # None when no snapshot carries the sections — partial or
        # pre-alert-plane snapshots keep aggregating
        "alerts": _alerts_section(ranks + others),
        "history": _history_section(ranks + others),
        # the wide-event plane: fleet-wide per-tenant spend merged from
        # each snapshot's rollup (docs/observability.md "Wide events &
        # tenant accounting"); None when no snapshot carries one
        "tenants": _tenants_section(ranks + others),
        # the fleet plane: router stream accounting + replica table +
        # canary state from fleetctl/bench snapshots (docs/fleet.md);
        # None when no snapshot carries a fleet extra
        "fleet": _fleet_section(ranks + others),
        "flight_recorders": flightrecs,
        "clients": other_rows,
        "errors": errors,
    }
