"""Metrics registry: counters, gauges, fixed-bucket histograms.

The per-round hot path does DICT-CHEAP work only — a counter increment is
one float add under a lock, a histogram observe is a bisect into fixed
buckets. Exporters are pull-style and pay their cost at export time:

- :meth:`MetricsRegistry.to_prometheus` — Prometheus text exposition
  format, written atomically by :meth:`write_prometheus` (the standard
  node-exporter *textfile collector* pattern: point
  ``--collector.textfile.directory`` at the file's directory and the
  metrics scrape like any other target).
- :meth:`MetricsRegistry.snapshot` / :meth:`write_jsonl_snapshot` — one
  JSON object of current values; the registry also retains the last
  ``snapshot_keep`` snapshots in a ring for the flight recorder.

Metric names follow Prometheus conventions (``consensusml_`` prefix,
``_total`` on counters, base units — see docs/observability.md for the
full schema).
"""

from __future__ import annotations

import bisect
import json
import math
import os
import threading
import time
from collections import deque
from typing import Any, Iterable

from consensusml_tpu.analysis import guarded_by

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "parse_metric_key",
    "DEFAULT_LATENCY_BUCKETS",
    "DEFAULT_LINK_LATENCY_BUCKETS",
    "DEFAULT_ROUND_COUNT_BUCKETS",
    "DEFAULT_SLO_BUCKETS",
]

# round latencies span ~1 ms (smoke MLP on CPU) to minutes (first-round
# XLA compile); log-spaced like prometheus defaults but wider
DEFAULT_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

# link probes resolve ICI/DCN one-hop transfers: microseconds on-chip,
# milliseconds cross-slice, seconds only when something is wrong
DEFAULT_LINK_LATENCY_BUCKETS = (
    1e-6, 2.5e-6, 5e-6, 1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
)

# serving SLOs (TTFT, inter-token gaps, per-stage serving latencies):
# decode steps run sub-millisecond on real chips, so the request-path
# families need resolution DEFAULT_LATENCY_BUCKETS does not have below
# 1 ms; the top stays low — a 30 s serving latency is already an outage
DEFAULT_SLO_BUCKETS = (
    1e-4, 2.5e-4, 5e-4, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)

# small-integer round counts (gossip-bootstrap length, recovery windows):
# the spectral-gap-derived K lands between a handful and a few dozen
DEFAULT_ROUND_COUNT_BUCKETS = (
    1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0, 48.0, 64.0,
)

_VALID_FIRST = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ_:")


def _labelstr(labels: dict[str, Any] | None) -> str:
    """Canonical Prometheus label rendering: sorted keys, quoted values.
    Empty/None labels render as "" so unlabeled metrics keep their bare
    names everywhere (exposition, snapshots, registry keys)."""
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        if not k or k[0] not in _VALID_FIRST:
            raise ValueError(f"bad label name {k!r}")
        v = str(labels[k]).replace("\\", "\\\\").replace('"', '\\"')
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


_LABEL_RE = None  # compiled lazily; module import stays regex-free


def parse_metric_key(key: str) -> tuple[str, dict[str, str]]:
    """Invert :func:`_labelstr`: ``'m{src="0",dst="1"}'`` ->
    ``("m", {"src": "0", "dst": "1"})``. Snapshot consumers (the cluster
    aggregator) use this to merge labeled families across ranks.
    Quote-aware: commas/equals INSIDE a quoted value survive the
    round-trip (a bare split would shred them into garbage labels)."""
    if "{" not in key:
        return key, {}
    global _LABEL_RE
    if _LABEL_RE is None:
        import re

        # name="value" with \" and \\ escapes inside the quotes
        _LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
    name, _, rest = key.partition("{")
    labels: dict[str, str] = {}
    for k, v in _LABEL_RE.findall(rest.rstrip("}")):
        labels[k] = v.replace('\\"', '"').replace("\\\\", "\\")
    return name, labels


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "", labels: dict | None = None):
        if not name or name[0] not in _VALID_FIRST:
            raise ValueError(f"bad metric name {name!r}")
        self.name = name
        self.help = help
        self.labels = dict(labels) if labels else {}
        # full identity: family name + canonical label rendering — what
        # exposition lines, snapshot keys, and the registry key on
        self.key = name + _labelstr(self.labels)
        # RLock, not Lock: the flight recorder's SIGTERM handler runs ON
        # the main thread and dumps the registry — with a plain lock a
        # signal landing inside a metric's critical section would
        # deadlock the handler against the very frame it interrupted
        self._lock = threading.RLock()

    def _line_name(self, suffix: str = "", extra: dict | None = None) -> str:
        """Exposition-line name: ``name<suffix>{labels...}`` with ``extra``
        labels (a histogram's ``le``) merged after the metric's own."""
        if extra:
            merged = dict(self.labels)
            merged.update(extra)
            return f"{self.name}{suffix}{_labelstr(merged)}"
        return f"{self.name}{suffix}{_labelstr(self.labels)}"

    def expose(self) -> list[str]:
        raise NotImplementedError

    def value_dict(self) -> Any:
        raise NotImplementedError


@guarded_by("_lock", "_value")
class Counter(_Metric):
    """Monotonically increasing float (Prometheus ``counter``).

    Updated from the train loop, the prefetch/native producer threads
    and the flight recorder's dump path concurrently — every ``_value``
    access (reads included: a torn read exports garbage to a scraper)
    holds the metric lock, enforced by the cml-check lock pass.
    """

    kind = "counter"

    def __init__(self, name: str, help: str = "", labels: dict | None = None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def expose(self) -> list[str]:
        with self._lock:
            return [f"{self._line_name()} {_fmt(self._value)}"]

    def value_dict(self) -> float:
        with self._lock:
            return self._value


@guarded_by("_lock", "_value")
class Gauge(_Metric):
    """Point-in-time float (Prometheus ``gauge``)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "", labels: dict | None = None):
        super().__init__(name, help, labels)
        self._value = math.nan

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def add(self, amount: float) -> None:
        with self._lock:
            self._value = (0.0 if math.isnan(self._value) else self._value) + amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def expose(self) -> list[str]:
        with self._lock:
            return [f"{self._line_name()} {_fmt(self._value)}"]

    def value_dict(self) -> float:
        with self._lock:
            return self._value


EXEMPLAR_KEEP = 8  # worst observations retained per histogram


@guarded_by("_lock", "_counts", "_sum", "_count", "_exemplars")
class Histogram(_Metric):
    """Fixed-bucket cumulative histogram (Prometheus ``histogram``).

    Buckets are chosen at registration and never reallocated — an
    ``observe`` is a bisect + two adds, cheap enough for every round.
    Exporters snapshot counts/sum/count under the same lock the
    observers hold: an unlocked export could emit a cumulative bucket
    row that disagrees with ``_sum`` (torn between two observes), which
    Prometheus rate math turns into negative latencies.

    ``observe(v, exemplar="req-...")`` makes the histogram
    EXEMPLAR-BEARING: the ``EXEMPLAR_KEEP`` worst (largest) exemplared
    observations are retained with their ids, so a p99 spike in an SLO
    family resolves to the concrete request ids that caused it (the
    ``value_dict``/snapshot side carries them; the Prometheus text
    exposition stays plain-format — exemplars are an OpenMetrics
    extension the textfile collector does not parse). Semantics in
    docs/observability.md "Request tracing".
    """

    kind = "histogram"

    def __init__(
        self, name: str, help: str = "",
        buckets: Iterable[float] = DEFAULT_LATENCY_BUCKETS,
        labels: dict | None = None,
    ):
        super().__init__(name, help, labels)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError(f"histogram {name} needs at least one bucket")
        self.buckets = tuple(bs)
        self._counts = [0] * (len(bs) + 1)  # last slot = +Inf
        self._sum = 0.0
        self._count = 0
        # (value, exemplar_id, unix_time) sorted worst-first, len<=KEEP
        self._exemplars: list[tuple[float, str, float]] = []

    def observe(self, value: float, exemplar: str | None = None) -> None:
        v = float(value)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if exemplar is not None:
                ex = self._exemplars
                if len(ex) < EXEMPLAR_KEEP or v > ex[-1][0]:
                    ex.append((v, str(exemplar), time.time()))
                    ex.sort(key=lambda t: -t[0])
                    del ex[EXEMPLAR_KEEP:]

    def exemplars(self) -> list[dict[str, Any]]:
        """Worst-first retained exemplars (``value``/``id``/``time_s``)."""
        with self._lock:
            ex = list(self._exemplars)
        return [
            {"value": v, "id": rid, "time_s": ts} for v, rid, ts in ex
        ]

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def _snapshot(self) -> tuple[list[int], float, int]:
        with self._lock:
            return list(self._counts), self._sum, self._count

    def raw(self) -> tuple[tuple[float, ...], tuple[int, ...], float, int]:
        """``(bucket_edges, per-bucket counts incl. the +Inf slot, sum,
        count)`` as one consistent locked read — the numeric form the
        history ring samples (``value_dict`` renders edges as strings
        for JSON; delta math wants floats)."""
        counts, total, n = self._snapshot()
        return self.buckets, tuple(counts), total, n

    def expose(self) -> list[str]:
        counts, total, n = self._snapshot()
        lines = []
        cum = 0
        for le, c in zip(self.buckets, counts):
            cum += c
            lines.append(
                f'{self._line_name("_bucket", {"le": _fmt(le)})} {cum}'
            )
        cum += counts[-1]
        lines.append(f'{self._line_name("_bucket", {"le": "+Inf"})} {cum}')
        lines.append(f'{self._line_name("_sum")} {_fmt(total)}')
        lines.append(f'{self._line_name("_count")} {n}')
        return lines

    def value_dict(self) -> dict[str, Any]:
        counts, total, n = self._snapshot()
        out = {
            "count": n,
            "sum": total,
            "buckets": {
                _fmt(le): c for le, c in zip(self.buckets, counts)
            },
            "inf": counts[-1],
        }
        ex = self.exemplars()
        if ex:
            out["exemplars"] = ex
        return out


def _fmt(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


@guarded_by("_lock", "_metrics", "_snapshots", "_family_kinds")
class MetricsRegistry:
    """Get-or-create metric registry with Prometheus / JSONL exporters.

    Written from the prefetch thread (feed metrics), the train loop
    (round metrics) and the flight recorder's crash-dump path (which
    snapshots mid-signal) — registry structures only move under
    ``_lock``; individual metric values ride each metric's own lock.

    Metrics may carry Prometheus LABELS (``labels={"src": "0", ...}``):
    each label combination is its own child metric (own lock, own
    values), the family name keeps ONE kind across all children, and
    exposition/snapshots key children as ``name{k="v",...}`` (see
    :func:`parse_metric_key` for the inverse — the cluster aggregator's
    merge path).
    """

    def __init__(self, snapshot_keep: int = 64):
        self._metrics: dict[str, _Metric] = {}  # key (name+labels) -> metric
        self._family_kinds: dict[str, str] = {}  # family name -> kind
        # RLock for the same signal-reentrancy reason as _Metric._lock
        self._lock = threading.RLock()
        self._snapshots: deque[dict[str, Any]] = deque(maxlen=snapshot_keep)

    def _get(
        self, cls, name: str, help: str, labels: dict | None = None, **kwargs
    ) -> _Metric:
        key = name + _labelstr(labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                kind = self._family_kinds.get(name)
                if kind is not None and kind != cls.kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {kind}, "
                        f"requested {cls.kind}"
                    )
                m = cls(name, help, labels=labels, **kwargs)
                self._metrics[key] = m
                self._family_kinds[name] = cls.kind
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}"
                )
            return m

    def counter(
        self, name: str, help: str = "", labels: dict | None = None
    ) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(
        self, name: str, help: str = "", labels: dict | None = None
    ) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(
        self, name: str, help: str = "",
        buckets: Iterable[float] = DEFAULT_LATENCY_BUCKETS,
        labels: dict | None = None,
    ) -> Histogram:
        return self._get(Histogram, name, help, labels, buckets=buckets)

    def metrics(self) -> list[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    # -- Prometheus exporter ----------------------------------------------
    def to_prometheus(self) -> str:
        lines: list[str] = []
        last_family = None
        # sort by (family, labels): one HELP/TYPE header per family, its
        # labeled children grouped under it
        ms = sorted(self.metrics(), key=lambda m: (m.name, m.key))
        helps: dict[str, str] = {}
        for m in ms:  # any child may carry the family help string
            if m.help and m.name not in helps:
                helps[m.name] = m.help
        for m in ms:
            if m.name != last_family:
                if m.name in helps:
                    lines.append(f"# HELP {m.name} {helps[m.name]}")
                lines.append(f"# TYPE {m.name} {m.kind}")
                last_family = m.name
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"

    def write_prometheus(self, path: str) -> str:
        """Atomic textfile write (tmp + rename): a scraper never reads a
        torn file, which is the textfile-collector contract."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.to_prometheus())
        os.replace(tmp, path)
        return path

    # -- JSONL / snapshot sink --------------------------------------------
    def snapshot(self, extra: dict[str, Any] | None = None) -> dict[str, Any]:
        """Current values as one JSON-able dict; retained in the
        last-K ring the flight recorder dumps."""
        snap: dict[str, Any] = {"time_s": time.time()}
        if extra:
            snap.update(extra)
        snap["metrics"] = {m.key: m.value_dict() for m in self.metrics()}
        with self._lock:
            self._snapshots.append(snap)
        return snap

    def snapshots(self) -> list[dict[str, Any]]:
        # list(deque) while another thread appends raises "deque mutated
        # during iteration" — exactly the flight-recorder-dump-during-
        # telemetry-snapshot race
        with self._lock:
            return list(self._snapshots)

    def write_jsonl_snapshot(
        self, fileobj, extra: dict[str, Any] | None = None
    ) -> dict[str, Any]:
        snap = self.snapshot(extra)
        fileobj.write(json.dumps(snap) + "\n")
        fileobj.flush()
        return snap


_GLOBAL = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry the instrumented hot paths feed."""
    return _GLOBAL
