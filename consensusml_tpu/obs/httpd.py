"""Live ``/metrics`` endpoint: scrape the registry over HTTP.

The textfile collector is pull-at-cadence — the file is only as fresh as
the last ``--telemetry-every`` rewrite, and a serving process with no
training loop has no natural rewrite cadence at all. This stdlib
``ThreadingHTTPServer`` serves the SAME locked ``expose()`` path the
textfile writer uses, freshly rendered per GET, so a Prometheus scraper
(or a human with curl) sees live values:

- ``GET /metrics``  — Prometheus text exposition (``to_prometheus()``);
- ``GET /traces``   — the merged Chrome trace JSON (span ring + request
  lanes, Perfetto-loadable — the live twin of ``--trace-events``);
- ``GET /requests`` — the request-trace registry snapshot JSON
  (in-flight + recent completed, docs/observability.md "Request
  tracing").

Surfaces: ``train.py --metrics-port N`` and
``ServeServer(metrics_port=N)`` (``0`` picks a free port; read it back
from :attr:`MetricsServer.port`). Render cost is paid by the scraper's
thread — the train/serve hot paths only ever touch the per-metric locks
they already hold for a few µs per update.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from consensusml_tpu.obs.metrics import MetricsRegistry, get_registry
from consensusml_tpu.obs.requests import (
    RequestTraceRegistry,
    get_request_registry,
    merged_chrome_trace,
)
from consensusml_tpu.obs.tracer import SpanTracer, get_tracer

__all__ = ["MetricsServer"]


class MetricsServer:
    """Threaded HTTP exporter over the process's observability state."""

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        registry: MetricsRegistry | None = None,
        tracer: SpanTracer | None = None,
        requests: RequestTraceRegistry | None = None,
    ):
        registry = registry if registry is not None else get_registry()
        tracer = tracer if tracer is not None else get_tracer()
        requests = requests if requests is not None else get_request_registry()

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 - stdlib API name
                path = self.path.split("?", 1)[0]
                if path in ("/metrics", "/"):
                    body = registry.to_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/traces":
                    body = json.dumps(
                        merged_chrome_trace(tracer, requests)
                    ).encode()
                    ctype = "application/json"
                elif path == "/requests":
                    body = json.dumps(requests.snapshot()).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404, "try /metrics, /traces, /requests")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:
                pass  # scrapes are not log lines

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.address: tuple[str, int] = self._httpd.server_address[:2]
        self.port: int = self.address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="obs-metrics-http",
            daemon=True,
        )
        self._thread.start()

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.address[0]}:{self.port}{path}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
