"""Live HTTP plane: scrape metrics, traces — and capture profiles.

The textfile collector is pull-at-cadence — the file is only as fresh as
the last ``--telemetry-every`` rewrite, and a serving process with no
training loop has no natural rewrite cadence at all. This stdlib
``ThreadingHTTPServer`` serves the SAME locked ``expose()`` path the
textfile writer uses, freshly rendered per GET, so a Prometheus scraper
(or a human with curl) sees live values:

- ``GET /metrics``  — Prometheus text exposition (``to_prometheus()``);
- ``GET /traces``   — the merged Chrome trace JSON (span ring + request
  lanes, Perfetto-loadable — the live twin of ``--trace-events``);
- ``GET /requests`` — the request-trace registry snapshot JSON
  (in-flight + recent completed, docs/observability.md "Request
  tracing");
- ``GET /alerts``   — the alert engine's snapshot (firing worst-first,
  recent resolutions, plane events — docs/observability.md "Alerting &
  history");
- ``GET /query?series=NAME[&window=S][&n=N]`` — one history series:
  derived points (rate / p99-per-interval / raw gauge), windowed stats;
- ``GET /healthz``  — the readiness probe a fleet router polls: process
  up, last history-tick age, firing-alert count. Returns **503** when
  this server OWNS the tick cadence (``tick_s > 0``) and ticks stopped
  landing — a wedged serving process stops being routable;
- ``GET /events?n=N&tenant=T`` — the newest wide events from the
  request-accounting log, optionally filtered to one tenant
  (docs/observability.md "Wide events & tenant accounting");
- ``GET /tenants`` — the per-tenant rollup: requests, tokens, joined
  TFLOPs, HBM gigabytes, block-seconds, worst-TTFT exemplars. Both
  PEEK the global log (wired ``events=`` wins): a scrape must never
  create one, so an un-armed process answers with an empty doc;
- ``GET /profile?ms=N`` — an ON-DEMAND ``jax.profiler`` capture of the
  next N milliseconds of whatever this process is doing (a live train
  loop, a serving engine mid-traffic) — no restart, no ``--profile-dir``
  pre-arrangement. The response links the dump through
  ``tools/xprof_summary.py``'s machine-readable summary when the tool
  is importable, and always carries the ``*.trace.json.gz`` path so a
  caller can run ``xprof_summary --json`` itself (docs/perf.md "Live
  profiling").

``/profile`` is SINGLE-FLIGHT: ``jax.profiler`` supports one session
per process, so a second request while a capture runs gets **409** with
the in-flight capture id instead of a corrupted double-start — never
two overlapping profiler sessions. Capture directories rotate under a
bounded quota (oldest deleted), so a scraper polling ``/profile`` by
accident cannot fill the disk.

Surfaces: ``train.py --metrics-port N`` and
``ServeServer(metrics_port=N)`` (``0`` picks a free port; read it back
from :attr:`MetricsServer.port`). Render cost is paid by the scraper's
thread — the train/serve hot paths only ever touch the per-metric locks
they already hold for a few µs per update.

History/alert state is OPT-IN wiring (``history=``/``alerts=``): the
train loop drives ``record()``/``evaluate()`` from its own telemetry
tick and passes the engines in for surfacing only; a serving process
has no loop to ride, so ``tick_s > 0`` starts the ``obs-ticker``
daemon thread (docs/threads.md) that drives them at cadence —
``ServeServer(metrics_port=...)`` does exactly that.
"""

from __future__ import annotations

import glob
import json
import math
import os
import shutil
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from consensusml_tpu.analysis import guarded_by
from consensusml_tpu.obs.metrics import MetricsRegistry, get_registry
from consensusml_tpu.obs.requests import (
    RequestTraceRegistry,
    get_request_registry,
    merged_chrome_trace,
)
from consensusml_tpu.obs.tracer import SpanTracer, get_tracer

__all__ = ["MetricsServer"]

PROFILE_MAX_MS = 30_000  # one capture may stall a scraper thread this long
PROFILE_DEFAULT_MS = 500


def _jsonsafe(doc):
    """Non-finite floats -> null: ``json.dumps`` would emit bare
    ``NaN``/``Infinity``, which strict JSON parsers (a Go router polling
    /healthz, jq) reject. Applied to the alert/history endpoint docs,
    whose empty-window stats are NaN by construction."""
    if isinstance(doc, float) and not math.isfinite(doc):
        return None
    if isinstance(doc, dict):
        return {k: _jsonsafe(v) for k, v in doc.items()}
    if isinstance(doc, (list, tuple)):
        return [_jsonsafe(v) for v in doc]
    return doc


def _xprof_summary_json(trace_json: str) -> dict | None:
    """Machine-readable op-family summary via tools/xprof_summary.py
    (shared by-path loader: obs.memviz.load_tool). None when the tool
    is absent (installed package without the repo) or the parse fails;
    the caller still gets the raw trace path either way."""
    from consensusml_tpu.obs.memviz import load_tool

    try:
        mod = load_tool("xprof_summary")
        if mod is None:
            return None
        return mod.summarize(trace_json)
    except Exception:
        return None


@guarded_by("_lock", "_profile_inflight", "_profile_seq")
class MetricsServer:
    """Threaded HTTP exporter over the process's observability state.

    ``/profile`` single-flight state is a flag under a plain ``with``
    lock, NOT a held-across-the-capture lock: scraper handler threads
    race only on the few-instruction check-and-set, and the 409 loser
    reads the winner's capture id under the same lock it was written
    (the old bare try-``acquire``/``release`` pair additionally read
    ``_profile_inflight`` unlocked — fixed by cml-check's
    ``locks:bare-acquire`` rule landing, see docs/static_analysis.md).
    """

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        registry: MetricsRegistry | None = None,
        tracer: SpanTracer | None = None,
        requests: RequestTraceRegistry | None = None,
        profile_dir: str | None = None,
        profile_quota: int = 4,
        history=None,
        alerts=None,
        tick_s: float = 0.0,
        events=None,
        ready_fn=None,
    ):
        registry = registry if registry is not None else get_registry()
        tracer = tracer if tracer is not None else get_tracer()
        requests = requests if requests is not None else get_request_registry()
        # MetricsHistory / AlertEngine (obs.history / obs.alerts): when
        # wired, /alerts and /query go live and /healthz reports tick
        # freshness; tick_s > 0 additionally makes THIS server drive
        # record()/evaluate() on the obs-ticker thread
        self.history = history
        self.alerts = alerts
        # WideEventLog (obs.events): /events and /tenants surface it;
        # None means peek-at-request-time — the serving engine arms the
        # global log, a scrape never creates one
        self.events = events
        self.tick_s = float(tick_s)
        # readiness hook: a zero-arg callable (e.g. ``lambda:
        # engine.warmed``) consulted by /healthz — False turns the probe
        # 503 so a fleet router places zero new streams here (a replica
        # still paying warmup compiles must not take traffic). None
        # keeps the pre-fleet behavior: tick freshness alone decides.
        self.ready_fn = ready_fn
        server = self

        # /profile state: one capture at a time, process-wide semantics
        # (jax.profiler has one global session) but guarded per server —
        # a second server on the same process still 503s on the double
        # start rather than corrupting the session.
        self.profile_dir = profile_dir or os.path.join(
            tempfile.gettempdir(), f"cml-profiles-{os.getpid()}"
        )
        self.profile_quota = max(1, int(profile_quota))
        self._lock = threading.Lock()
        self._profile_seq = 0
        self._profile_inflight: str | None = None
        self._m_captures = registry.counter(
            "consensusml_profile_captures_total",
            "on-demand /profile captures completed",
        )
        self._m_prof_rejected = registry.counter(
            "consensusml_profile_rejected_total",
            "/profile requests refused (single-flight 409s + profiler "
            "double-start 503s)",
        )

        # the one JSON content type every JSON endpoint sends — /metrics
        # alone stays Prometheus text exposition
        JSON_CTYPE = "application/json; charset=utf-8"

        class Handler(BaseHTTPRequestHandler):
            def _send_json(self, code: int, doc) -> None:
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", JSON_CTYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:  # noqa: N802 - stdlib API name
                url = urlparse(self.path)
                path = url.path
                if path in ("/metrics", "/"):
                    body = registry.to_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/traces":
                    self._send_json(
                        200, merged_chrome_trace(tracer, requests)
                    )
                elif path == "/requests":
                    self._send_json(200, requests.snapshot())
                elif path == "/alerts":
                    code, doc = server._alerts_doc()
                    self._send_json(code, _jsonsafe(doc))
                elif path == "/query":
                    code, doc = server._query_doc(parse_qs(url.query))
                    self._send_json(code, _jsonsafe(doc))
                elif path == "/healthz":
                    code, doc = server._healthz_doc()
                    self._send_json(code, _jsonsafe(doc))
                elif path == "/events":
                    code, doc = server._events_doc(parse_qs(url.query))
                    self._send_json(code, _jsonsafe(doc))
                elif path == "/tenants":
                    code, doc = server._tenants_doc()
                    self._send_json(code, _jsonsafe(doc))
                elif path == "/profile":
                    self._send_json(*server._profile(parse_qs(url.query)))
                else:
                    self._send_json(
                        404,
                        {
                            "error": "try /metrics, /traces, /requests, "
                                     "/alerts, /query, /healthz, /events, "
                                     "/tenants, /profile"
                        },
                    )

            def log_message(self, *args) -> None:
                pass  # scrapes are not log lines

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.address: tuple[str, int] = self._httpd.server_address[:2]
        self.port: int = self.address[1]
        # /healthz state must exist before the first handler can run
        self._started_s = time.time()
        self._tick_stop = threading.Event()
        self._ticker = None
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="obs-metrics-http",
            daemon=True,
        )
        self._thread.start()
        # obs-ticker (docs/threads.md): serving processes have no train
        # loop to ride, so the server itself drives history.record() +
        # alerts.evaluate() at tick_s cadence; the thread only touches
        # the locked history/alert/registry paths
        if self.tick_s > 0 and (
            self.history is not None or self.alerts is not None
        ):
            self._ticker = threading.Thread(
                target=self._tick_loop, name="obs-ticker", daemon=True
            )
            self._ticker.start()

    def _tick_loop(self) -> None:
        while not self._tick_stop.wait(self.tick_s):
            try:
                if self.history is not None:
                    self.history.record()
                if self.alerts is not None:
                    self.alerts.evaluate()
            except Exception:
                # a transient export failure must not kill the cadence;
                # /healthz staleness catches a persistently broken tick
                pass

    # -- /alerts /query /healthz ------------------------------------------

    def _alerts_doc(self) -> tuple[int, dict]:
        if self.alerts is None:
            return 200, {"enabled": False, "firing": [], "firing_total": 0}
        doc = self.alerts.snapshot()
        doc["enabled"] = True
        return 200, doc

    def _query_doc(self, query: dict) -> tuple[int, dict]:
        if self.history is None:
            return 404, {"error": "no metrics history wired on this server"}
        series = (query.get("series") or [None])[0]
        if not series:
            return 400, {
                "error": "series is required: /query?series=NAME"
                         "[&window=SECONDS][&n=POINTS]",
                "series_known": self.history.keys(),
            }
        try:
            window = query.get("window")
            window_s = float(window[0]) if window else None
            n = query.get("n")
            points = int(n[0]) if n else None
        except (TypeError, ValueError):
            return 400, {"error": "window/n must be numeric"}
        doc = self.history.query(series, window_s=window_s, n=points)
        if doc is None:
            return 404, {
                "error": f"unknown series {series!r}",
                "series_known": self.history.keys(),
            }
        return 200, doc

    def _healthz_doc(self) -> tuple[int, dict]:
        """Readiness: 200 while the process (and, when this server owns
        the cadence, its obs tick) is live; 503 when an owned tick went
        stale — the signal a fleet router stops routing on."""
        now = time.time()
        age = None
        if self.history is not None:
            last = self.history.last_record_s
            if last == last:  # not NaN: at least one record landed
                # floor at server start: an OWNED tick cannot be stale
                # before this server has lived a tick interval — an
                # inherited process-global history may carry records
                # from long before this server existed
                age = round(now - max(last, self._started_s), 3)
            else:
                age = round(now - self._started_s, 3)
        firing = len(self.alerts.firing()) if self.alerts is not None else 0
        ok = True
        if self.tick_s > 0 and age is not None:
            ok = age <= max(5.0 * self.tick_s, 10.0)
        # the warmup gate (docs/fleet.md): ready_fn False means the
        # process is alive but must take zero NEW streams — same 503 a
        # stale tick earns, with the reason split out so a fleet
        # router's scrape can tell "warming" from "wedged"
        ready = True
        if self.ready_fn is not None:
            try:
                ready = bool(self.ready_fn())
            except Exception:
                ready = False
        return (200 if ok and ready else 503), {
            "ok": ok and ready,
            "ready": ready,
            "time_s": now,
            "pid": os.getpid(),
            "tick_s": self.tick_s if self.tick_s > 0 else None,
            "last_tick_age_s": age,
            "firing_alerts": firing,
            "history_series": (
                len(self.history) if self.history is not None else 0
            ),
        }

    # -- /events /tenants --------------------------------------------------

    def _event_log(self):
        """Wired log, else the global PEEKED (never created — the
        engine's terminal funnel arms it; a scrape must not)."""
        if self.events is not None:
            return self.events
        from consensusml_tpu.obs.events import peek_wide_event_log

        return peek_wide_event_log()

    def _events_doc(self, query: dict) -> tuple[int, dict]:
        log = self._event_log()
        if log is None:
            return 200, {"enabled": False, "events": [], "emitted_total": 0}
        try:
            n = query.get("n")
            count = int(n[0]) if n else 64
        except (TypeError, ValueError):
            return 400, {"error": "n must be an integer"}
        tenant = (query.get("tenant") or [None])[0]
        return 200, {
            "enabled": True,
            "emitted_total": log.emitted_total,
            "retained": len(log),
            "events": log.events(count, tenant=tenant),
        }

    def _tenants_doc(self) -> tuple[int, dict]:
        log = self._event_log()
        if log is None:
            return 200, {"enabled": False, "tenants": {}}
        return 200, {"enabled": True, "tenants": log.rollup()}

    # -- /profile ---------------------------------------------------------

    def _profile(self, query: dict) -> tuple[int, dict]:
        """One on-demand capture. Returns (http_status, response_doc).

        Runs ON the scraper's handler thread: the hot paths never wait
        on it, and the profiler's own overhead is confined to the
        requested window. The locked check-and-set of
        ``_profile_inflight`` IS the single-flight guard — the loser
        reads the winner's capture id under the same lock.
        """
        try:
            ms = int(query.get("ms", [PROFILE_DEFAULT_MS])[0])
        except (TypeError, ValueError):
            return 400, {"error": "ms must be an integer"}
        ms = min(max(ms, 10), PROFILE_MAX_MS)

        with self._lock:
            inflight = self._profile_inflight
            if inflight is None:
                self._profile_seq += 1
                cap_id = f"cap-{self._profile_seq:05d}-{int(time.time())}"
                self._profile_inflight = cap_id
        if inflight is not None:
            self._m_prof_rejected.inc()
            return 409, {
                "error": "a profile capture is already in flight",
                "capture_id": inflight,
            }
        try:
            import jax

            cap_dir = os.path.join(self.profile_dir, cap_id)
            os.makedirs(cap_dir, exist_ok=True)
            try:
                jax.profiler.start_trace(cap_dir)
            except Exception as e:
                # a batch --profile-dir window (or another tool) holds
                # the process's one profiler session
                self._m_prof_rejected.inc()
                shutil.rmtree(cap_dir, ignore_errors=True)
                return 503, {
                    "error": f"profiler session unavailable: {e}",
                    "capture_id": None,
                }
            try:
                time.sleep(ms / 1000.0)
            finally:
                jax.profiler.stop_trace()
            self._rotate_captures()
            hits = sorted(
                glob.glob(
                    os.path.join(cap_dir, "**", "*.trace.json.gz"),
                    recursive=True,
                )
            )
            trace_json = hits[-1] if hits else None
            self._m_captures.inc()
            return 200, {
                "capture_id": cap_id,
                "dir": cap_dir,
                "ms": ms,
                "trace_json": trace_json,
                "summary": (
                    _xprof_summary_json(trace_json) if trace_json else None
                ),
            }
        finally:
            with self._lock:
                self._profile_inflight = None

    def _rotate_captures(self) -> None:
        """Keep the newest ``profile_quota`` capture dirs (ids sort by
        sequence, so lexicographic order is capture order)."""
        try:
            caps = sorted(
                d
                for d in os.listdir(self.profile_dir)
                if d.startswith("cap-")
                and os.path.isdir(os.path.join(self.profile_dir, d))
            )
        except OSError:
            return
        for stale in caps[: -self.profile_quota]:
            shutil.rmtree(
                os.path.join(self.profile_dir, stale), ignore_errors=True
            )

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.address[0]}:{self.port}{path}"

    def close(self) -> None:
        self._tick_stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=max(2.0, 2 * self.tick_s))
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
