"""In-process metrics history: bounded time-series rings over the registry.

Every exported signal in the stack is point-in-time — a scrape sees the
current gauge value, the counter total, the cumulative histogram. Nothing
can answer "what was the TTFT p99 over the last minute" or "is this
counter still moving", which is exactly what SLO evaluation
(:mod:`~consensusml_tpu.obs.alerts`), the ROADMAP item 2 router, and a
post-mortem sparkline need. :class:`MetricsHistory` closes that gap
without an external TSDB:

- ``record()`` (called at telemetry cadence — the train loop's
  ``--telemetry-every`` tick, the :class:`~consensusml_tpu.obs.httpd.
  MetricsServer` ticker thread on serving processes, loadgen's sampler)
  appends one ``(timestamp, value)`` sample per registry series into a
  fixed-size per-series ring. Labels are preserved (the series key IS
  the registry key, ``name{k="v"}``); histograms sample their raw
  cumulative ``(count, sum, bucket counts)`` so windowed math can be
  done on DELTAS later.
- memory is bounded (``keep`` samples/series, ``max_series`` series,
  overflow counted — never silent) and accounted: the
  ``consensusml_history_*`` gauges report live series/sample counts and
  the estimated retained bytes.
- query helpers turn the rings into the derived signals alert rules and
  reports consume: :meth:`rate` / :meth:`increase` (counter-reset
  tolerant), :meth:`quantile` and :meth:`bad_fraction`
  (percentiles-from-histogram-deltas over a window), :meth:`spark`
  (per-interval derived points for sparklines), :meth:`last` (last-N
  dumps), and :meth:`query` (the ``/query`` endpoint's document).

Thread-safety: ``record()`` reads each metric under its own lock FIRST
(no nesting of metric locks inside the history lock), then appends under
``_lock``; every query copies under ``_lock``. Writers (telemetry tick)
and scrapers (``/query`` handler threads, the cluster writer) race only
on that lock. Schema and retention model: docs/observability.md
"Alerting & history".
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from collections import deque
from typing import Any, Callable

from consensusml_tpu.analysis import guarded_by
from consensusml_tpu.obs.metrics import (
    MetricsRegistry,
    get_registry,
    parse_metric_key,
)

__all__ = ["MetricsHistory", "get_history", "peek_history"]

# ~1 h of history at the 15 s scrape / serving-ticker cadence, ~40 min
# at train's default --telemetry-every on fast rounds
DEFAULT_KEEP = 240
DEFAULT_MAX_SERIES = 4096


class _Series:
    """One ring: scalar samples are ``(t, value)``; histogram samples
    are ``(t, count, sum, bucket_counts)`` with ``bucket_counts``
    including the +Inf slot (cumulative-by-time, per-bucket raw)."""

    __slots__ = ("key", "kind", "buckets", "samples")

    def __init__(self, key: str, kind: str, keep: int, buckets=None):
        self.key = key
        self.kind = kind
        self.buckets = buckets  # histogram bucket edges, else None
        self.samples: deque = deque(maxlen=keep)

    def est_bytes(self) -> int:
        # honest-order-of-magnitude estimate (tuple + float boxing), the
        # number the memory gauge reports; exactness is not the point —
        # boundedness is, and maxlen already guarantees that
        per = 64 + 16 * (2 if self.buckets is None else 3 + len(self.buckets) + 1)
        return 96 + per * len(self.samples)


@guarded_by("_lock", "_series", "_dropped", "_last_record_s")
class MetricsHistory:
    """Bounded per-series time-series rings over a metrics registry."""

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        *,
        keep: int = DEFAULT_KEEP,
        max_series: int = DEFAULT_MAX_SERIES,
        series_filter: Callable[[str, str], bool] | None = None,
    ):
        self.registry = registry if registry is not None else get_registry()
        self.keep = int(keep)
        self.max_series = int(max_series)
        # optional opt-out: (key, kind) -> False skips the series
        self.series_filter = series_filter
        self._lock = threading.Lock()
        self._series: dict[str, _Series] = {}
        self._dropped = 0
        self._last_record_s = math.nan
        r = self.registry
        self._g_series = r.gauge(
            "consensusml_history_series",
            "metric series retained in the in-process history rings",
        )
        self._g_samples = r.gauge(
            "consensusml_history_samples",
            "total samples across all history rings (bounded by "
            "keep x series)",
        )
        self._g_bytes = r.gauge(
            "consensusml_history_bytes",
            "estimated bytes retained by the history rings",
        )
        self._m_dropped = r.counter(
            "consensusml_history_series_dropped_total",
            "series refused because the max_series cap was reached "
            "(bounded memory, counted — never silent)",
        )

    # -- recording ---------------------------------------------------------

    def record(self, now: float | None = None) -> int:
        """Sample every registry series once; returns series recorded.

        Values are read under each metric's own lock BEFORE the history
        lock is taken (no nested lock order with the hot paths)."""
        now = time.time() if now is None else float(now)
        rows: list[tuple[str, str, Any, Any]] = []
        for m in self.registry.metrics():
            if self.series_filter is not None and not self.series_filter(
                m.key, m.kind
            ):
                continue
            if m.kind == "histogram":
                buckets, counts, total, n = m.raw()
                rows.append((m.key, m.kind, buckets, (now, n, total, counts)))
            else:
                rows.append((m.key, m.kind, None, (now, float(m.value))))
        recorded = 0
        with self._lock:
            for key, kind, buckets, sample in rows:
                s = self._series.get(key)
                if s is None:
                    if len(self._series) >= self.max_series:
                        self._dropped += 1
                        continue
                    s = _Series(key, kind, self.keep, buckets)
                    self._series[key] = s
                s.samples.append(sample)
                recorded += 1
            self._last_record_s = now
            n_series = len(self._series)
            n_samples = sum(len(s.samples) for s in self._series.values())
            est = sum(s.est_bytes() for s in self._series.values())
            dropped = self._dropped
        self._g_series.set(n_series)
        self._g_samples.set(n_samples)
        self._g_bytes.set(est)
        if dropped:
            drop_inc = dropped - self._m_dropped.value
            if drop_inc > 0:
                self._m_dropped.inc(drop_inc)
        return recorded

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._series)

    @property
    def last_record_s(self) -> float:
        """Unix time of the latest ``record()`` (NaN before the first) —
        the ``/healthz`` last-tick-age source."""
        with self._lock:
            return self._last_record_s

    def keys(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def keys_for(self, series: str) -> list[str]:
        """Keys a rule's ``series`` matches: an exact key when it names
        one (carries labels or exists verbatim), else every labeled
        child of the family."""
        with self._lock:
            if series in self._series or "{" in series:
                return [series] if series in self._series else []
            return sorted(
                k
                for k in self._series
                if parse_metric_key(k)[0] == series
            )

    def kind_of(self, key: str) -> str | None:
        """The series' metric kind (``counter``/``gauge``/``histogram``),
        None when unknown."""
        with self._lock:
            s = self._series.get(key)
            return s.kind if s is not None else None

    def _get(self, key: str) -> tuple[str, Any, list] | None:
        """(kind, buckets, samples-copy) — the one locked read every
        query helper builds on."""
        with self._lock:
            s = self._series.get(key)
            if s is None:
                return None
            return s.kind, s.buckets, list(s.samples)

    # -- scalar queries ----------------------------------------------------

    def last(self, key: str, n: int = 1) -> list[tuple]:
        """Last-``n`` raw samples, oldest first (empty when unknown)."""
        got = self._get(key)
        if got is None:
            return []
        return got[2][-max(int(n), 0):]

    def latest_value(self, key: str) -> tuple[float, float] | None:
        """Latest ``(t, value)`` for a scalar series; for a histogram
        the value is its cumulative observation count."""
        got = self._get(key)
        if got is None or not got[2]:
            return None
        s = got[2][-1]
        return (s[0], float(s[1]))

    def _scalar_window(self, key: str, window_s: float, now: float | None):
        got = self._get(key)
        if got is None or len(got[2]) < 2 or got[0] == "histogram":
            return None
        samples = got[2]
        now = samples[-1][0] if now is None else float(now)
        cutoff = now - float(window_s)
        # baseline: the latest sample at/before the cutoff so the delta
        # spans the whole window when history reaches back that far
        base = 0
        for i, (t, _v) in enumerate(samples):
            if t <= cutoff:
                base = i
            else:
                break
        return samples[base:]

    def increase(
        self, key: str, window_s: float, now: float | None = None
    ) -> float:
        """Counter increase over the window: the sum of positive
        sample-to-sample deltas (a process restart resets the counter —
        negative deltas are treated as resets, not decreases). NaN when
        fewer than two samples exist."""
        win = self._scalar_window(key, window_s, now)
        if win is None:
            return math.nan
        total = 0.0
        for (_, a), (_, b) in zip(win, win[1:]):
            if math.isfinite(a) and math.isfinite(b) and b > a:
                total += b - a
        return total

    def rate(
        self, key: str, window_s: float, now: float | None = None
    ) -> float:
        """Per-second :meth:`increase` over the measured sample span."""
        win = self._scalar_window(key, window_s, now)
        if win is None:
            return math.nan
        span = win[-1][0] - win[0][0]
        if span <= 0:
            return math.nan
        total = 0.0
        for (_, a), (_, b) in zip(win, win[1:]):
            if math.isfinite(a) and math.isfinite(b) and b > a:
                total += b - a
        return total / span

    # -- histogram-delta queries -------------------------------------------

    def _hist_delta(
        self, key: str, window_s: float, now: float | None
    ) -> tuple[tuple[float, ...], list[int], float, int] | None:
        """(bucket_edges, delta_counts incl +Inf, delta_sum, delta_count)
        between the window's baseline sample and the latest one."""
        got = self._get(key)
        if got is None or got[0] != "histogram" or len(got[2]) < 2:
            return None
        kind, buckets, samples = got
        now = samples[-1][0] if now is None else float(now)
        cutoff = now - float(window_s)
        base = samples[0]
        for s in samples:
            if s[0] <= cutoff:
                base = s
            else:
                break
        latest = samples[-1]
        if latest[0] <= base[0]:
            return None
        d_counts = [
            max(b - a, 0) for a, b in zip(base[3], latest[3])
        ]
        return (
            buckets,
            d_counts,
            max(latest[2] - base[2], 0.0),
            max(latest[1] - base[1], 0),
        )

    def quantile(
        self, key: str, q: float, window_s: float, now: float | None = None
    ) -> float:
        """Windowed percentile from histogram deltas (cumulative-bucket
        linear interpolation, same estimate as the cluster report's
        ``hist_stats``). NaN when the window saw no observations."""
        d = self._hist_delta(key, window_s, now)
        if d is None:
            return math.nan
        buckets, counts, _total, n = d
        if n <= 0:
            return math.nan
        # landing in +Inf reports the last finite edge
        return _delta_quantile(buckets, counts, n, q)

    def bad_fraction(
        self,
        key: str,
        threshold: float,
        window_s: float,
        now: float | None = None,
    ) -> float:
        """Fraction of the window's observations ABOVE ``threshold`` —
        the burn-rate engine's error fraction. Resolved at the smallest
        bucket edge >= threshold (put SLO thresholds on bucket edges for
        exact accounting). 0.0 when the window saw no traffic: no
        observations means no errors, so burn rates decay to zero and
        alerts clear when load stops."""
        d = self._hist_delta(key, window_s, now)
        if d is None:
            return 0.0
        buckets, counts, _total, n = d
        if n <= 0:
            return 0.0
        i = bisect.bisect_left(buckets, float(threshold))
        good = sum(counts[: i + 1]) if i < len(buckets) else n
        return max(0.0, 1.0 - good / n)

    def window_stats(
        self, key: str, window_s: float, now: float | None = None
    ) -> dict[str, float] | None:
        """{count, rate_per_s, mean, p50, p99} over the window's deltas."""
        d = self._hist_delta(key, window_s, now)
        if d is None:
            return None
        _buckets, _counts, total, n = d
        return {
            "count": n,
            "rate_per_s": n / float(window_s) if window_s > 0 else math.nan,
            "mean": total / n if n else math.nan,
            "p50": self.quantile(key, 0.50, window_s, now),
            "p99": self.quantile(key, 0.99, window_s, now),
        }

    # -- derived points / dumps --------------------------------------------

    def spark(self, key: str, points: int | None = None) -> list[list[float]]:
        """Per-sample derived points ``[t, v]`` for sparklines: gauges
        plot raw values, counters plot the per-interval rate, histograms
        the per-interval p99 (None when that interval saw nothing)."""
        got = self._get(key)
        if got is None:
            return []
        kind, buckets, samples = got
        if points is not None:
            samples = samples[-max(int(points) + 1, 2):]
        out: list[list[float]] = []
        if kind == "gauge":
            return [[t, v] for t, v in samples[-(points or len(samples)):]]
        if kind == "histogram" and samples:
            # implicit zero baseline: the first sample's interval covers
            # everything observed before the first record, so a short
            # run (one recorded sample) still yields its p99 point
            first = samples[0]
            samples = [
                (first[0], 0, 0.0, (0,) * len(first[3]))
            ] + samples
        for a, b in zip(samples, samples[1:]):
            dt = b[0] - a[0]
            if kind == "histogram":
                # p99 is dt-independent; only an empty interval yields
                # no point (the zero-baseline first interval qualifies)
                dn = max(b[1] - a[1], 0)
                if dn <= 0:
                    out.append([b[0], None])
                    continue
                d_counts = [max(y - x, 0) for x, y in zip(a[3], b[3])]
                out.append([b[0], _delta_quantile(buckets, d_counts, dn, 0.99)])
            else:  # counter: per-interval rate
                dv = b[1] - a[1]
                out.append(
                    [b[0], max(dv, 0.0) / dt if dt > 0 else None]
                )
        return out

    def query(
        self,
        series: str,
        window_s: float | None = None,
        n: int | None = None,
        now: float | None = None,
    ) -> dict[str, Any] | None:
        """The ``/query`` endpoint's document for one series key (or a
        family name resolving to one unlabeled key). None when the
        series is unknown."""
        keys = self.keys_for(series)
        if not keys:
            return None
        key = series if series in keys else keys[0]
        got = self._get(key)
        if got is None:
            return None
        kind, _buckets, samples = got
        window = float(window_s) if window_s else None
        doc: dict[str, Any] = {
            "series": key,
            "kind": kind,
            "samples_retained": len(samples),
            "keys": keys,
            "points": self.spark(key, points=n),
        }
        if kind == "histogram":
            doc["window_s"] = window or 300.0
            doc["window"] = self.window_stats(key, doc["window_s"], now)
        else:
            last = samples[-1] if samples else None
            doc["last"] = (
                {"time_s": last[0], "value": last[1]} if last else None
            )
            if kind == "counter":
                doc["window_s"] = window or 300.0
                doc["rate_per_s"] = self.rate(key, doc["window_s"], now)
                doc["increase"] = self.increase(key, doc["window_s"], now)
        return doc

    def digest(
        self, points: int = 32, now: float | None = None
    ) -> dict[str, Any]:
        """Compact per-series last-N summary for cluster snapshots and
        flight-recorder dumps (``tools/obs_report.py`` renders the rows
        as sparklines)."""
        rows: list[dict[str, Any]] = []
        for key in self.keys():
            pts = self.spark(key, points=points)
            if not pts:
                continue
            vals = [v for _t, v in pts if v is not None and math.isfinite(v)]
            rows.append(
                {
                    "series": key,
                    "kind": self.kind_of(key) or "?",
                    # non-finite -> null: the digest lands in JSON FILES
                    # (cluster snapshots, flight dumps) where a bare NaN
                    # token breaks strict parsers — a never-set gauge
                    # samples as NaN
                    "points": [
                        [
                            round(t, 3),
                            (
                                _round6(v)
                                if v is not None and math.isfinite(v)
                                else None
                            ),
                        ]
                        for t, v in pts
                    ],
                    "last": _round6(vals[-1]) if vals else None,
                    "min": _round6(min(vals)) if vals else None,
                    "max": _round6(max(vals)) if vals else None,
                }
            )
        with self._lock:
            est = sum(s.est_bytes() for s in self._series.values())
            n_samples = sum(len(s.samples) for s in self._series.values())
        return {
            "keep": self.keep,
            "points": points,
            "series": rows,
            "series_total": len(rows),
            "samples_total": n_samples,
            "memory_bytes_est": est,
        }


def _delta_quantile(buckets, counts, n, q) -> float:
    target = q * n
    cum = 0.0
    lo = 0.0
    for le, c in zip(buckets, counts):
        if cum + c >= target:
            frac = (target - cum) / c if c else 0.0
            return lo + frac * (le - lo)
        cum += c
        lo = le
    return lo


def _round6(v: float) -> float:
    return float(f"{float(v):.6g}")


_GLOBAL: MetricsHistory | None = None
_GLOBAL_LOCK = threading.Lock()


def get_history() -> MetricsHistory:
    """The process-wide history over the global registry (created on
    first use — the surfaces that tick it call this)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = MetricsHistory()
        return _GLOBAL


def peek_history() -> MetricsHistory | None:
    """The global history if some surface already armed it, else None —
    the flight recorder / cluster writer fallback that must not CREATE
    one as a side effect of dumping."""
    with _GLOBAL_LOCK:
        return _GLOBAL
