"""Wide-event request accounting: one structured record per request.

Every other plane answers one question at a time — the SLO histograms
say *that* a percentile spiked (obs/metrics.py), the request registry
says *which request* was slow (obs/requests.py), the cost ledger says
*what each executable costs* (obs/costs.py). Nothing joined them: no
single record answered "what did THIS request consume, and on whose
behalf?". This module is that join (docs/observability.md "Wide events
& tenant accounting").

At every engine-terminal state — complete (``eos``), truncated
(``max_tokens``/``length``), or error-drain (``cancelled``) — the
serving engine emits ONE wide event: the request's trace timings
(submit→admission→prefill→first-decode→complete, ``defer_ticks``,
``decode_ticks``, preemptions, hot-swap generation, speculative
propose/accept counts), its token counts (prompt in, generated out),
the pool block-seconds it held (integrated over hold time by
:class:`~consensusml_tpu.serve.pool.blocks.BlockPool`), the resolved
attention tier, and the LEDGER-DERIVED cost: ``decode_ticks`` × the
``serve.decode`` row's flops/bytes plus one ``serve.prefill.b{bucket}``
row per admission and the ``serve.spec.propose``/``serve.spec.verify``
rows on speculative engines — per-request TFLOPs and HBM bytes are
computed from XLA's own cost analysis, never guessed.

Events carry a ``tenant`` label threaded end to end (line-JSON protocol
→ ``ServeServer`` → ``Engine.submit(tenant=)`` → ``RequestTrace`` /
``GenResult``; default ``"default"``), so :meth:`WideEventLog.rollup`
attributes the fleet's spend per workload — the "tenant A consumed X
TFLOP-s and Y block-seconds" signal ROADMAP item 3(c) names and the
router/autoscaler tier places traffic on.

**Retention / memory model.** The log is a bounded ring (``capacity``
events, default 2048, oldest dropped — a weeks-long serving process
keeps the recent story, same policy as the span ring and the request
registry) plus an optional line-JSONL sink for durable offload: with
``jsonl_path`` set every event is appended as one JSON line at emit
time, so the full history lives on disk while memory stays bounded.
Non-finite floats are nulled at emit (events land in strict-JSON
consumers: cluster snapshots, flight dumps, ``/events``).

Singleton rule (the PR 14 pattern): producers arm the global log via
:func:`get_wide_event_log`; dump-path consumers (the flight recorder,
``ClusterWriter``) use :func:`peek_wide_event_log` and embed the log
only when something already armed it — a dump must never CREATE a log
as a side effect.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from typing import Any

from consensusml_tpu.analysis import guarded_by

__all__ = [
    "WideEventLog",
    "sanitize_tenant",
    "get_wide_event_log",
    "peek_wide_event_log",
    "reset_wide_event_log",
]

DEFAULT_CAPACITY = 2048
# worst-TTFT exemplars retained per tenant in rollup() — matches the
# histogram exemplar cap (obs/metrics.py EXEMPLAR_KEEP)
WORST_TTFT_KEEP = 8

_TENANT_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-"
)


def sanitize_tenant(tenant: Any) -> str:
    """The canonical tenant label: ``None``/empty → ``"default"``,
    otherwise the string with non-``[A-Za-z0-9._-]`` characters replaced
    by ``_`` and capped at 64 chars — tenants arrive from untrusted
    line-JSON clients and become Prometheus label values and rollup
    keys, so the label charset is enforced at the boundary, once."""
    if tenant is None:
        return "default"
    s = str(tenant)[:64]
    if not s:
        return "default"
    return "".join(c if c in _TENANT_OK else "_" for c in s)


def _jsonclean(v: Any) -> Any:
    """Null non-finite floats, recursively — bare NaN/Infinity tokens
    break strict JSON parsers downstream (same rule as alerts.notify)."""
    if isinstance(v, float):
        return v if math.isfinite(v) else None
    if isinstance(v, dict):
        return {k: _jsonclean(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonclean(x) for x in v]
    return v


@guarded_by("_lock", "_events", "_sink", "_emitted")
class WideEventLog:
    """Bounded ring of wide events + optional JSONL sink.

    The engine thread emits; scrapers (``/events``, ``/tenants``), the
    flight recorder, and the cluster writer read concurrently. RLock:
    the flight recorder's signal-handler dump may land inside an emit
    on the same thread (same reason as the metrics registry)."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        jsonl_path: str | None = None,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self._lock = threading.RLock()
        self._events: deque[dict[str, Any]] = deque(maxlen=capacity)
        self._emitted = 0
        self.jsonl_path = jsonl_path
        self._sink = None  # opened lazily on first emit

    # -- producer side -----------------------------------------------------

    def emit(self, event: dict[str, Any]) -> dict[str, Any]:
        """Record one wide event (the engine's terminal funnel calls
        this once per request). Missing ``time_s``/``tenant`` fields are
        stamped; the stored dict is JSON-safe. Returns the stored
        event."""
        ev = _jsonclean(dict(event))
        ev.setdefault("time_s", time.time())
        ev["tenant"] = sanitize_tenant(ev.get("tenant"))
        with self._lock:
            self._events.append(ev)
            self._emitted += 1
            if self.jsonl_path is not None:
                if self._sink is None:
                    self._sink = open(self.jsonl_path, "a")
                self._sink.write(json.dumps(ev) + "\n")
                self._sink.flush()
        return ev

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None

    # -- read side ---------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    @property
    def emitted_total(self) -> int:
        with self._lock:
            return self._emitted

    def events(
        self, n: int | None = None, tenant: str | None = None
    ) -> list[dict[str, Any]]:
        """The newest ``n`` retained events (all when ``None``),
        newest-last, optionally filtered to one tenant."""
        with self._lock:
            evs = list(self._events)
        if tenant is not None:
            evs = [e for e in evs if e.get("tenant") == tenant]
        if n is not None and n >= 0:
            evs = evs[-n:]
        return evs

    def tenants(self) -> list[str]:
        with self._lock:
            return sorted({e.get("tenant", "default") for e in self._events})

    def rollup(self) -> dict[str, dict[str, Any]]:
        """Per-tenant aggregates over the RETAINED ring: request count,
        prompt/generated tokens, ledger-joined TFLOPs and HBM
        gigabytes, pool block-seconds, decode/defer ticks, preemptions,
        and the worst-TTFT exemplars (cap :data:`WORST_TTFT_KEEP`,
        worst first) — the per-workload spend table the ``/tenants``
        endpoint, cluster aggregate, and ``obs_report`` render."""
        with self._lock:
            evs = list(self._events)
        out: dict[str, dict[str, Any]] = {}
        for ev in evs:
            t = ev.get("tenant", "default")
            agg = out.get(t)
            if agg is None:
                agg = out[t] = {
                    "requests": 0,
                    "tokens_in": 0,
                    "tokens_out": 0,
                    "tflops": 0.0,
                    "hbm_gbytes": 0.0,
                    "block_seconds": 0.0,
                    "shared_block_seconds": 0.0,
                    "prefix_hit_blocks": 0,
                    "decode_ticks": 0,
                    "defer_ticks": 0,
                    "preemptions": 0,
                    "worst_ttft": [],
                }
            agg["requests"] += 1
            agg["tokens_in"] += int(ev.get("prompt_len") or 0)
            agg["tokens_out"] += int(ev.get("tokens_out") or 0)
            agg["tflops"] += float(ev.get("tflops") or 0.0)
            agg["hbm_gbytes"] += float(ev.get("hbm_bytes") or 0.0) / 1e9
            agg["block_seconds"] += float(ev.get("block_seconds") or 0.0)
            # prefix-cache attribution: shared holds roll up separately
            # from the charged (exclusive) block-seconds above
            agg["shared_block_seconds"] += float(
                ev.get("shared_block_seconds") or 0.0
            )
            agg["prefix_hit_blocks"] += int(ev.get("prefix_hit_blocks") or 0)
            agg["decode_ticks"] += int(ev.get("decode_ticks") or 0)
            agg["defer_ticks"] += int(ev.get("defer_ticks") or 0)
            agg["preemptions"] += int(ev.get("preemptions") or 0)
            ttft = ev.get("ttft_s")
            if ttft is not None:
                agg["worst_ttft"].append(
                    {
                        "ttft_s": float(ttft),
                        "request_id": ev.get("request_id"),
                        "trace_id": ev.get("trace_id"),
                    }
                )
        for agg in out.values():
            agg["worst_ttft"] = sorted(
                agg["worst_ttft"], key=lambda r: -r["ttft_s"]
            )[:WORST_TTFT_KEEP]
            agg["tflops"] = round(agg["tflops"], 6)
            agg["hbm_gbytes"] = round(agg["hbm_gbytes"], 6)
            agg["block_seconds"] = round(agg["block_seconds"], 6)
            agg["shared_block_seconds"] = round(
                agg["shared_block_seconds"], 6
            )
        return out

    def snapshot(self, last_n: int = 64) -> dict[str, Any]:
        """JSON-able digest for cluster snapshots and flight dumps:
        per-tenant rollup + the last ``last_n`` raw events."""
        with self._lock:
            emitted = self._emitted
            retained = len(self._events)
        return {
            "time_s": time.time(),
            "emitted_total": emitted,
            "retained": retained,
            "tenants": self.rollup(),
            "events_recent": self.events(last_n),
        }


_GLOBAL: WideEventLog | None = None
_GLOBAL_LOCK = threading.Lock()


def get_wide_event_log() -> WideEventLog:
    """The process-wide log, created on first use by whichever producer
    arms it (the serving engine's terminal funnel). An optional JSONL
    sink path is taken from ``CONSENSUSML_WIDE_EVENTS_JSONL`` at
    creation time."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = WideEventLog(
                jsonl_path=os.environ.get("CONSENSUSML_WIDE_EVENTS_JSONL")
            )
        return _GLOBAL


def peek_wide_event_log() -> WideEventLog | None:
    """The global log if armed, else ``None`` — the dump-path accessor
    (flight recorder, cluster writer): a dump must never create a log
    as a side effect."""
    with _GLOBAL_LOCK:
        return _GLOBAL


def reset_wide_event_log() -> None:
    """Drop the global log (tests only — isolates per-test tenants)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is not None:
            _GLOBAL.close()
        _GLOBAL = None
