"""Cost ledger: per-executable compiled cost/memory accounting.

Every gauge and span in the stack so far answers "how long did it take";
nothing answers "how long SHOULD it have taken". This module closes that
gap at the place XLA already knows the answer: ``lower().compile()``.
For every executable the stack owns (the train step, the gossip round
under its active bucket plan, the paged prefill/decode serving stages,
the hot-swap staging transfer) the :class:`CostLedger` records

- **compiled cost**: FLOPs and bytes-accessed from
  ``Compiled.cost_analysis()`` — the roofline inputs;
- **compiled memory**: argument/output/temp/generated-code bytes from
  ``Compiled.memory_analysis()`` plus the live-footprint combination
  (arguments + temps + outputs − aliases) the HBM reconciliation uses
  (docs/memory.md "Reconciliation");
- **compile wall time**: measured around the ledger's own
  ``lower().compile()`` call.

Rows land in labeled ``consensusml_cost_*`` gauge families (one child
per ``executable=`` label) and the monotonic ``consensusml_compile_*``
counters, so the cluster snapshot / ``tools/obs_report.py`` carry the
full per-executable table (docs/observability.md "Cost attribution").

Registration is ANALYSIS-ONLY: the ledger lowers with shape structs (or
concrete arrays — nothing executes either way) through JAX's AOT path,
which never touches the jit dispatch cache — the zero-recompile
contract's ``compile_counts()`` stays byte-identical after wiring (the
``pytest -m profiling`` tier pins it). The price is one DUPLICATE
compile per registered executable, paid once at registration — which is
why ``train.py --cost-ledger`` is opt-in while the run-time side
(:meth:`CostLedger.observe_measured`, a few gauge stores) is cheap
enough for every telemetry tick (<1% of a round, bench "attribution").

Expected-vs-measured attribution: :meth:`observe_measured` pairs a
measured span time (the PR 10 round timeline, engine SLO stats) with
the executable's roofline floors —

    compute floor = flops / peak_flops_per_s
    memory  floor = bytes_accessed / peak_bytes_per_s
    expected      = max(compute floor, memory floor)

— and reports which bound binds plus the measured/floor ratio ("this
round is 1.7x its bytes-bound floor; the gap is the fence"). Peaks are
rough per-platform anchors (overridable per ledger): attribution ratios
are a diagnostic ordering, not a benchmark claim.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Any, Callable

from consensusml_tpu.analysis import guarded_by
from consensusml_tpu.obs.metrics import MetricsRegistry, get_registry

__all__ = [
    "DEVICE_PEAKS",
    "TRANSFER_PEAKS",
    "ExecutableCost",
    "CostLedger",
    "get_cost_ledger",
]

# (peak FLOP/s, peak bytes/s) roofline anchors per jax platform. Rough on
# purpose — they order executables and name the binding resource; the
# measured/floor RATIO trends are what matter, and a deployment that
# wants tight ratios passes its own peaks to CostLedger.
DEVICE_PEAKS: dict[str, tuple[float, float]] = {
    "tpu": (197e12, 819e9),  # v5e bf16 MXU / HBM2e
    "gpu": (90e12, 900e9),
    "cpu": (5e10, 2e10),
}

# host<->device staging bandwidth per platform: transfer rows (hot-swap
# artifact stage, prefetch windows) cross PCIe/host links, NOT the HBM
# bus — flooring them against DEVICE_PEAKS' bytes/s would understate
# the floor ~30x and read every healthy transfer as an anomaly
TRANSFER_PEAKS: dict[str, float] = {
    "tpu": 30e9,
    "gpu": 25e9,
    "cpu": 10e9,  # a memcpy between host buffers
}


def _tree_device_bytes(tree: Any) -> int:
    """Total leaf bytes of an array tree (shape structs count too)."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
            continue
        dtype = getattr(leaf, "dtype", None)
        if dtype is None:
            continue
        n = int(np.prod(getattr(leaf, "shape", ()) or (1,)))
        try:
            itemsize = np.dtype(dtype).itemsize
        except TypeError:  # extended dtype (typed PRNG key): 4B words
            itemsize = 4
        total += n * itemsize
    return total


@dataclasses.dataclass(frozen=True)
class ExecutableCost:
    """One ledger row: what XLA compiled for one executable."""

    name: str
    platform: str
    flops: float
    bytes_accessed: float
    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    alias_bytes: int
    generated_code_bytes: int
    compile_s: float
    kind: str = "compiled"  # "compiled" | "transfer"
    meta: dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def peak_bytes(self) -> int:
        """XLA's live device footprint for one invocation: arguments +
        temps + outputs − aliases (donated inputs alias their outputs) —
        the number the three-way HBM reconciliation compares against
        the analytic model and the live runtime (docs/memory.md)."""
        return (
            self.argument_bytes
            + self.temp_bytes
            + self.output_bytes
            - self.alias_bytes
        )

    def floors_s(
        self,
        peak_flops_per_s: float,
        peak_bytes_per_s: float,
        peak_transfer_bytes_per_s: float | None = None,
    ) -> tuple[float, float]:
        """(compute floor, memory floor) in seconds. Transfer rows have
        no FLOPs: their floor is bytes over the host<->device staging
        bandwidth (``peak_transfer_bytes_per_s``), not the HBM bus."""
        compute = self.flops / peak_flops_per_s if peak_flops_per_s else 0.0
        if self.kind == "compiled":
            moved, bw = self.bytes_accessed, peak_bytes_per_s
        else:
            moved = float(self.argument_bytes)
            bw = peak_transfer_bytes_per_s or peak_bytes_per_s
        memory = moved / bw if bw else 0.0
        return compute, memory

    def as_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["peak_bytes"] = self.peak_bytes
        return d


@guarded_by("_lock", "_rows", "_measured")
class CostLedger:
    """Get-or-create per-executable cost table + metric exporter.

    One process-wide instance (:func:`get_cost_ledger`) feeds the global
    registry; benches/tests build private instances over private
    registries. Thread-safe: serving registers from the client thread
    while the engine thread serves, and observe_measured may come from a
    telemetry tick.
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        platform: str | None = None,
        peak_flops_per_s: float | None = None,
        peak_bytes_per_s: float | None = None,
        peak_transfer_bytes_per_s: float | None = None,
    ):
        self.registry = registry if registry is not None else get_registry()
        if platform is None:
            import jax

            platform = jax.default_backend()
        self.platform = platform
        dflops, dbytes = DEVICE_PEAKS.get(platform, DEVICE_PEAKS["cpu"])
        self.peak_flops_per_s = peak_flops_per_s or dflops
        self.peak_bytes_per_s = peak_bytes_per_s or dbytes
        self.peak_transfer_bytes_per_s = (
            peak_transfer_bytes_per_s
            or TRANSFER_PEAKS.get(platform, TRANSFER_PEAKS["cpu"])
        )
        self._rows: dict[str, ExecutableCost] = {}
        self._measured: dict[str, float] = {}
        self._lock = threading.RLock()
        reg = self.registry
        # monotonic compile-side counters (the "is something recompiling
        # behind my back" signal reads these, so they must only go up)
        self._m_compiles = reg.counter(
            "consensusml_compile_total",
            "executables lowered+compiled into the cost ledger",
        )
        self._m_compile_s = reg.counter(
            "consensusml_compile_seconds_total",
            "cumulative ledger compile wall time",
        )

    # -- registration -----------------------------------------------------

    def register(
        self,
        name: str,
        fn: Callable,
        *args: Any,
        meta: dict[str, Any] | None = None,
        **kwargs: Any,
    ) -> ExecutableCost:
        """Lower + compile ``fn(*args, **kwargs)`` and record its row.

        ``fn`` must be jit-wrapped (``hasattr(fn, "lower")``); bare
        callables are wrapped on the fly. ``args`` may be concrete
        arrays or ``jax.ShapeDtypeStruct``s — NOTHING executes, no
        device memory is touched beyond XLA's compile arena, and the
        jit dispatch cache (``_cache_size`` / ``compile_counts()``) is
        not populated (AOT path). Re-registering a name overwrites its
        row (a re-lowered executable after a world/shape change).
        """
        import jax

        if not hasattr(fn, "lower"):
            fn = jax.jit(fn)
        t0 = time.perf_counter()
        compiled = fn.lower(*args, **kwargs).compile()
        compile_s = time.perf_counter() - t0

        try:
            ca = compiled.cost_analysis()
        except Exception:  # backend without cost analysis
            ca = None
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        ca = ca or {}
        flops = float(ca.get("flops", 0.0) or 0.0)
        bytes_accessed = float(ca.get("bytes accessed", 0.0) or 0.0)

        try:
            ma = compiled.memory_analysis()
        except Exception:
            ma = None
        row = ExecutableCost(
            name=name,
            platform=self.platform,
            flops=flops,
            bytes_accessed=bytes_accessed,
            argument_bytes=int(getattr(ma, "argument_size_in_bytes", 0)),
            output_bytes=int(getattr(ma, "output_size_in_bytes", 0)),
            temp_bytes=int(getattr(ma, "temp_size_in_bytes", 0)),
            alias_bytes=int(getattr(ma, "alias_size_in_bytes", 0)),
            generated_code_bytes=int(
                getattr(ma, "generated_code_size_in_bytes", 0)
            ),
            compile_s=compile_s,
            meta=dict(meta or {}),
        )
        self._record(row)
        return row

    def register_transfer(
        self, name: str, tree: Any, meta: dict[str, Any] | None = None
    ) -> ExecutableCost:
        """Record a host↔device staging transfer (the hot-swap artifact
        stage, a prefetch window) as a bytes-only row: no FLOPs, no
        compile — its floor is pure bandwidth."""
        nbytes = _tree_device_bytes(tree)
        row = ExecutableCost(
            name=name,
            platform=self.platform,
            flops=0.0,
            bytes_accessed=float(nbytes),
            argument_bytes=nbytes,
            output_bytes=nbytes,
            temp_bytes=0,
            alias_bytes=nbytes,  # staged in place: not double-resident
            generated_code_bytes=0,
            compile_s=0.0,
            kind="transfer",
            meta=dict(meta or {}),
        )
        self._record(row)
        return row

    def _record(self, row: ExecutableCost) -> None:
        reg = self.registry
        labels = {"executable": row.name}
        reg.gauge(
            "consensusml_cost_flops",
            "compiled FLOPs per invocation (XLA cost analysis)",
            labels=labels,
        ).set(row.flops)
        reg.gauge(
            "consensusml_cost_bytes_accessed",
            "compiled bytes accessed per invocation (XLA cost analysis)",
            labels=labels,
        ).set(row.bytes_accessed)
        reg.gauge(
            "consensusml_cost_argument_bytes",
            "compiled argument buffer bytes",
            labels=labels,
        ).set(row.argument_bytes)
        reg.gauge(
            "consensusml_cost_output_bytes",
            "compiled output buffer bytes",
            labels=labels,
        ).set(row.output_bytes)
        reg.gauge(
            "consensusml_cost_temp_bytes",
            "compiled temp buffer bytes (XLA scratch)",
            labels=labels,
        ).set(row.temp_bytes)
        reg.gauge(
            "consensusml_cost_generated_code_bytes",
            "compiled program code size",
            labels=labels,
        ).set(row.generated_code_bytes)
        reg.gauge(
            "consensusml_cost_peak_bytes",
            "compiled live footprint: arguments + temps + outputs - aliases",
            labels=labels,
        ).set(row.peak_bytes)
        reg.gauge(
            "consensusml_compile_seconds",
            "ledger-measured lower+compile wall time for this executable",
            labels=labels,
        ).set(row.compile_s)
        if row.kind == "compiled":
            self._m_compiles.inc()
            self._m_compile_s.inc(row.compile_s)
        with self._lock:
            self._rows[row.name] = row

    # -- queries ----------------------------------------------------------

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._rows)

    def row(self, name: str) -> ExecutableCost | None:
        with self._lock:
            return self._rows.get(name)

    def rows(self) -> list[ExecutableCost]:
        with self._lock:
            return [self._rows[k] for k in sorted(self._rows)]

    # -- run-time attribution --------------------------------------------

    def observe_measured(self, name: str, seconds: float) -> dict[str, Any]:
        """Pair a measured wall time with ``name``'s modeled cost.

        Feeds the ``consensusml_cost_{measured,expected}_seconds`` and
        ``consensusml_cost_floor_ratio`` gauges and returns the
        attribution row. Raises ``KeyError`` for an unregistered name —
        a silent typo here would report an executable as free."""
        row = self.row(name)
        if row is None:
            raise KeyError(
                f"executable {name!r} is not in the cost ledger "
                f"(registered: {self.names()})"
            )
        with self._lock:
            self._measured[name] = float(seconds)
        attr = self.attribution(name)
        labels = {"executable": name}
        reg = self.registry
        reg.gauge(
            "consensusml_cost_measured_seconds",
            "measured wall time paired with this executable's cost row",
            labels=labels,
        ).set(seconds)
        reg.gauge(
            "consensusml_cost_expected_seconds",
            "roofline floor: max(flops/peak_flops, bytes/peak_bw)",
            labels=labels,
        ).set(attr["expected_s"])
        reg.gauge(
            "consensusml_cost_floor_ratio",
            "measured / roofline floor (1.0 = at the hardware bound)",
            labels=labels,
        ).set(attr["ratio_to_floor"])
        return attr

    def attribution(self, name: str) -> dict[str, Any]:
        """Expected-vs-measured row for one executable (measured fields
        are NaN until :meth:`observe_measured` pairs a wall time)."""
        row = self.row(name)
        if row is None:
            raise KeyError(f"executable {name!r} is not in the cost ledger")
        compute_s, memory_s = row.floors_s(
            self.peak_flops_per_s,
            self.peak_bytes_per_s,
            self.peak_transfer_bytes_per_s,
        )
        expected = max(compute_s, memory_s)
        if row.kind == "transfer":
            bound = "transfer"
        else:
            bound = "compute" if compute_s >= memory_s else "memory"
        with self._lock:
            measured = self._measured.get(name, math.nan)
        ratio = measured / expected if expected > 0 else math.nan
        return {
            "executable": name,
            "kind": row.kind,
            "bound": bound,
            "compute_floor_s": compute_s,
            "memory_floor_s": memory_s,
            "expected_s": expected,
            "measured_s": measured,
            "ratio_to_floor": ratio,
            "unattributed_s": (
                max(0.0, measured - expected)
                if not math.isnan(measured)
                else math.nan
            ),
        }

    def snapshot(self) -> dict[str, Any]:
        """The full table as one JSON-able doc (cluster snapshots, the
        bench attribution section, obs_report)."""
        out = []
        for row in self.rows():
            d = row.as_dict()
            d.update(self.attribution(row.name))
            out.append(d)
        return {
            "platform": self.platform,
            "peak_flops_per_s": self.peak_flops_per_s,
            "peak_bytes_per_s": self.peak_bytes_per_s,
            "peak_transfer_bytes_per_s": self.peak_transfer_bytes_per_s,
            "executables": out,
        }


_GLOBAL: CostLedger | None = None
_GLOBAL_LOCK = threading.Lock()


def get_cost_ledger() -> CostLedger:
    """The process-wide ledger over the global metrics registry (built
    lazily so importing obs never touches the jax backend)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = CostLedger()
        return _GLOBAL
