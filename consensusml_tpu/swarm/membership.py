"""Live membership: epoch-stamped member views with barrier-free handoff.

The swarm's source of truth for "who is in the run right now". A
:class:`MemberView` is an immutable snapshot — epoch counter, per-member
status, and the :class:`~consensusml_tpu.topology.Topology` re-derived
for the view's world size — and the :class:`MembershipController` owns
the sequence of views:

- **propose** stages membership events (join / drop / rejoin /
  straggle) against the CURRENT view; nothing changes yet.
- **advance** applies the staged events at a round boundary: a new view
  (epoch + 1) becomes current, its topology re-derived via
  :func:`consensusml_tpu.topology.rederive` whenever the world size
  changed.
- **pin / release** implement the barrier-free transition protocol: a
  gossip round pins the view it launched against, and that view stays
  valid — retrievable, its topology/mask intact — until released, even
  across any number of ``advance`` calls. In-flight rounds therefore
  complete against the old view while the next round picks up the new
  one; no barrier, no drain. ``advance`` never blocks on pins.

The pin/advance protocol (plus the ``_fed_epoch`` gauge-feed claim
below) is model-checked over every pin/advance/complete interleaving by
the ``membership-epoch`` abstraction in
:mod:`consensusml_tpu.analysis.protocol_models` (cml-check pass 8):
rounds complete against their pinned epoch across any number of
advances, and no gauge feed lands at an older epoch than the newest
claimed. A recorded pin/advance/release trace of this controller
replays as a model path (:mod:`consensusml_tpu.analysis.conformance`).

Statuses: ``active`` members gossip and train; ``dead`` members
(dropped/preempted) are frozen — their replica is untouched until a
rejoin; ``straggling`` members keep training locally but miss gossip
until their straggle window expires (auto-recovered by ``advance``).
Dead and straggling members keep their SLOT — the stacked state row and
the topology vertex — so consensus runs over the full graph with an
alive mask, which is exactly the regime push-sum-weighted recovery
(``GossipConfig.push_sum="auto"``) keeps mean-exact.

Thread contract: the controller is read from the training thread and
(in multi-controller deployments) written from a membership-event
thread, so the view chain is lock-guarded and checked by cml-check's
lock-discipline pass (``@guarded_by``).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Iterable

import numpy as np

from consensusml_tpu.analysis import guarded_by
from consensusml_tpu.topology import Topology, rederive

__all__ = ["Member", "MemberView", "MembershipController", "ACTIVE", "DEAD", "STRAGGLING"]

ACTIVE = "active"
DEAD = "dead"
STRAGGLING = "straggling"


@dataclasses.dataclass(frozen=True)
class Member:
    """One swarm member. ``uid`` doubles as the member's SLOT: its row in
    the stacked state and its vertex in the topology (stable for the
    member's lifetime, including across drop/rejoin)."""

    uid: int
    status: str = ACTIVE
    joined_epoch: int = 0
    # straggle bookkeeping: rounds of gossip left to miss (auto-recovers)
    straggle_left: int = 0

    def __post_init__(self):
        if self.status not in (ACTIVE, DEAD, STRAGGLING):
            raise ValueError(f"bad member status {self.status!r}")


@dataclasses.dataclass(frozen=True)
class MemberView:
    """Immutable epoch-stamped membership snapshot."""

    epoch: int
    members: tuple[Member, ...]  # slot order: members[i].uid == i
    topology: Topology  # derived at world_size = len(members)

    @property
    def world_size(self) -> int:
        return len(self.members)

    @property
    def active(self) -> tuple[int, ...]:
        return tuple(m.uid for m in self.members if m.status == ACTIVE)

    @property
    def n_active(self) -> int:
        return sum(1 for m in self.members if m.status == ACTIVE)

    def alive_mask(self) -> np.ndarray:
        """``(world,)`` f32: 1 for members that gossip this round (active),
        0 for dead AND straggling ones (a straggler's payload is late, so
        the round proceeds without it — it re-syncs through later gossip)."""
        return np.asarray(
            [1.0 if m.status == ACTIVE else 0.0 for m in self.members],
            np.float32,
        )

    def frozen_mask(self) -> np.ndarray:
        """``(world,)`` f32: 1 for members whose replica is FROZEN (dead:
        the worker is gone, its row must not move); stragglers still train."""
        return np.asarray(
            [1.0 if m.status == DEAD else 0.0 for m in self.members],
            np.float32,
        )


@guarded_by("_lock", "_current", "_staged", "_pins", "_retired")
@guarded_by("_feed_lock", "_fed_epoch")
class MembershipController:
    """Owner of the live member view; see the module docstring for the
    propose/advance/pin protocol."""

    def __init__(self, topology: Topology, registry=None):
        self._lock = threading.Lock()
        members = tuple(
            Member(uid=i) for i in range(topology.world_size)
        )
        self._current = MemberView(epoch=0, members=members, topology=topology)
        self._staged: list[tuple[str, tuple]] = []
        # epoch -> pin refcount; views stay reachable while pinned
        self._pins: dict[int, int] = {}
        self._retired: dict[int, MemberView] = {}
        self._registry = registry
        # serializes the GAUGE side of metric feeds: the claim and the
        # sets must be one critical section or a preempted older feed
        # could land its gauge values after a newer one's. Dedicated
        # lock (not _lock) so the view lock never spans foreign metric
        # locks; Gauge/Registry locks are leaves, so no cycle is
        # possible through this hold.
        self._feed_lock = threading.Lock()
        self._fed_epoch = -1  # newest epoch whose gauges were fed
        self._feed_metrics(self._current, events=())

    # ---- reads -----------------------------------------------------------
    def view(self) -> MemberView:
        """The current view (a snapshot; never mutated)."""
        with self._lock:
            return self._current

    def pin(self) -> MemberView:
        """Pin the current view for an in-flight round. The returned view
        stays valid across ``advance`` until :meth:`release` — the
        barrier-free half of the transition protocol."""
        with self._lock:
            v = self._current
            self._pins[v.epoch] = self._pins.get(v.epoch, 0) + 1
            self._retired.setdefault(v.epoch, v)
            return v

    def release(self, view: MemberView) -> None:
        """Release a pinned view; fully-released non-current epochs drop."""
        with self._lock:
            n = self._pins.get(view.epoch, 0) - 1
            if n < 0:
                raise ValueError(f"epoch {view.epoch} was not pinned")
            if n == 0:
                del self._pins[view.epoch]
                if view.epoch != self._current.epoch:
                    self._retired.pop(view.epoch, None)
            else:
                self._pins[view.epoch] = n

    def pinned_epochs(self) -> tuple[int, ...]:
        """Epochs with live pins (transition-protocol introspection)."""
        with self._lock:
            return tuple(sorted(self._pins))

    # ---- staging ---------------------------------------------------------
    def propose_join(self, n: int = 1) -> None:
        """Stage ``n`` joiners; they take slots ``world..world+n-1`` at the
        next ``advance`` (the caller bootstraps their replicas then)."""
        if n < 1:
            raise ValueError(f"join count must be positive, got {n}")
        with self._lock:
            self._staged.append(("join", (int(n),)))

    def propose_drop(self, uids: Iterable[int]) -> None:
        with self._lock:
            self._staged.append(("drop", tuple(int(u) for u in uids)))

    def propose_rejoin(self, uids: Iterable[int]) -> None:
        with self._lock:
            self._staged.append(("rejoin", tuple(int(u) for u in uids)))

    def propose_straggle(self, uids: Iterable[int], rounds: int = 1) -> None:
        if rounds < 1:
            raise ValueError(f"straggle rounds must be positive, got {rounds}")
        with self._lock:
            self._staged.append(
                ("straggle", (tuple(int(u) for u in uids), int(rounds)))
            )

    # ---- transition ------------------------------------------------------
    def advance(self) -> MemberView:
        """Apply the staged events: install the next epoch's view as
        current and return it. Straggle windows tick down here (a member
        whose window hits zero recovers to active). Never blocks on pins;
        a no-event advance with no straggler ticks returns the current
        view unchanged (no epoch burn)."""
        with self._lock:
            staged, self._staged = self._staged, []
            cur = self._current
            members = list(cur.members)
            # tick straggle windows first: recovery is visible in the same
            # view as this boundary's events
            ticked = False
            for i, m in enumerate(members):
                if m.status == STRAGGLING:
                    ticked = True
                    left = m.straggle_left - 1
                    members[i] = dataclasses.replace(
                        m,
                        status=ACTIVE if left <= 0 else STRAGGLING,
                        straggle_left=max(left, 0),
                    )
            if not staged and not ticked:
                return cur
            new_epoch = cur.epoch + 1
            events = []
            for kind, args in staged:
                if kind == "join":
                    (n,) = args
                    base = len(members)
                    for k in range(n):
                        members.append(
                            Member(uid=base + k, joined_epoch=new_epoch)
                        )
                    events.append(("join", tuple(range(base, base + n))))
                elif kind == "drop":
                    for u in args:
                        self._check_slot(members, u)
                        members[u] = dataclasses.replace(
                            members[u], status=DEAD, straggle_left=0
                        )
                    events.append(("drop", args))
                elif kind == "rejoin":
                    for u in args:
                        self._check_slot(members, u)
                        if members[u].status != DEAD:
                            raise ValueError(
                                f"rejoin of member {u} which is "
                                f"{members[u].status}, not dead"
                            )
                        members[u] = dataclasses.replace(
                            members[u], status=ACTIVE
                        )
                    events.append(("rejoin", args))
                elif kind == "straggle":
                    uids, rounds = args
                    for u in uids:
                        self._check_slot(members, u)
                        if members[u].status == DEAD:
                            raise ValueError(
                                f"straggle of dead member {u}"
                            )
                        members[u] = dataclasses.replace(
                            members[u], status=STRAGGLING, straggle_left=rounds
                        )
                    events.append(("straggle", uids))
                else:  # pragma: no cover - staging validates kinds
                    raise AssertionError(kind)
            if sum(1 for m in members if m.status == ACTIVE) < 1:
                raise ValueError(
                    "membership change would leave no active member"
                )
            topo = cur.topology
            if len(members) != cur.world_size:
                topo = rederive(topo, len(members))
            new = MemberView(
                epoch=new_epoch, members=tuple(members), topology=topo
            )
            self._current = new
            # drop fully-released retired views; keep pinned ones alive
            self._retired = {
                e: v for e, v in self._retired.items() if e in self._pins
            }
        # metrics feed OUTSIDE the view lock: gauge/counter updates take
        # the registry's and each metric's own lock, and holding the
        # controller lock across foreign locks is exactly the cross-class
        # nesting the lockorder pass exists to keep out of the graph.
        # _feed_metrics's monotonic-epoch claim keeps two racing
        # advances from feeding the gauges in the wrong order; the view
        # itself was installed atomically above.
        self._feed_metrics(new, events)
        return new

    @staticmethod
    def _check_slot(members: list, u: int) -> None:
        if not 0 <= u < len(members):
            raise ValueError(
                f"member {u} out of range (world is {len(members)})"
            )

    # ---- telemetry -------------------------------------------------------
    def _feed_metrics(self, view: MemberView, events) -> None:
        """consensusml_swarm_* families (docs/observability.md).

        Runs OUTSIDE the view lock (see :meth:`advance`): the gauges
        carry a monotonic-epoch claim so two advances racing into their
        feeds cannot leave the gauges at the older epoch; event counters
        always count (they are per-event totals, not point-in-time).
        """
        if self._registry is None:
            return
        reg = self._registry
        with self._feed_lock:
            # claim + sets are ONE critical section: a feed that merely
            # claimed first but set last would leave the gauges at the
            # older epoch until the next advance
            if view.epoch >= self._fed_epoch:
                self._fed_epoch = view.epoch
                reg.gauge(
                    "consensusml_swarm_epoch",
                    "membership epoch of the live member view",
                ).set(view.epoch)
                reg.gauge(
                    "consensusml_swarm_members",
                    "members currently ACTIVE in the swarm",
                ).set(view.n_active)
                reg.gauge(
                    "consensusml_swarm_world_size",
                    "total member slots (active + dead + straggling)",
                ).set(view.world_size)
        for kind, uids in events:
            reg.counter(
                "consensusml_swarm_events_total",
                "membership events applied, by kind",
                labels={"kind": kind},
            ).inc(max(len(uids), 1))
