"""Deterministic churn schedules: the reproducible chaos fixture.

A :class:`ChurnSchedule` is a fixed list of membership events pinned to
round indices — the seeded, replayable input every elastic test, the
tier-1 churn smoke, and the bench elastic section run against. Two ways
to build one:

- **generate** — ``ChurnSchedule.generate(seed=.., rounds=.., joins=..,
  drops=.., stragglers=.., initial_world=..)`` draws event rounds and
  targets from ``numpy.random.default_rng(seed)``: same seed, same
  schedule, forever.
- **parse** — an explicit spec string, one event per ``;``-separated
  term (also what ``train.py --churn-schedule`` accepts):

      join@R[:N]        N workers join at round R (default 1)
      drop@R:U[,U..]    slots U.. drop (preempted) at round R
      rejoin@R:U[,U..]  previously dropped slots U.. rejoin at round R
      straggle@R:UxD    slot U misses gossip for D rounds from round R

  or the generator form ``seed=S,rounds=R,joins=J,drops=D,stragglers=K``
  which calls :meth:`generate`.

Semantics of an event at round R (enforced by the harness): drops and
straggles take effect IN round R (the mask the in-flight round mixes
with — a mid-round drop is exactly ``masked_mixing_matrix``/push-sum's
alive mask); joins bootstrap DURING round R and participate from round
R+1 (the membership view transition lands at the boundary, barrier-free
for the in-flight round). Rejoins lift the frozen mask at round R.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["ChurnEvent", "ChurnSchedule"]

KINDS = ("join", "drop", "rejoin", "straggle")


@dataclasses.dataclass(frozen=True)
class ChurnEvent:
    round: int
    kind: str  # join | drop | rejoin | straggle
    workers: tuple[int, ...] = ()  # slot uids (drop/rejoin/straggle)
    n: int = 1  # joiner count (join)
    duration: int = 1  # straggle rounds (straggle)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"bad churn event kind {self.kind!r}")
        if self.round < 0:
            raise ValueError(f"event round must be >= 0, got {self.round}")
        if self.kind == "join" and self.n < 1:
            raise ValueError(f"join needs n >= 1, got {self.n}")
        if self.kind != "join" and not self.workers:
            raise ValueError(f"{self.kind} needs worker slots")
        if self.kind == "straggle" and self.duration < 1:
            raise ValueError(
                f"straggle needs duration >= 1, got {self.duration}"
            )

    def spec(self) -> str:
        if self.kind == "join":
            return f"join@{self.round}:{self.n}"
        us = ",".join(str(u) for u in self.workers)
        if self.kind == "straggle":
            return f"straggle@{self.round}:{us}x{self.duration}"
        return f"{self.kind}@{self.round}:{us}"


@dataclasses.dataclass(frozen=True)
class ChurnSchedule:
    """An ordered, validated churn event list."""

    events: tuple[ChurnEvent, ...]

    def __post_init__(self):
        object.__setattr__(
            self,
            "events",
            tuple(sorted(self.events, key=lambda e: (e.round, e.kind))),
        )

    # ---- queries ---------------------------------------------------------
    def events_at(self, rnd: int) -> list[ChurnEvent]:
        return [e for e in self.events if e.round == rnd]

    @property
    def total_joins(self) -> int:
        return sum(e.n for e in self.events if e.kind == "join")

    def counts(self) -> dict[str, int]:
        out = {k: 0 for k in KINDS}
        for e in self.events:
            out[e.kind] += e.n if e.kind == "join" else len(e.workers)
        return out

    def spec(self) -> str:
        """Canonical serialization; ``parse(spec())`` round-trips."""
        return ";".join(e.spec() for e in self.events)

    # ---- construction ----------------------------------------------------
    @classmethod
    def parse(
        cls, spec: str, initial_world: int | None = None
    ) -> "ChurnSchedule":
        """``initial_world`` is the generator default when the spec does
        not name one (the train CLI passes the run's actual world)."""
        spec = spec.strip()
        if "@" not in spec and "=" in spec:
            kv = {}
            for term in spec.split(","):
                k, _, v = term.partition("=")
                kv[k.strip()] = int(v)
            unknown = set(kv) - {
                "seed", "rounds", "joins", "drops", "stragglers",
                "initial_world",
            }
            if unknown:
                raise ValueError(
                    f"unknown churn-schedule keys {sorted(unknown)}"
                )
            if "rounds" not in kv:
                raise ValueError("generator spec needs rounds=<int>")
            return cls.generate(
                seed=kv.get("seed", 0),
                rounds=kv["rounds"],
                joins=kv.get("joins", 3),
                drops=kv.get("drops", 2),
                stragglers=kv.get("stragglers", 1),
                initial_world=kv.get("initial_world", initial_world or 4),
            )
        events = []
        for term in spec.split(";"):
            term = term.strip()
            if not term:
                continue
            head, _, arg = term.partition(":")
            kind, at, rnd = head.partition("@")
            if not at:
                raise ValueError(
                    f"bad churn event {term!r} (expected kind@round[:arg])"
                )
            kind = kind.strip()
            rnd = int(rnd)
            if kind == "join":
                events.append(
                    ChurnEvent(rnd, "join", n=int(arg) if arg else 1)
                )
            elif kind in ("drop", "rejoin"):
                if not arg:
                    raise ValueError(f"{kind}@{rnd} needs worker slots")
                events.append(
                    ChurnEvent(
                        rnd, kind,
                        workers=tuple(int(u) for u in arg.split(",")),
                    )
                )
            elif kind == "straggle":
                us, x, dur = arg.partition("x")
                events.append(
                    ChurnEvent(
                        rnd, "straggle",
                        workers=tuple(int(u) for u in us.split(",")),
                        duration=int(dur) if x else 1,
                    )
                )
            else:
                raise ValueError(
                    f"unknown churn event kind {kind!r} "
                    f"(expected one of {KINDS})"
                )
        if not events:
            raise ValueError(f"empty churn schedule {spec!r}")
        return cls(events=tuple(events))

    @classmethod
    def generate(
        cls,
        seed: int,
        rounds: int,
        joins: int = 3,
        drops: int = 2,
        stragglers: int = 1,
        initial_world: int = 4,
    ) -> "ChurnSchedule":
        """Seeded schedule with the requested event mix.

        Deterministic rules: event rounds are drawn without replacement
        from ``[1, rounds-2]`` (round 0 warms compile, the last round
        stays churn-free so the post-churn loss is measured on a full
        round); drops target distinct INITIAL members (never a fresh
        joiner, never slot 0 — someone must anchor the bootstrap
        neighborhood); every drop rejoins 2 rounds later when the window
        allows; stragglers pick initial members not already dropped, for
        2 rounds each.
        """
        if rounds < 4:
            raise ValueError(f"need rounds >= 4 for a churn window, got {rounds}")
        if initial_world < 2:
            raise ValueError(
                f"initial_world must be >= 2, got {initial_world}"
            )
        n_events = joins + drops + stragglers
        window = range(1, rounds - 1)
        if n_events > len(window):
            raise ValueError(
                f"{n_events} events do not fit in rounds 1..{rounds - 2}"
            )
        droppable = max(initial_world - 1, 1)
        if drops > droppable:
            raise ValueError(
                f"{drops} drops exceed the {droppable} droppable initial "
                f"members (slot 0 anchors the swarm)"
            )
        rng = np.random.default_rng(seed)
        when = sorted(
            int(r) for r in rng.choice(list(window), size=n_events, replace=False)
        )
        kinds = ["join"] * joins + ["drop"] * drops + ["straggle"] * stragglers
        rng.shuffle(kinds)
        drop_pool = list(rng.permutation(np.arange(1, initial_world)))
        events: list[ChurnEvent] = []
        dropped_at: dict[int, int] = {}
        for rnd, kind in zip(when, kinds):
            if kind == "join":
                events.append(ChurnEvent(rnd, "join", n=1))
            elif kind == "drop":
                u = int(drop_pool.pop())
                events.append(ChurnEvent(rnd, "drop", workers=(u,)))
                dropped_at[u] = rnd
            else:
                # straggle an initial member that is not mid-drop at rnd
                cands = [
                    u for u in range(initial_world)
                    if not (u in dropped_at and dropped_at[u] <= rnd)
                ]
                u = int(rng.choice(cands)) if cands else 0
                events.append(
                    ChurnEvent(rnd, "straggle", workers=(u,), duration=2)
                )
        # every drop rejoins 2 rounds later (clamped inside the window)
        for u, rnd in sorted(dropped_at.items()):
            back = min(rnd + 2, rounds - 2)
            if back > rnd:
                events.append(ChurnEvent(back, "rejoin", workers=(u,)))
        return cls(events=tuple(events))
