"""Live swarm membership: churn without stopping training.

The subsystem ROADMAP item 4 calls for, built from four parts:

- :mod:`~consensusml_tpu.swarm.membership` — the
  :class:`MembershipController`: epoch-stamped member views, topology
  re-derivation on change, and a barrier-free pin/advance transition
  protocol (in-flight gossip rounds complete against the old view while
  the next round uses the new one).
- :mod:`~consensusml_tpu.swarm.churn` — deterministic churn schedules
  (:class:`ChurnSchedule`): seeded generation or an explicit spec
  string (``train.py --churn-schedule``), the reproducible fixture the
  elastic tests and the bench elastic section replay.
- :mod:`~consensusml_tpu.swarm.bootstrap` — gossip bootstrap: a joiner
  reconstructs its replica from neighbor gossip via push-sum partial
  sums over the new edges (provably within epsilon of
  ``utils.consensus_mean`` of the swarm — no checkpoint read).
- :mod:`~consensusml_tpu.swarm.harness` — :func:`run_churn`, the
  simulated-backend replay loop tying them together, with push-sum
  weighted recovery as the default whenever membership goes asymmetric
  (``GossipConfig.push_sum="auto"``).

See docs/elasticity.md for the membership protocol, the churn-schedule
format, and the bootstrap epsilon guarantee.
"""

from consensusml_tpu.swarm.bootstrap import (  # noqa: F401
    bootstrap_joiners,
    bootstrap_rounds_for,
    gossip_bootstrap,
)
from consensusml_tpu.swarm.churn import ChurnEvent, ChurnSchedule  # noqa: F401
from consensusml_tpu.swarm.harness import (  # noqa: F401
    ChurnReport,
    alive_consensus_state,
    churn_config,
    run_churn,
    validate_schedule,
)
from consensusml_tpu.swarm.membership import (  # noqa: F401
    Member,
    MembershipController,
    MemberView,
)
