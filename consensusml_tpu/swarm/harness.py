"""The churn harness: replay a seeded membership schedule while training.

This is the production scenario ROADMAP item 4 names — workers joining
and leaving every few minutes while training never stops — run end to
end on the simulated comm backend, deterministically:

- the :class:`~consensusml_tpu.swarm.membership.MembershipController`
  owns the epoch-stamped view; every round PINS the view it launches
  against and the boundary's events ADVANCE the next epoch underneath it
  (barrier-free: the in-flight round completes on the old view);
- **drops** land mid-round as alive-mask zeros — exactly the
  ``masked_mixing_matrix`` / push-sum alive semantics, with push-sum
  recovery engaged by default whenever the view's topology is
  asymmetric (``GossipConfig.push_sum="auto"``) — and the dropped
  member's replica is FROZEN (its inner loop rolls back) until rejoin;
- **stragglers** keep training locally but miss gossip for their window;
- **joins** gossip-bootstrap the new replica from neighbors during the
  round (:mod:`consensusml_tpu.swarm.bootstrap` — NO checkpoint read)
  and participate from the next round, with the topology re-derived at
  the new world size.

Membership changes the stacked world size, so each distinct world gets
its own jitted step (cached); that recompile — not a stop, a checkpoint
read, and a restart — is the whole cost of a join.

Batches come from any ``(rounds, seed) -> iterator`` source built
at the schedule's CAPACITY (initial world + total joins); each round
slices the leading axis down to the current world, so worker slot ``i``
consumes the same stream with or without churn — the equal-data contract
the loss-continuity acceptance test relies on.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from consensusml_tpu.comm import simulated
from consensusml_tpu.consensus import FaultConfig, record_fault_metrics
from consensusml_tpu.swarm.bootstrap import bootstrap_joiners
from consensusml_tpu.swarm.churn import ChurnSchedule
from consensusml_tpu.swarm.membership import DEAD, MembershipController
from consensusml_tpu.topology import rederive

__all__ = [
    "ChurnReport",
    "alive_consensus_state",
    "churn_config",
    "run_churn",
    "validate_schedule",
]


def alive_consensus_state(state, view):
    """A copy of ``state`` whose DEAD members' frozen rows are replaced
    by the ALIVE members' consensus mean — what evaluation (and any
    mean-model consumer) should see after a run that ended with members
    still down. The raw state is the honest CHECKPOINT content (a
    frozen replica is exactly what a rejoin resumes from); this view is
    for aggregation, where a stale replica would silently bias the mean
    model and the per-worker average."""
    import jax.numpy as jnp

    from consensusml_tpu.utils.tree import masked_worker_mean

    frozen = np.asarray(view.frozen_mask(), np.float32)
    if not frozen.any():
        return state
    keep = jnp.asarray(1.0 - frozen)

    def fix(tree):
        def one(x):
            x = jnp.asarray(x)
            k = keep.reshape((-1,) + (1,) * (x.ndim - 1))
            mean = masked_worker_mean(x, keep)
            return jnp.where(k > 0, x, mean.astype(x.dtype)[None])
        return jax.tree.map(one, tree)

    return state._replace(
        params=fix(state.params), model_state=fix(state.model_state)
    )


@dataclasses.dataclass
class ChurnReport:
    """What one churn replay produced (plain data; JSON-able via
    ``summary()`` except the final state)."""

    losses: list[float]
    consensus_errors: list[float]  # over ALIVE members (masked)
    alive_fracs: list[float]
    round_s: list[float]  # wall time per round (bootstrap time excluded)
    events: list[dict]  # timeline rows: round/kind/workers/epoch/detail
    world_trajectory: list[tuple[int, int]]  # (round, active members)
    bootstraps: list[dict]  # per-join gossip_bootstrap info
    recompiles: int
    final_state: Any
    final_view: Any
    wall_s: float

    def summary(self) -> dict:
        return {
            "rounds": len(self.losses),
            "final_loss": self.losses[-1] if self.losses else None,
            "final_consensus_error": (
                self.consensus_errors[-1] if self.consensus_errors else None
            ),
            "events": self.events,
            "world_trajectory": self.world_trajectory,
            "bootstraps": self.bootstraps,
            "recompiles": self.recompiles,
            "wall_s": round(self.wall_s, 3),
        }


def churn_config(cfg):
    """A LocalSGDConfig ready for scheduled churn: the alive-mask gossip
    plumbing on (``FaultConfig(drop_prob=0)`` unless faults are already
    configured) and push-sum recovery as the default under asymmetric
    membership (``push_sum="auto"`` unless explicitly set)."""
    gossip = cfg.gossip
    if gossip.compressor is not None:
        raise NotImplementedError(
            "churn on a compressed-gossip config is not supported: CHOCO "
            "tracking assumes every peer applies every innovation, which "
            "a membership change violates (use an exact-mixing config)"
        )
    if gossip.overlap:
        raise NotImplementedError(
            "churn + overlap gossip is not supported: a dropped round "
            "would apply a correction computed against a W the peer "
            "never participated in"
        )
    changes = {}
    if gossip.faults is None:
        changes["faults"] = FaultConfig(drop_prob=0.0)
    if gossip.push_sum is False:
        changes["push_sum"] = "auto"
    if changes:
        gossip = dataclasses.replace(gossip, **changes)
    return dataclasses.replace(cfg, gossip=gossip)


def validate_schedule(schedule: ChurnSchedule, topology, rounds: int) -> int:
    """Dry-replay ``schedule`` against a scratch controller so an invalid
    event sequence (rejoin of a never-dropped member, straggle of a dead
    one, an event past ``rounds`` or outside capacity) fails BEFORE any
    training round runs, with the offending round in the message. Returns
    the capacity the replay reached. ``run_churn`` calls this itself; the
    CLI calls it up front to turn bad specs into a clean exit."""
    initial = topology.world_size
    capacity = initial + schedule.total_joins
    for e in schedule.events:
        if e.round >= rounds:
            raise ValueError(
                f"churn event {e.spec()} lands beyond the {rounds}-round run"
            )
        if e.kind != "join" and max(e.workers) >= capacity:
            raise ValueError(
                f"churn event {e.spec()} targets slot {max(e.workers)} "
                f"outside capacity {capacity}"
            )
    ctl = MembershipController(topology)
    for rnd in range(rounds):
        # stage in run_churn's EXACT order — non-join events during the
        # round, joins only at the boundary (after bootstrap) — so the
        # dry replay accepts/rejects precisely what the live loop would
        joins = 0
        rejoined: set[int] = set()
        for e in schedule.events_at(rnd):
            if e.kind == "join":
                joins += e.n
            elif e.kind == "drop":
                ctl.propose_drop(e.workers)
            elif e.kind == "rejoin":
                ctl.propose_rejoin(e.workers)
                rejoined.update(e.workers)
            elif e.kind == "straggle":
                # a same-round rejoin re-activates the member before the
                # controller applies the straggle, so it doesn't count as
                # dead here (matches advance's staged-order semantics)
                view = ctl.view()
                for u in e.workers:
                    if (
                        u not in rejoined
                        and u < view.world_size
                        and view.members[u].status == DEAD
                    ):
                        raise ValueError(
                            f"churn schedule invalid at round {rnd}: "
                            f"straggle of dead member {u}"
                        )
                if e.duration > 1:
                    # the event round itself is masked directly in the
                    # live loop; the controller window covers the rest
                    ctl.propose_straggle(e.workers, rounds=e.duration - 1)
        if joins:
            ctl.propose_join(joins)
        try:
            ctl.advance()
        except ValueError as err:
            raise ValueError(
                f"churn schedule invalid at round {rnd}: {err}"
            ) from err
    return capacity


def run_churn(
    cfg,
    loss_fn,
    init_params,
    schedule: ChurnSchedule,
    rounds: int,
    batches: Callable[..., Any],
    seed: int = 0,
    registry=None,
    bootstrap_tol: float = 1e-3,
    on_round: Callable[[int, dict], None] | None = None,
    on_event: Callable[[dict], None] | None = None,
) -> ChurnReport:
    """Train ``rounds`` rounds under ``schedule`` on the simulated backend.

    ``cfg``'s topology size is the INITIAL world; ``batches(rounds, seed)``
    must yield round batches stacked at CAPACITY = initial world +
    ``schedule.total_joins`` (extra rows are sliced off while the world is
    smaller). ``on_round(rnd, row)`` / ``on_event(row)`` are observation
    hooks (the train CLI's logging and cluster-timeline feed).
    """
    from consensusml_tpu.train import (
        init_stacked_state,
        make_simulated_train_step,
    )

    t0 = time.time()
    cfg = churn_config(cfg)
    initial = cfg.gossip.topology.world_size
    # fail on a bad event sequence BEFORE round 0, not mid-training
    capacity = validate_schedule(schedule, cfg.gossip.topology, rounds)

    controller = MembershipController(cfg.gossip.topology, registry=registry)
    state = init_stacked_state(
        cfg, init_params, jax.random.key(seed), initial
    )

    cfg_by_world = {initial: cfg}
    step_cache: dict[int, Any] = {}

    def cfg_at(world: int):
        if world not in cfg_by_world:
            cfg_by_world[world] = dataclasses.replace(
                cfg,
                gossip=dataclasses.replace(
                    cfg.gossip, topology=rederive(cfg.gossip.topology, world)
                ),
            )
        return cfg_by_world[world]

    def step_at(world: int):
        if world not in step_cache:
            step_cache[world] = make_simulated_train_step(
                cfg_at(world), loss_fn, external_alive=True
            )
            # a membership-driven world change costs one step rebuild —
            # the honest price of a join, instead of stop/checkpoint/restart
            report.recompiles += 1
        return step_cache[world]

    report = ChurnReport(
        losses=[], consensus_errors=[], alive_fracs=[], round_s=[],
        events=[], world_trajectory=[], bootstraps=[], recompiles=0,
        final_state=None, final_view=None, wall_s=0.0,
    )

    def record_event(rnd, kind, workers, epoch, detail=None):
        row = {
            "round": rnd, "kind": kind,
            "workers": [int(u) for u in workers], "epoch": epoch,
        }
        if detail:
            row["detail"] = detail
        report.events.append(row)
        if on_event is not None:
            on_event(row)
        if registry is not None:
            registry.gauge(
                "consensusml_swarm_last_event_round",
                "round index of the latest membership event",
            ).set(rnd)

    prev_alive = None
    for rnd, batch in zip(range(rounds), batches(rounds, seed)):
        t_round = time.time()
        # the in-flight round runs against the view pinned HERE; the
        # boundary's events install the next epoch underneath it
        view = controller.pin()
        try:
            events = schedule.events_at(rnd)
            # mid-round drops/straggles mask THIS round's gossip; the
            # epoch transition itself lands at the boundary below
            alive = view.alive_mask()
            frozen = view.frozen_mask()
            joins = 0
            for e in events:
                if e.kind == "drop":
                    # slots are pre-validated by the dry replay above
                    for u in e.workers:
                        if u < view.world_size:
                            alive[u] = 0.0
                            frozen[u] = 1.0
                    controller.propose_drop(e.workers)
                    record_event(rnd, "drop", e.workers, view.epoch)
                elif e.kind == "straggle":
                    # this round is missed via the mask below; the
                    # controller window covers the REMAINING duration-1
                    # rounds, so the member misses exactly `duration`
                    for u in e.workers:
                        if u < view.world_size:
                            alive[u] = 0.0
                    if e.duration > 1:
                        controller.propose_straggle(
                            e.workers, rounds=e.duration - 1
                        )
                    elif registry is not None:
                        # a 1-round straggle is applied wholly via the
                        # mask above and never reaches the controller —
                        # count it here so events_total agrees with the
                        # timeline
                        registry.counter(
                            "consensusml_swarm_events_total",
                            "membership events applied, by kind",
                            labels={"kind": "straggle"},
                        ).inc(len(e.workers))
                    record_event(
                        rnd, "straggle", e.workers, view.epoch,
                        {"duration": e.duration},
                    )
                elif e.kind == "rejoin":
                    # the member is back for this round: unfreeze + gossip
                    for u in e.workers:
                        if u < view.world_size:
                            alive[u] = 1.0
                            frozen[u] = 0.0
                    controller.propose_rejoin(e.workers)
                    record_event(rnd, "rejoin", e.workers, view.epoch)
                elif e.kind == "join":
                    joins += e.n

            world = view.world_size
            step = step_at(world)
            sliced = jax.tree.map(lambda x: x[:world], batch)
            state, metrics = step(
                state, sliced,
                jnp.asarray(alive), jnp.asarray(frozen),
            )
            loss = float(metrics["loss"])
            mask = np.asarray(metrics["alive_mask"])
            err_alive = float(
                simulated.consensus_error_masked(state.params, mask)
            )
            report.losses.append(loss)
            report.consensus_errors.append(err_alive)
            report.alive_fracs.append(float(metrics["alive_frac"]))
            report.world_trajectory.append((rnd, int(mask.sum())))
            record_fault_metrics(
                float(metrics["alive_frac"]), alive=mask,
                prev_alive=prev_alive,
            )
            prev_alive = mask
            if registry is not None and alive.sum() < world:
                # the recovery rounds counter: gossip proceeded with a
                # partial membership (push-sum-weighted when asymmetric)
                registry.counter(
                    "consensusml_swarm_recovery_rounds_total",
                    "gossip rounds completed under a partial alive mask",
                ).inc()

            # boundary: joins bootstrap NOW (during round rnd, against the
            # post-round replicas) and participate from rnd + 1
            if joins:
                report.round_s.append(time.time() - t_round)
                t_boot = time.time()
                new_world = world + joins
                new_topo = cfg_at(new_world).gossip.topology
                state, info = bootstrap_joiners(
                    cfg_at(new_world), state, joins, new_topo,
                    rng=jax.random.fold_in(jax.random.key(seed + 1), rnd),
                    tol=bootstrap_tol,
                    # DEAD members' frozen replicas carry zero bootstrap
                    # mass: the joiner reconstructs the LIVE swarm's mean
                    # (stragglers are late, not stale — they stay in)
                    alive=1.0 - frozen,
                )
                info["wall_s"] = round(time.time() - t_boot, 4)
                report.bootstraps.append({"round": rnd, **info})
                controller.propose_join(joins)
                record_event(
                    rnd, "join",
                    list(range(world, new_world)), view.epoch,
                    {
                        "bootstrap_rounds": info["rounds"],
                        "eps_measured": info["eps_measured"],
                    },
                )
            new_view = controller.advance()
            if on_round is not None:
                on_round(rnd, {
                    "loss": loss,
                    "consensus_error": err_alive,
                    "alive_frac": float(metrics["alive_frac"]),
                    "epoch": new_view.epoch,
                    "world": new_view.world_size,
                    "active": new_view.n_active,
                })
        finally:
            controller.release(view)
        if len(report.round_s) <= rnd:
            report.round_s.append(time.time() - t_round)

    report.final_state = state
    report.final_view = controller.view()
    report.wall_s = time.time() - t0
    return report
