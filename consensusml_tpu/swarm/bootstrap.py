"""Gossip bootstrap: a joiner reconstructs state from neighbor gossip.

The checkpoint-free join. Instead of reading an artifact, a joiner
enters the NEW topology (the view re-derived at world + n) holding zero
parameters and zero push-sum mass, while every existing member holds its
replica at unit mass. Push-sum partial sums then flow over the new edges
(:func:`consensusml_tpu.consensus.pushsum.pushsum_round_simulated` — the
exact operator a recovery round uses):

    x(0) = [x_1 .. x_W, 0 .. 0]      w(0) = [1 .. 1, 0 .. 0]

A doubly-stochastic mixing matrix is column-stochastic, so BOTH sums are
conserved every round: ``sum x(k) = sum_old x_i`` and ``sum w(k) = W``.
Each worker's de-biased ratio ``z = x / w`` therefore converges to

    sum(x) / sum(w)  =  (1/W) * sum_old x_i  =  utils.consensus_mean(x_old)

— bit-for-bit the SAME definition of "the consensus model" evaluation,
elastic resume and serving export share — with geometric rate: after K
rounds the standard push-sum bound gives

    ||z_j(K) - mean|| <= (C / w_min(K)) * rho^K,   rho = 1 - spectral_gap

so :func:`bootstrap_rounds_for` picks K from the topology's own measured
contraction and the requested epsilon. The joiner's replica is provably
within that epsilon of the swarm mean, and the function also REPORTS the
realized error (measured against ``consensus_mean`` directly) so the
guarantee is checked, not assumed, on every join.

Survivors are untouched: the bootstrap rounds run on a scratch copy and
only the JOINER rows are taken from the result — a join never perturbs a
live replica (same contract as ``utils.elastic.resize_state``'s grow).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from consensusml_tpu.comm import simulated
from consensusml_tpu.consensus.pushsum import (
    PushSumState,
    pushsum_round_simulated,
)
from consensusml_tpu.topology import Topology
from consensusml_tpu.utils.tree import consensus_mean, masked_worker_mean

__all__ = ["bootstrap_rounds_for", "gossip_bootstrap", "bootstrap_joiners"]


def bootstrap_rounds_for(
    topology: Topology, tol: float = 1e-3, lo: int = 4, hi: int = 64
) -> int:
    """Rounds of neighbor gossip for a joiner to land within ``tol``
    (relative) of the swarm mean: ``K = ceil(log tol / log rho)`` from the
    topology's per-round contraction ``rho``, clamped to ``[lo, hi]``.
    Time-varying topologies contract per PERIOD, so K scales by it."""
    gap = topology.spectral_gap()
    period = topology.period if topology.is_time_varying else 1
    rho = 1.0 - max(min(gap, 1.0), 0.0)
    if rho <= 0.0:
        return max(lo, period)  # dense: one round is exact, keep the floor
    k = int(np.ceil(np.log(tol) / np.log(rho))) * period
    return int(min(max(k, lo), hi))


def gossip_bootstrap(
    tree: Any,
    topology: Topology,
    n_new: int,
    rounds: int | None = None,
    tol: float = 1e-3,
    alive: Any | None = None,
) -> tuple[Any, dict]:
    """Bootstrap ``n_new`` joiner replicas from neighbor gossip.

    ``tree``: the survivors' stacked ``(W, ...)`` pytree (params and —
    gossip carries it too — model_state). ``topology``: the NEW view's
    topology, already re-derived at ``W + n_new``. ``rounds``: run
    exactly this many gossip rounds; None (default) sizes the first
    burst from the spectral gap and then EXTENDS until every joiner
    measures within ``tol`` of the mean (capped at 64 rounds). Returns
    ``(joiner_rows, info)``: a stacked ``(n_new, ...)`` pytree plus an
    info dict with the rounds run, the epsilon TARGET, and the measured
    relative error of each joiner against ``utils.consensus_mean`` —
    the enforced half of the within-epsilon guarantee.

    ``alive``: optional ``(W,)`` 0/1 mask over the survivors. Rows at 0
    (DEAD members whose replicas froze rounds ago) get ZERO initial
    push-sum mass, so they contribute nothing to the partial sums and
    the joiner converges to — and is measured against — the mean of the
    LIVE swarm, not a mean polluted by stale frozen replicas.
    """
    n_old = int(jax.tree.leaves(tree)[0].shape[0])
    n = n_old + n_new
    if topology.world_size != n:
        raise ValueError(
            f"topology is sized {topology.world_size}, expected "
            f"{n_old} survivors + {n_new} joiners = {n}"
        )
    if rounds is not None and rounds < 1:
        raise ValueError(f"bootstrap rounds must be >= 1, got {rounds}")
    max_rounds = 64 if rounds is None else rounds
    # explicit rounds= runs EXACTLY that many; None sizes the first burst
    # from the spectral gap and extends adaptively below
    target = (
        bootstrap_rounds_for(topology, tol=tol, hi=max_rounds)
        if rounds is None
        else rounds
    )
    ws = (
        [simulated.mixing_matrix(p) for p in topology.phases]
        if topology.is_time_varying
        else [simulated.mixing_matrix(topology)]
    )
    f32 = lambda t: jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), t)
    # scratch copy: survivors' live replicas never move
    x = jax.tree.map(
        lambda v: jnp.concatenate(
            [jnp.asarray(v, jnp.float32), jnp.zeros((n_new, *v.shape[1:]), jnp.float32)]
        ),
        tree,
    )
    if alive is None:
        mass0 = jnp.ones((n_old,), jnp.float32)
    else:
        mass0 = jnp.asarray(alive, jnp.float32).reshape((n_old,))
        if float(mass0.sum()) < 1.0:
            raise ValueError(
                "gossip bootstrap needs at least one alive survivor"
            )
    state = PushSumState(
        w=jnp.concatenate([mass0, jnp.zeros((n_new,), jnp.float32)])
    )
    # a DEAD row holds zero mass, so its frozen replica never enters the
    # partial sums; the reference mean is the ALIVE members' mean — the
    # same quantity the sums converge to
    if alive is None:
        mean = f32(consensus_mean(tree))
    else:
        mean = jax.tree.map(lambda v: masked_worker_mean(v, mass0), tree)

    def measure(x) -> np.ndarray:
        """Per-joiner relative deviation from the shared consensus-mean
        definition (one host fetch; join-time, not per-round)."""
        sq_err = np.zeros(n_new)
        sq_ref = 0.0
        for x_leaf, m_leaf in zip(jax.tree.leaves(x), jax.tree.leaves(mean)):
            j_host = np.asarray(x_leaf, np.float64)[n_old:].reshape(n_new, -1)
            m_host = np.asarray(m_leaf, np.float64).reshape(-1)
            sq_err += ((j_host - m_host[None, :]) ** 2).sum(axis=1)
            sq_ref += float((m_host ** 2).sum())
        return np.sqrt(sq_err) / max(np.sqrt(sq_ref), 1e-12)

    # the spectral-gap estimate sizes the first burst; the push-sum bound's
    # constant (C / w_min) can exceed 1, so the guarantee is ENFORCED by
    # measuring and extending — never assumed from the estimate alone
    period = len(ws)
    done = 0
    rel = None
    while done < max_rounds:
        burst = (
            target if done == 0 else max(period, min(8, max_rounds - done))
        )
        burst = min(burst, max_rounds - done)
        for k in range(done, done + burst):
            x, state = pushsum_round_simulated(x, state, ws[k % period])
        done += burst
        rel = measure(x)
        if rounds is not None or float(rel.max()) <= tol:
            break

    joiners = jax.tree.map(lambda v: v[n_old:], x)
    # cast the rows to the survivors' dtypes (the state they join)
    joiners = jax.tree.map(
        lambda j, v: j.astype(jnp.asarray(v).dtype), joiners, tree
    )
    converged = float(rel.max()) <= tol
    if rounds is None and not converged:
        # the cap truncated the adaptive loop below tol: the guarantee is
        # only real if missing it is LOUD — the joiner still enters (its
        # replica is the best available estimate and later training
        # gossip keeps contracting), but nobody should find out from a
        # dashboard weeks later
        import warnings

        warnings.warn(
            f"gossip bootstrap hit the {max_rounds}-round cap at "
            f"eps={float(rel.max()):.3g} > tol={tol:.3g} on "
            f"{topology.name}(world={n}); the joiner enters OUTSIDE the "
            "requested epsilon (poorly-mixing topology — raise tol, pass "
            "rounds=, or pick a better-connected graph)",
            stacklevel=2,
        )
    info = {
        "rounds": int(done),
        "tol": float(tol),
        "eps_measured": float(rel.max()),
        "eps_per_joiner": [float(r) for r in rel],
        "converged": converged,
        "topology": topology.name,
        "world": n,
    }
    return joiners, info


def bootstrap_joiners(
    cfg,
    state,
    n_new: int,
    topology: Topology,
    rng: jax.Array | None = None,
    rounds: int | None = None,
    tol: float = 1e-3,
    alive: Any | None = None,
):
    """Grow a stacked :class:`TrainState` by ``n_new`` gossip-bootstrapped
    joiners — the swarm counterpart of the checkpoint-boundary
    ``resize_state`` grow, with NO checkpoint read.

    Bootstraps params and model_state jointly (they gossip jointly), then
    delegates the concat/optimizer-init/rng/gossip-reset mechanics to
    ``resize_state(joiner_params=...)``. Returns ``(new_state, info)``.
    """
    from consensusml_tpu.utils.elastic import resize_state

    old_world = int(state.step.shape[0])
    rows, info = gossip_bootstrap(
        {"params": state.params, "model_state": state.model_state},
        topology,
        n_new,
        rounds=rounds,
        tol=tol,
        alive=alive,
    )
    new_state = resize_state(
        cfg,
        state,
        old_world + n_new,
        rng=rng,
        joiner_params=rows["params"],
        joiner_model_state=rows["model_state"],
    )
    from consensusml_tpu.obs import get_registry

    reg = get_registry()
    reg.counter(
        "consensusml_swarm_bootstrapped_joiners_total",
        "joiners whose replica came from neighbor gossip (no checkpoint read)",
    ).inc(n_new)
    reg.gauge(
        "consensusml_swarm_bootstrap_epsilon",
        "latest join's measured relative deviation from the consensus mean",
    ).set(info["eps_measured"])
    from consensusml_tpu.obs.metrics import DEFAULT_ROUND_COUNT_BUCKETS

    reg.histogram(
        "consensusml_swarm_bootstrap_rounds",
        "neighbor-gossip rounds each join spent reconstructing state",
        buckets=DEFAULT_ROUND_COUNT_BUCKETS,
    ).observe(info["rounds"])
    return new_state, info
