"""The serving engine: one decode loop thread over a slot table.

``Engine`` owns the compiled program families (paged stages from
:mod:`consensusml_tpu.serve.pool.stages` by default, the PR 5 per-slot
path from :mod:`consensusml_tpu.serve.decode` as ``kv_impl="slot"``),
the KV memory (block pool or slot caches), and a single scheduler
thread that interleaves prefill admissions with in-flight decode
(continuous batching, :mod:`consensusml_tpu.serve.batcher`). Clients —
the in-process API, the socket front-end, loadgen — only touch the
bounded submit queue and per-request handles; all device work stays on
the one engine thread, so the jit caches, the cache pytree, and the
slot table need no locking.

Paged mode adds three behaviors on top of the PR 5 loop
(:mod:`consensusml_tpu.serve.pool`):

- slot occupancy is bounded by total live tokens (the block pool), so
  more lanes than ``HBM / max_len`` can be in flight under a heavy-tail
  length mix; on block exhaustion the youngest stream is preempted by
  RECOMPUTE (its blocks free, its prompt+generated-so-far re-enqueues at
  the head of the line — tokens already streamed stand, nothing drops);
- prefill admission is budgeted per tick (:class:`.pool.stages.
  AdmissionScheduler`): the decode step runs every tick, so a burst of
  long prompts spreads over ticks instead of stalling every stream;
- :meth:`watch` arms the drain-free hot swap: a new artifact generation
  flips params (and every resident slot's generation tag) between two
  decode steps with zero dropped streams and zero recompiles.

The admission / preempt-readmit / hot-swap protocol this loop
implements is model-checked over every interleaving by the
``request-lifecycle`` abstraction (cml-check pass 8,
:mod:`consensusml_tpu.analysis.protocol_models`): slots never aliased,
per-stream generations monotone, no stream lost across a flip, a
preempted stream re-admitted exactly once as a continuation. The
engine's own wide-event request traces double as the conformance
recording — a real preempt + hot-swap run must replay as a valid model
path (``tests/test_model_check.py``).

Sampling is in-jit and per-request (:mod:`consensusml_tpu.serve.
sampling`): ``submit(temperature=, top_p=, seed=, eos_id=)`` threads the
triple through the compiled steps as data — greedy is the
``temperature = 0`` case of the same executables, and a stream replays
deterministically from its echoed seed. ``Engine(...,
spec_decode=SpecConfig(model=draft, params=..., k=...))`` switches the
per-token decode step for the speculative round
(:mod:`consensusml_tpu.serve.pool.spec`): the draft proposes ``k``
tokens per lane, ONE fused target forward verifies every lane's window,
and rejection-sampling acceptance keeps the output distribution exactly
target-only sampling (1 to ``k + 1`` tokens per lane per round).

SLO instrumentation (docs/serving.md, docs/observability.md): every
request path stage lands on the ``consensusml_serve_*`` /
``consensusml_pool_*`` metric families (TTFT, inter-token latency, queue
depth, batch occupancy, block occupancy, evictions, swaps, tokens/s) and
``serve.prefill`` / ``serve.decode_step`` spans.

The steady-state contract: after :meth:`warmup` (one decode compile +
one prefill compile per prompt bucket), serving ANY admission order of
ANY mix of prompt lengths performs ZERO further compiles —
:meth:`compile_counts` exposes the jit cache sizes so tests and the
bench assert it, and cml-check's jaxpr contracts pin the
step-over-step program hash per stage.
"""

from __future__ import annotations

import collections
import dataclasses
import queue
import threading
import time
from typing import Any, Sequence

import numpy as np

__all__ = ["ServeConfig", "Engine", "load_engine"]


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine geometry + admission policy (all fixed at construction —
    shapes are compile-time)."""

    num_slots: int = 8  # decode batch lanes
    max_len: int = 0  # cache length; 0 = the model's max_len
    queue_depth: int = 64  # bounded admission queue
    max_new_tokens: int = 16  # default per-request generation cap
    eos_id: int | None = None  # default stop token; submit() can override
    idle_wait_s: float = 0.02  # scheduler block when nothing is in flight
    # -- default sampling (submit() overrides per request) ---------------
    temperature: float = 0.0  # 0 = greedy argmax (the original path)
    top_p: float = 1.0  # nucleus mass; 1.0 = full distribution
    # -- paged KV pool (serve/pool/; "slot" = the PR 5 per-slot rows) ----
    kv_impl: str = "paged"  # "paged" | "slot"
    block_size: int = 8  # tokens per physical KV block (must divide max_len)
    num_blocks: int = 0  # pool size; 0 = num_slots * max_len/block_size + 1
    prefill_budget: int = 0  # prefill tokens per tick; 0 = one max_len bucket
    # refcounted prefix-block sharing (serve/pool/prefix.py): matched
    # block-aligned prompt prefixes map into the slot's table and only
    # the unshared suffix prefills. Bit-exact vs the unshared path, so
    # the switch is a perf/memory knob, never a quality one. Opt-in:
    # warmup compiles one extra prefill executable per suffix bucket
    # (plus draft twins under spec decode), so engines that never see
    # repeated prompts shouldn't pay that compile time.
    prefix_cache: bool = False
    # paged-attention tier (models/paged_attention.py): "gather" = the
    # two-step reference (the measured default until TPU floor-ratio
    # data flips it); "auto" resolves via resolve_attention_impl —
    # compiled pallas on TPU, the interpreter elsewhere, NEVER silently
    # the reference. All impls are bit-exact, so switching tiers never
    # changes a stream.
    attn_impl: str = "gather"  # "gather" | "jnp" | "interpret" | "pallas" | "auto"


class Engine:
    """In-process serving engine over an exported consensus artifact.

    ``Engine(model, params)`` then :meth:`submit` from any thread;
    :meth:`score` is the prefill-only batch scoring path (golden parity
    with the evaluator's consensus-mean model). Use as a context manager
    or call :meth:`shutdown` — it drains in-flight work by default.
    """

    def __init__(
        self,
        model: Any,
        params: Any,
        config: ServeConfig | None = None,
        *,
        spec_decode: Any = None,
    ):
        import jax

        from consensusml_tpu.obs import get_registry, get_tracer
        from consensusml_tpu.serve import decode as D
        from consensusml_tpu.serve.batcher import Request, RequestHandle, SlotTable

        self.config = cfg = config or ServeConfig()
        self._dm = dm = D.DecodeModel.wrap(model)
        self.max_len = cfg.max_len or dm.max_len
        if not 0 < self.max_len <= dm.max_len:
            raise ValueError(
                f"max_len {self.max_len} outside (0, {dm.max_len}] "
                "(the model's position table bounds the cache)"
            )
        if cfg.num_slots < 1:
            raise ValueError(f"num_slots must be positive, got {cfg.num_slots}")
        if cfg.kv_impl not in ("paged", "slot"):
            raise ValueError(
                f"kv_impl must be 'paged' or 'slot', got {cfg.kv_impl!r}"
            )
        self.paged = cfg.kv_impl == "paged"
        from consensusml_tpu.models.paged_attention import (
            resolve_attention_impl,
        )

        # resolve ONCE at construction — "auto" means the kernel path
        # (pallas on TPU, interpret elsewhere), and the resolved value
        # is what stats()/the serve CLI report, so the executed tier is
        # always the reported tier
        self.attn_impl = resolve_attention_impl(cfg.attn_impl)
        if self.attn_impl != "gather" and not self.paged:
            raise ValueError(
                f"attn_impl={self.attn_impl!r} requires kv_impl='paged' "
                "(the fused kernels read the block pool; the slot path "
                "keeps its own parity baseline)"
            )
        self._params = jax.device_put(params)
        if self.paged:
            from consensusml_tpu.serve import pool as P

            # paged buckets start at the block size so every bucket is
            # block-aligned (prefill scatters whole blocks)
            self.buckets = D.prefill_buckets(
                self.max_len, smallest=max(8, cfg.block_size)
            )
            misaligned = [b for b in self.buckets if b % cfg.block_size]
            if misaligned:
                raise ValueError(
                    f"block_size {cfg.block_size} does not divide prefill "
                    f"bucket(s) {misaligned} (buckets "
                    f"{list(self.buckets)} for max_len {self.max_len}); "
                    "the prefill scatter chunks whole blocks — use a "
                    "power-of-two block_size, or one >= 8 that divides "
                    "max_len"
                )
            self._pool = P.BlockPool(
                cfg.num_slots, self.max_len, cfg.block_size, cfg.num_blocks
            )
            self._pages = P.init_pages(
                dm, self._pool.num_blocks, cfg.block_size
            )
            self._prefill_fn = P.make_paged_prefill_fn(dm)
            self._decode_fn = P.make_paged_decode_fn(
                dm, attn_impl=self.attn_impl
            )
            self._sched = P.AdmissionScheduler(
                cfg.prefill_budget or self.max_len
            )
            # content-addressed prefix sharing (serve/pool/prefix.py):
            # the index invalidates eagerly on block reuse (reuse_hook)
            # and freed-but-indexed blocks park at the bottom of the
            # free stack (cached_hook) so cached prefixes die last
            self._prefix = None
            if cfg.prefix_cache:
                self._prefix = P.PrefixIndex(cfg.block_size)
                self._pool.reuse_hook = self._prefix.invalidate_block
                self._pool.cached_hook = self._prefix.cached
                self._prefix_prefill_fn = P.make_prefix_prefill_fn(
                    dm, attn_impl=self.attn_impl
                )
        else:
            self.buckets = D.prefill_buckets(self.max_len)
            self._pool = None
            self._cache = D.init_cache(dm, cfg.num_slots, self.max_len)
            self._prefill_fn = D.make_prefill_fn(dm)
            self._decode_fn = D.make_decode_fn(dm)
            self._sched = None
            self._prefix = None
        self._score_fn = D.make_score_fn(dm)
        # -- speculative decode (serve/pool/spec.py): a draft model over
        # its own smaller pages, one fused k-verify on the target -------
        self.spec = spec_decode
        if self.spec is not None:
            from consensusml_tpu.serve import pool as P

            if not self.paged:
                raise ValueError(
                    "spec_decode requires kv_impl='paged' (the k-verify "
                    "is a widening of the paged decode stage)"
                )
            sd = D.DecodeModel.wrap(self.spec.model)
            if sd.vocab_size != dm.vocab_size:
                raise ValueError(
                    f"draft vocab {sd.vocab_size} != target vocab "
                    f"{dm.vocab_size}; speculative acceptance compares "
                    "distributions over one shared vocabulary"
                )
            if sd.max_len < self.max_len:
                raise ValueError(
                    f"draft max_len {sd.max_len} < engine max_len "
                    f"{self.max_len}; the draft must reach every "
                    "position the target serves"
                )
            self._draft_dm = sd
            self._draft_params = jax.device_put(self.spec.params)
            # the draft's pages share the pool's BLOCK TABLE (identical
            # logical geometry: same blocks, same offsets) but are their
            # own — smaller — arrays, sized by the draft architecture
            self._draft_pages = P.init_pages(
                sd, self._pool.num_blocks, cfg.block_size
            )
            self._draft_prefill_fn = P.make_paged_prefill_fn(sd)
            self._propose_fn = P.make_draft_propose_fn(
                sd, self.spec.k, attn_impl=self.attn_impl
            )
            self._verify_fn = P.make_verify_fn(
                dm, self.spec.k, attn_impl=self.attn_impl
            )
            self._spec_extra_cols = (
                P.spec_table_cols(
                    self._pool.blocks_per_slot, cfg.block_size, self.spec.k
                )
                - self._pool.blocks_per_slot
            )
            if self._prefix is not None:
                # draft pages share the pool's block table, so a prefix
                # hit skips the DRAFT prefill too — same program family
                # over the draft's own pages
                self._draft_prefix_prefill_fn = P.make_prefix_prefill_fn(
                    sd, attn_impl=self.attn_impl
                )
        self._Request, self._RequestHandle = Request, RequestHandle

        self._queue: "queue.Queue" = queue.Queue(cfg.queue_depth)
        # evicted continuations re-enter here, ahead of fresh arrivals
        # (engine thread appends/pops; submit's lost-race sweep may drain)
        self._requeue: "collections.deque" = collections.deque()
        self._table = SlotTable(cfg.num_slots)
        self._generation = 0  # artifact generation (load_engine sets it)
        self._watcher = None
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._drained = threading.Event()
        # readiness for fleet placement: set when a warmup() completes,
        # so a router's /healthz poll never routes streams onto a
        # replica still paying multi-second compiles (docs/fleet.md)
        self._warmed = threading.Event()

        from consensusml_tpu.obs import get_request_registry

        self._tracer = get_tracer()
        # request-scoped traces: every request's submit → admission →
        # prefill → decode → completion story (obs/requests.py; the
        # flight recorder dumps this registry on a serving crash)
        self._rt = get_request_registry()
        from consensusml_tpu.obs.events import get_wide_event_log

        # wide-event accounting (obs/events.py): ONE structured record
        # per terminal request, joining the trace with token counts,
        # block-seconds, and ledger-derived cost — the engine is a
        # producer, so it ARMS the global log (dump paths only peek)
        self._events = get_wide_event_log()
        self._cost_ledger = None  # set by register_costs()
        reg = get_registry()
        self._registry = reg
        # per-tenant labeled children (consensusml_tenant_*), created
        # lazily on a tenant's first terminal event and cached — the
        # registry dedupes by key, the cache just skips its lock
        self._tenant_children: dict[str, dict[str, Any]] = {}
        self._m_requests = reg.counter(
            "consensusml_serve_requests_total", "requests accepted by submit()"
        )
        self._m_rejected = reg.counter(
            "consensusml_serve_rejected_total",
            "requests rejected (bounded queue full or engine draining)",
        )
        self._m_completed = reg.counter(
            "consensusml_serve_completed_total", "requests served to completion"
        )
        self._m_tokens = reg.counter(
            "consensusml_serve_tokens_total", "tokens generated (prefill + decode)"
        )
        from consensusml_tpu.obs.metrics import DEFAULT_SLO_BUCKETS

        self._m_ttft = reg.histogram(
            "consensusml_serve_ttft_seconds",
            "time to first token: arrival -> first generated token",
            buckets=DEFAULT_SLO_BUCKETS,
        )
        self._m_intertoken = reg.histogram(
            "consensusml_serve_intertoken_seconds",
            "per-decode-step latency (== inter-token gap for resident slots)",
            buckets=DEFAULT_SLO_BUCKETS,
        )
        self._m_prefill = reg.histogram(
            "consensusml_serve_prefill_seconds", "prefill forward wall time",
            buckets=DEFAULT_SLO_BUCKETS,
        )
        self._m_queue = reg.gauge(
            "consensusml_serve_queue_depth", "requests waiting for a slot"
        )
        self._m_occupancy = reg.gauge(
            "consensusml_serve_batch_occupancy",
            "active decode slots / num_slots (sampled per step)",
        )
        self._m_tps = reg.gauge(
            "consensusml_serve_tokens_per_sec",
            "decode throughput: active slots / step wall time (sampled)",
        )
        self._m_generation = reg.gauge(
            "consensusml_serve_generation",
            "artifact generation currently serving",
        )
        self._m_swaps = reg.counter(
            "consensusml_serve_swaps_total",
            "drain-free hot swaps applied (params flipped between steps)",
        )
        self._m_evictions = reg.counter(
            "consensusml_pool_evictions_total",
            "streams preempted by recompute on block-pool exhaustion",
        )
        # loop liveness: set every engine-thread iteration — the
        # staleness signal the default alert ruleset's serve-loop-stale
        # rule (and a fleet router's /healthz poll) watches; a wedged
        # decode step or a dead engine thread stops it moving
        self._m_loop_heartbeat = reg.gauge(
            "consensusml_serve_loop_heartbeat_seconds",
            "unix time of the engine loop's latest iteration (liveness; "
            "staleness means the serving thread is wedged or dead)",
        )
        self._m_loop_heartbeat.set(time.time())
        if self.spec is not None:
            self._m_spec_rounds = reg.counter(
                "consensusml_spec_rounds_total",
                "speculative rounds (one draft scan + one fused verify)",
            )
            self._m_spec_proposed = reg.counter(
                "consensusml_spec_proposed_total",
                "draft tokens proposed across all live lanes",
            )
            self._m_spec_accepted = reg.counter(
                "consensusml_spec_accepted_total",
                "draft tokens accepted by the target's rejection sampler",
            )
            self._m_spec_rate = reg.gauge(
                "consensusml_spec_acceptance_rate",
                "accepted / proposed over the engine lifetime (sampled "
                "per verify round) — the k-tuning signal",
            )
        # live HBM tagging (obs/memviz.py): the engine's big resident
        # consumers as first-class gauges, so per-engine KV headroom is
        # a signal a fleet router can place traffic on (ROADMAP item 2)
        # and the three-way reconciliation can attribute serving bytes
        self._params_nbytes = sum(
            int(x.nbytes) for x in jax.tree.leaves(self._params)
        )
        self._m_params_bytes = reg.gauge(
            "consensusml_serve_params_bytes",
            "device bytes of the serving params tree (current generation)",
        )
        self._m_params_bytes.set(self._params_nbytes)
        if self.paged:
            self._m_blocks_free = reg.gauge(
                "consensusml_pool_blocks_free",
                "free physical KV blocks (trash block excluded)",
            )
            self._m_block_occ = reg.gauge(
                "consensusml_pool_block_occupancy",
                "allocated blocks / usable blocks (sampled per step)",
            )
            self._m_blocks_free.set(self._pool.free_blocks)
            self._m_block_occ.set(0.0)
            pool_bytes = sum(
                int(x.nbytes) for x in jax.tree.leaves(self._pages)
            )
            self._block_nbytes = pool_bytes // max(self._pool.num_blocks, 1)
            if self.spec is not None:
                self._draft_block_nbytes = sum(
                    int(x.nbytes) for x in jax.tree.leaves(self._draft_pages)
                ) // max(self._pool.num_blocks, 1)
            self._m_pool_hbm = reg.gauge(
                "consensusml_pool_hbm_bytes",
                "device bytes held by the paged KV block pool (all layers)",
            )
            self._m_pool_hbm.set(pool_bytes)
            self._m_pool_hbm_free = reg.gauge(
                "consensusml_pool_hbm_free_bytes",
                "KV bytes still allocatable (free blocks x per-block "
                "bytes) — the per-engine serving HBM headroom signal",
            )
            self._m_pool_hbm_free.set(
                self._pool.free_blocks * self._block_nbytes
            )
        if self._prefix is not None:
            self._m_prefix_hits = reg.counter(
                "consensusml_prefix_hits_total",
                "admissions that adopted at least one indexed prefix block",
            )
            self._m_prefix_misses = reg.counter(
                "consensusml_prefix_misses_total",
                "admissions that prefilled from scratch (no indexed prefix)",
            )
            self._m_prefix_hit_blocks = reg.counter(
                "consensusml_prefix_hit_blocks_total",
                "KV blocks mapped in from the prefix index instead of "
                "prefilled",
            )
            self._m_prefix_cow_copies = reg.counter(
                "consensusml_prefix_cow_copies_total",
                "copy-on-write block copies (full-match divergence: the "
                "last shared block copied to a fresh page in-jit)",
            )
            self._m_prefix_bytes_saved = reg.counter(
                "consensusml_prefix_bytes_saved_total",
                "KV bytes NOT materialized thanks to prefix sharing "
                "(adopted blocks x per-block bytes, draft pages included)",
            )
            self._m_prefix_entries = reg.gauge(
                "consensusml_prefix_entries",
                "live prefix-index entries (current generation)",
            )
            self._m_prefix_shared_blocks = reg.gauge(
                "consensusml_prefix_shared_blocks",
                "physical blocks currently held by more than one stream",
            )

        # host-side SLO accumulators for bench/loadgen percentiles —
        # BOUNDED rings (a serving process lives for weeks; the Prometheus
        # histograms carry the full-lifetime distributions, these lists
        # only feed stats() percentiles over the recent window)
        self._ttfts: "collections.deque[float]" = collections.deque(maxlen=4096)
        self._step_times: "collections.deque[float]" = collections.deque(
            maxlen=4096
        )
        self._occupancy_sum = 0.0
        self._block_occupancy_sum = 0.0
        self._decode_steps = 0
        self._tokens_out = 0
        self._tokens_in = 0  # prompt tokens of first-time admissions
        self._decode_time_s = 0.0
        self._evictions = 0
        self._swaps = 0
        self._spec_rounds = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_tokens = 0  # emitted by verify rounds (prefill excluded)
        # prefix-cache host accumulators (mirror the counters for
        # stats()/bench reads without registry scrapes); the tokens-
        # computed counter runs on EVERY paged engine so a prefix-off
        # baseline reports the same field
        self._prefill_tokens_computed = 0
        self._prefix_hits = 0
        self._prefix_misses = 0
        self._prefix_hit_blocks = 0
        self._prefix_cow_copies = 0
        self._prefix_bytes_saved = 0
        self._error: BaseException | None = None

        self._thread = threading.Thread(
            target=self._loop, name="serve-engine", daemon=True
        )
        self._thread.start()

    # -- client API ---------------------------------------------------------

    def submit(
        self,
        ids: Sequence[int],
        max_new_tokens: int | None = None,
        *,
        block: bool = True,
        timeout: float | None = None,
        trace: Any = None,
        temperature: float | None = None,
        top_p: float | None = None,
        seed: int | None = None,
        eos_id: int | None = None,
        tenant: str | None = None,
    ):
        """Enqueue one request; returns a ``RequestHandle``.

        ``trace`` is an optional :class:`~consensusml_tpu.obs.
        TraceContext` the client minted (loadgen / the line-JSON
        protocol); without one the engine mints its own, so EVERY
        accepted request has a recorded trace (docs/observability.md
        "Request tracing").

        ``temperature``/``top_p``/``seed`` sample THIS request
        (defaults: the ``ServeConfig`` values / a freshly minted seed);
        the resolved triple is echoed on the ``GenResult`` so the stream
        replays deterministically — same seed, same tokens, whatever
        else shares the batch. ``eos_id`` overrides the engine-wide stop
        token per request (the two causal-LM families use different eos
        ids; ``None`` keeps the config default).

        ``tenant`` labels THIS request for per-workload attribution
        (docs/observability.md "Wide events & tenant accounting"):
        it rides the trace, the terminal wide event, the
        ``consensusml_tenant_*`` labeled families, and the echoed
        ``GenResult``. ``None``/empty means ``"default"``; the label is
        sanitized at this boundary (obs/events.py).

        Raises ``queue.Full`` when the bounded queue is full (with
        ``block=False`` or after ``timeout``) and ``RuntimeError`` once
        the engine is draining — both count on
        ``consensusml_serve_rejected_total``.
        """
        max_new = (
            self.config.max_new_tokens if max_new_tokens is None else max_new_tokens
        )
        temp = self.config.temperature if temperature is None else float(temperature)
        tp = self.config.top_p if top_p is None else float(top_p)
        if temp < 0:
            raise ValueError(f"temperature must be >= 0, got {temp}")
        if not 0 < tp <= 1:
            raise ValueError(f"top_p must be in (0, 1], got {tp}")
        if seed is None:
            import os as _os

            # greedy lanes never consume the seed; sampled lanes get a
            # fresh one so independent requests draw independent streams
            seed = (
                0 if temp == 0
                else int.from_bytes(_os.urandom(4), "little")
            )
        seed = int(seed) & 0xFFFFFFFF
        eos = self.config.eos_id if eos_id is None else int(eos_id)
        if self._draining.is_set() or self._stop.is_set():
            self._m_rejected.inc()
            if self._error is not None:
                raise RuntimeError(
                    f"engine died on {type(self._error).__name__}: "
                    f"{self._error}"
                ) from self._error
            raise RuntimeError("engine is draining/closed; not accepting requests")
        if len(ids) < 1:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new_tokens must be positive, got {max_new}")
        if len(ids) + max_new > self.max_len:
            raise ValueError(
                f"prompt ({len(ids)}) + max_new_tokens ({max_new}) exceeds "
                f"the cache length {self.max_len}; shorten one or build the "
                "engine with a larger ServeConfig.max_len"
            )
        from consensusml_tpu.obs import TraceContext
        from consensusml_tpu.obs.events import sanitize_tenant

        tenant_s = sanitize_tenant(tenant)
        ctx = trace if trace is not None else TraceContext.mint("srv")
        handle = self._RequestHandle(len(ids))
        req = self._Request(
            list(map(int, ids)), max_new, handle, ctx=ctx,
            temperature=temp, top_p=tp, seed=seed, eos_id=eos,
            tenant=tenant_s,
        )
        self._rt.start(
            ctx, len(ids), max_new_tokens=max_new,
            generation=self._generation, tenant=tenant_s,
        )
        try:
            self._queue.put(req, block=block, timeout=timeout)
        except queue.Full:
            self._m_rejected.inc()
            self._rt.finish(ctx.request_id, "rejected", detail="queue_full")
            raise
        if self._drained.is_set():
            # lost the race against loop exit: the put landed after the
            # loop's final cancellation sweep and nothing will ever
            # service it — sweep again ourselves and refuse
            self._cancel_queued()
            self._m_rejected.inc()
            raise RuntimeError(
                "engine is draining/closed; not accepting requests"
            )
        self._m_requests.inc()
        self._m_queue.set(self._queue.qsize())
        return handle

    def score(self, ids) -> Any:
        """Prefill-only batch scoring: f32 logits ``(B, S, V)`` for a full
        token batch — the forward is traced identically to the held-out
        evaluator's, so an exported artifact scores BIT-EXACTLY what
        ``evaluate()``'s mean model scores (the golden parity test)."""
        import jax.numpy as jnp

        return self._score_fn(self._params, jnp.asarray(ids, jnp.int32))

    def warmup(self, buckets: Sequence[int] | None = None) -> dict[str, int]:
        """Compile the steady-state program set: the decode step plus one
        prefill per prompt bucket. Returns :meth:`compile_counts`.

        Runs on the caller's thread against a THROWAWAY cache of the same
        shapes (jit caches key on shape, so the executables are shared
        with the live path) — the engine thread may already be serving,
        and warmup must not mutate (or donate away) the cache it is
        using. In paged mode the throwaway pool's all-zero block table
        routes every warmup write into the trash block.

        The program FAMILIES compile on parallel chains (XLA releases
        the GIL): the full-prefill chain, the prefix-suffix chain, and
        their draft twins each thread a private throwaway cache through
        their bucket ladder, so arming the prefix cache (or a draft)
        widens warmup instead of lengthening it — wall time stays ~the
        longest single chain. Transient cost: one extra cache per
        ACTIVE chain (a prefix-off, non-speculative engine allocates
        exactly one, as before); memory-tight deployments can stage via
        repeated ``warmup(buckets=[b])`` calls.
        """
        import jax.numpy as jnp

        from consensusml_tpu.serve import decode as D

        s = self.config.num_slots
        toks = jnp.zeros((s,), jnp.int32)
        samp = (  # per-slot sampling arrays (all-greedy warms the same
            jnp.zeros((s,), jnp.float32),  # executable sampled lanes use)
            jnp.ones((s,), jnp.float32),
            jnp.zeros((s,), jnp.uint32),
        )
        samp1 = (jnp.float32(0.0), jnp.float32(1.0), jnp.uint32(0))
        if self.paged:
            from consensusml_tpu.serve import pool as P

            bs = self.config.block_size
            bks = list(buckets if buckets is not None else self.buckets)
            trash = jnp.int32(P.TRASH_BLOCK)

            def chain_target():
                pages = P.init_pages(self._dm, self._pool.num_blocks, bs)
                for b in bks:
                    ids = jnp.zeros((1, b), jnp.int32)
                    row = jnp.zeros((b // bs,), jnp.int32)
                    _tok, _logits, pages = self._prefill_fn(
                        self._params, pages, ids, jnp.int32(1), row, *samp1
                    )
                if self.spec is None:
                    # a speculative engine never runs the one-token
                    # decode step (_spec_step replaces it) — don't burn
                    # a compile on an executable that will not execute
                    table = jnp.zeros(
                        (s, self._pool.blocks_per_slot), jnp.int32
                    )
                    self._decode_fn(
                        self._params, pages, table, toks,
                        jnp.zeros_like(toks), *samp,
                    )
                else:
                    stable = jnp.zeros(
                        (s, self._pool.blocks_per_slot + self._spec_extra_cols),
                        jnp.int32,
                    )
                    dpg = P.init_pages(
                        self._draft_dm, self._pool.num_blocks, bs
                    )
                    props, q_sel, q_probs, _dpg = self._propose_fn(
                        self._draft_params, dpg, stable, toks,
                        jnp.zeros_like(toks), *samp,
                    )
                    self._verify_fn(
                        self._params, pages, stable, toks, props, q_sel,
                        q_probs, jnp.zeros_like(toks), *samp,
                    )

            def chain_prefix(dm, params, fn):
                # the prefix path's suffix buckets walk the SAME ladder
                # — compile each so a hit never compiles on the serving
                # thread (all-trash row + trash COW pair = no-op writes)
                pages = P.init_pages(dm, self._pool.num_blocks, bs)
                for b in bks:
                    ids = jnp.zeros((1, b), jnp.int32)
                    prow = jnp.zeros(
                        (self._pool.blocks_per_slot + b // bs,), jnp.int32
                    )
                    _t, _l, pages = fn(
                        params, pages, ids, jnp.int32(1), jnp.int32(0),
                        prow, trash, trash, *samp1,
                    )

            def chain_draft():
                dpages = P.init_pages(
                    self._draft_dm, self._pool.num_blocks, bs
                )
                for b in bks:
                    ids = jnp.zeros((1, b), jnp.int32)
                    row = jnp.zeros((b // bs,), jnp.int32)
                    _t, _l, dpages = self._draft_prefill_fn(
                        self._draft_params, dpages, ids, jnp.int32(1),
                        row, *samp1,
                    )

            chains = [chain_target]
            if self._prefix is not None:
                chains.append(
                    lambda: chain_prefix(
                        self._dm, self._params, self._prefix_prefill_fn
                    )
                )
            if self.spec is not None:
                chains.append(chain_draft)
                if self._prefix is not None:
                    chains.append(
                        lambda: chain_prefix(
                            self._draft_dm, self._draft_params,
                            self._draft_prefix_prefill_fn,
                        )
                    )
            if len(chains) == 1:
                chains[0]()
            else:
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(len(chains)) as ex:
                    futs = [ex.submit(c) for c in chains]
                    for f in futs:
                        f.result()  # re-raise any chain's failure here
            self._warmed.set()
            return self.compile_counts()
        cache = D.init_cache(self._dm, self.config.num_slots, self.max_len)
        for b in buckets if buckets is not None else self.buckets:
            ids = jnp.zeros((1, b), jnp.int32)
            _tok, _logits, cache = self._prefill_fn(
                self._params, cache, ids, jnp.int32(1), jnp.int32(0),
                *samp1,
            )
        self._decode_fn(
            self._params, cache, toks, jnp.zeros_like(toks), *samp
        )
        self._warmed.set()
        return self.compile_counts()

    def watch(self, path: str, poll_s: float = 0.25):
        """Arm the drain-free hot swap: poll ``path`` for a new artifact
        generation, stage it off-thread, flip between decode steps
        (:mod:`consensusml_tpu.serve.pool.hotswap`). On a speculative
        engine the watcher also stages the DRAFT artifact riding in the
        ``draft/`` subdirectory (``export_draft``) under the same
        generation counter, so target and draft flip together. Returns
        the watcher."""
        from consensusml_tpu.serve.pool import GenerationWatcher

        if self._watcher is not None:
            raise RuntimeError("engine is already watching an artifact dir")
        self._watcher = GenerationWatcher(
            path, current_generation=self._generation, poll_s=poll_s,
            stage_draft=self.spec is not None,
        )
        return self._watcher

    @property
    def generation(self) -> int:
        """Artifact generation currently serving (0 = direct params)."""
        return self._generation

    def _maybe_swap(self) -> None:
        """Engine-thread flip of a staged generation (between steps).

        The staged tree must match the live tree leaf-for-leaf — same
        structure, shapes, dtypes — or the compiled programs would
        recompile (or worse, serve garbage); a mismatch is rejected and
        counted, and the engine keeps serving the current generation.
        """
        if self._watcher is None:
            return
        sw = self._watcher.take()
        if sw is None:
            return
        import jax

        def _tree_matches(live, staged):
            if jax.tree.structure(live) != jax.tree.structure(staged):
                return False
            return all(
                a.shape == b.shape and a.dtype == b.dtype
                for a, b in zip(jax.tree.leaves(live), jax.tree.leaves(staged))
            )

        ok = _tree_matches(self._params, sw.params)
        if ok and self.spec is not None and sw.draft_params is not None:
            # the draft flips with the target or not at all — a target
            # from generation g+1 verifying a draft from g would still
            # be distribution-correct, but the staged PAIR is what the
            # export protocol promised, so a torn pair is rejected whole
            ok = _tree_matches(self._draft_params, sw.draft_params)
        if not ok:
            self._watcher.reject(sw)  # roll back: a fixed same-gen
            return  # re-export must be stageable
        self._params = sw.params
        if self.spec is not None and sw.draft_params is not None:
            self._draft_params = sw.draft_params
        self._generation = sw.generation
        self._params_nbytes = sum(
            int(x.nbytes) for x in jax.tree.leaves(sw.params)
        )
        self._m_params_bytes.set(self._params_nbytes)
        for _i, slot in self._table.active:
            slot.generation = sw.generation
            # a mid-stream generation flip is part of the request's
            # story: prefix decoded under g, suffix under g+1
            self._rt.event(
                self._rid(slot.request), "hotswap", generation=sw.generation
            )
        self._swaps += 1
        self._m_swaps.inc()
        self._m_generation.set(sw.generation)
        if self._prefix is not None:
            # stale-generation entries are already unreachable (lookups
            # key on the current generation); this reclaims them and
            # lets the pool stop favoring their blocks as cached
            self._prefix.drop_stale(sw.generation)
            self._m_prefix_entries.set(len(self._prefix))

    def compile_counts(self) -> dict[str, int]:
        """Jit-cache entry counts per program family — the
        zero-recompile-after-warmup assertion reads this."""
        out = {}
        fams = [
            ("prefill", self._prefill_fn),
            ("decode", self._decode_fn),
            ("score", self._score_fn),
        ]
        if self._prefix is not None:
            fams.append(("prefix_prefill", self._prefix_prefill_fn))
        if self.spec is not None:
            fams += [
                ("draft_prefill", self._draft_prefill_fn),
                ("propose", self._propose_fn),
                ("verify", self._verify_fn),
            ]
            if self._prefix is not None:
                fams.append(
                    ("draft_prefix_prefill", self._draft_prefix_prefill_fn)
                )
        for name, fn in fams:
            size = getattr(fn, "_cache_size", None)
            out[name] = int(size()) if size is not None else -1
        return out

    def register_costs(self, ledger: Any = None) -> dict[str, Any]:
        """Register every serving executable in the cost ledger
        (:mod:`consensusml_tpu.obs.costs`): one prefill row per prompt
        bucket, the one decode row, and the hot-swap staging transfer.

        AOT-lowers with shape structs mirroring the live call shapes —
        nothing executes, no cache is mutated, and the zero-recompile
        contract's :meth:`compile_counts` is byte-identical before and
        after (pinned by ``pytest -m profiling``). The price is one
        duplicate compile per executable on the caller's thread, so run
        it alongside :meth:`warmup`, not per request. Returns
        ``{name: ExecutableCost}``.
        """
        import jax
        import jax.numpy as jnp

        if ledger is None:
            from consensusml_tpu.obs import get_cost_ledger

            ledger = get_cost_ledger()
        st = lambda t: jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), t
        )
        params = st(self._params)
        rows: dict[str, Any] = {}
        base_meta = {
            "kv_impl": self.config.kv_impl,
            "num_slots": self.config.num_slots,
            "max_len": self.max_len,
        }
        if self.paged:
            from consensusml_tpu.models.paged_attention import (
                resolve_attention_impl,
            )
            from consensusml_tpu.serve.pool.stages import (
                decode_cost_args,
                make_paged_decode_fn,
                prefill_cost_args,
            )

            pages = st(self._pages)
            bs = self.config.block_size
            base_meta["attn_impl"] = self.attn_impl
            # the KERNEL-tier impl for the side-by-side ".fused" rows:
            # the engine's own tier when it already runs fused, else
            # the auto resolution (pallas on TPU, interpret elsewhere —
            # never the gather reference)
            fused_impl = (
                self.attn_impl
                if self.attn_impl in ("interpret", "pallas")
                else resolve_attention_impl("auto")
            )
            for b in self.buckets:
                name = f"serve.prefill.b{b}"
                rows[name] = ledger.register(
                    name, self._prefill_fn, params, pages,
                    *prefill_cost_args(b, bs),
                    meta={**base_meta, "bucket": b, "block_size": bs},
                )
            if self._prefix is not None:
                from consensusml_tpu.serve.pool.stages import (
                    prefix_prefill_cost_args,
                )

                # one row per SUFFIX bucket: _request_cost joins each
                # prefix-hit admission against the bucket that actually
                # ran, so a 32-prompt admitted on an 8-token suffix is
                # charged the b8 executable, not the b32 one
                for b in self.buckets:
                    name = f"serve.prefix_prefill.b{b}"
                    rows[name] = ledger.register(
                        name, self._prefix_prefill_fn, params, pages,
                        *prefix_prefill_cost_args(
                            b, bs, self._pool.blocks_per_slot
                        ),
                        meta={**base_meta, "bucket": b, "block_size": bs},
                    )
            rows["serve.decode"] = ledger.register(
                "serve.decode", self._decode_fn, params, pages,
                *decode_cost_args(
                    self.config.num_slots, self._pool.blocks_per_slot
                ),
                meta={
                    **base_meta,
                    "num_blocks": self._pool.num_blocks,
                    "block_size": bs,
                },
            )
            # the fused decode step as its OWN row, so the attribution
            # table shows fused vs gather side by side (same shapes,
            # same load; AOT-only — no jit dispatch cache is touched)
            fused_decode_fn = (
                self._decode_fn
                if self.attn_impl == fused_impl
                else make_paged_decode_fn(self._dm, attn_impl=fused_impl)
            )
            rows["serve.decode.fused"] = ledger.register(
                "serve.decode.fused", fused_decode_fn, params, pages,
                *decode_cost_args(
                    self.config.num_slots, self._pool.blocks_per_slot
                ),
                meta={
                    **base_meta,
                    "attn_impl": fused_impl,
                    "num_blocks": self._pool.num_blocks,
                    "block_size": bs,
                },
            )
            if self.spec is not None:
                from consensusml_tpu.serve.pool.spec import (
                    propose_cost_args,
                    spec_table_cols,
                    verify_cost_args,
                )

                k = self.spec.k
                cols = spec_table_cols(self._pool.blocks_per_slot, bs, k)
                dparams = st(self._draft_params)
                dpages = st(self._draft_pages)
                spec_meta = {**base_meta, "k": k}
                for b in self.buckets:
                    name = f"serve.draft_prefill.b{b}"
                    rows[name] = ledger.register(
                        name, self._draft_prefill_fn, dparams, dpages,
                        *prefill_cost_args(b, bs),
                        meta={**spec_meta, "bucket": b, "block_size": bs},
                    )
                if self._prefix is not None:
                    from consensusml_tpu.serve.pool.stages import (
                        prefix_prefill_cost_args,
                    )

                    for b in self.buckets:
                        name = f"serve.draft_prefix_prefill.b{b}"
                        rows[name] = ledger.register(
                            name, self._draft_prefix_prefill_fn, dparams,
                            dpages,
                            *prefix_prefill_cost_args(
                                b, bs, self._pool.blocks_per_slot
                            ),
                            meta={
                                **spec_meta, "bucket": b, "block_size": bs,
                            },
                        )
                rows["serve.spec.propose"] = ledger.register(
                    "serve.spec.propose", self._propose_fn, dparams,
                    dpages,
                    *propose_cost_args(self.config.num_slots, cols),
                    meta=spec_meta,
                )
                rows["serve.spec.verify"] = ledger.register(
                    "serve.spec.verify", self._verify_fn, params, pages,
                    *verify_cost_args(
                        self.config.num_slots, cols, k,
                        self._dm.vocab_size,
                    ),
                    meta=spec_meta,
                )
                from consensusml_tpu.serve.pool.spec import make_verify_fn

                fused_verify_fn = (
                    self._verify_fn
                    if self.attn_impl == fused_impl
                    else make_verify_fn(self._dm, k, attn_impl=fused_impl)
                )
                rows["serve.spec.verify.fused"] = ledger.register(
                    "serve.spec.verify.fused", fused_verify_fn, params,
                    pages,
                    *verify_cost_args(
                        self.config.num_slots, cols, k,
                        self._dm.vocab_size,
                    ),
                    meta={**spec_meta, "attn_impl": fused_impl},
                )
        else:
            cache = st(self._cache)
            samp1 = (
                jax.ShapeDtypeStruct((), jnp.float32),
                jax.ShapeDtypeStruct((), jnp.float32),
                jax.ShapeDtypeStruct((), jnp.uint32),
            )
            for b in self.buckets:
                name = f"serve.prefill.b{b}"
                rows[name] = ledger.register(
                    name, self._prefill_fn, params, cache,
                    jax.ShapeDtypeStruct((1, b), jnp.int32),
                    jax.ShapeDtypeStruct((), jnp.int32),
                    jax.ShapeDtypeStruct((), jnp.int32),
                    *samp1,
                    meta={**base_meta, "bucket": b},
                )
            toks = jax.ShapeDtypeStruct((self.config.num_slots,), jnp.int32)
            f32s = jax.ShapeDtypeStruct((self.config.num_slots,), jnp.float32)
            u32s = jax.ShapeDtypeStruct((self.config.num_slots,), jnp.uint32)
            rows["serve.decode"] = ledger.register(
                "serve.decode", self._decode_fn, params, cache, toks, toks,
                f32s, f32s, u32s,
                meta=base_meta,
            )
        # the hot-swap stage is a transfer, not a program: restore +
        # device_put of one params tree on the watcher thread
        rows["serve.hotswap.stage"] = ledger.register_transfer(
            "serve.hotswap.stage", self._params,
            meta={**base_meta, "generation": self._generation},
        )
        self._cost_ledger = ledger
        return rows

    @property
    def warmed(self) -> bool:
        """True once a :meth:`warmup` has completed — the readiness bit
        ``/healthz`` (and a fleet router's placement) gates on."""
        return self._warmed.is_set()

    def drain(self, timeout: float | None = None) -> bool:
        """Stop admitting; serve everything queued + in flight to
        completion. Returns True when fully drained (the SIGTERM path —
        see :class:`consensusml_tpu.serve.server.ServeServer`)."""
        self._draining.set()
        return self._drained.wait(timeout)

    def shutdown(self, drain: bool = True, timeout: float | None = None) -> None:
        if drain:
            self.drain(timeout)
        self._stop.set()
        self._thread.join(timeout=5.0)
        if self._watcher is not None:
            self._watcher.stop()

    def __enter__(self) -> "Engine":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=exc == (None, None, None))

    def stats(self) -> dict[str, Any]:
        """Host-side SLO summary (the bench's serving section reads this;
        Prometheus scrapes the registry for the live families).
        Percentiles cover the last 4096 samples; totals are lifetime."""
        pct = lambda xs, q: (
            float(np.percentile(list(xs), q)) if xs else float("nan")
        )
        decode_time = self._decode_time_s
        out = {
            "kv_impl": self.config.kv_impl,
            "attn_impl": self.attn_impl,
            "tokens_in": self._tokens_in,
            "tokens_out": self._tokens_out,
            "decode_steps": self._decode_steps,
            "ttft_p50_ms": 1e3 * pct(self._ttfts, 50),
            "ttft_p99_ms": 1e3 * pct(self._ttfts, 99),
            "intertoken_p50_ms": 1e3 * pct(self._step_times, 50),
            "intertoken_p99_ms": 1e3 * pct(self._step_times, 99),
            "mean_batch_occupancy": (
                self._occupancy_sum / self._decode_steps
                if self._decode_steps
                else 0.0
            ),
            "decode_tokens_per_sec": (
                self._tokens_out / decode_time if decode_time > 0 else 0.0
            ),
            "generation": self._generation,
            "warmed": self.warmed,
            "swaps": self._swaps,
            "evictions": self._evictions,
            "compile_counts": self.compile_counts(),
        }
        if self.paged:
            out["pool"] = {
                "num_blocks": self._pool.num_blocks,
                "block_size": self._pool.block_size,
                "usable_blocks": self._pool.usable_blocks,
                "free_blocks": self._pool.free_blocks,
                "mean_block_occupancy": (
                    self._block_occupancy_sum / self._decode_steps
                    if self._decode_steps
                    else 0.0
                ),
            }
            # on every paged engine (prefix-off baselines report the
            # same field): padded tokens the prefill executables
            # actually computed — the number prefix sharing shrinks
            out["prefill_tokens_computed"] = self._prefill_tokens_computed
            if self._prefix is not None:
                lookups = self._prefix_hits + self._prefix_misses
                out["prefix_cache"] = {
                    "hits": self._prefix_hits,
                    "misses": self._prefix_misses,
                    "hit_rate": (
                        self._prefix_hits / lookups if lookups else 0.0
                    ),
                    "hit_blocks": self._prefix_hit_blocks,
                    "cow_copies": self._prefix_cow_copies,
                    "bytes_saved": self._prefix_bytes_saved,
                    "entries": len(self._prefix),
                    "indexed_blocks": self._prefix.indexed_blocks,
                    "shared_blocks": self._pool.shared_blocks,
                    "invalidations": self._prefix.invalidations,
                }
        if self.spec is not None:
            out["spec"] = {
                "k": self.spec.k,
                "rounds": self._spec_rounds,
                "proposed": self._spec_proposed,
                "accepted": self._spec_accepted,
                "acceptance_rate": (
                    self._spec_accepted / self._spec_proposed
                    if self._spec_proposed
                    else 0.0
                ),
                # round-emitted tokens only (prefill firsts excluded),
                # so intertoken_seconds / tokens_per_round is the honest
                # per-token gap (docs/observability.md)
                "tokens_per_round": (
                    self._spec_tokens / self._spec_rounds
                    if self._spec_rounds
                    else 0.0
                ),
            }
        return out

    # -- engine thread ------------------------------------------------------

    def _loop(self) -> None:
        q = self._queue
        try:
            while not self._stop.is_set():
                self._m_loop_heartbeat.set(time.time())
                self._maybe_swap()  # flip a staged generation between steps
                if self._sched is not None:
                    self._sched.start_tick()
                self._admit_waiting()
                if self._table.num_active:
                    if self.spec is not None:
                        self._spec_step()
                    else:
                        self._decode_step()
                    continue
                if self._draining.is_set() and q.empty() and not self._requeue:
                    break
                if self._requeue:  # deferred by budget; retry next tick
                    continue
                try:
                    req = q.get(timeout=self.config.idle_wait_s)
                except queue.Empty:
                    continue
                self._m_queue.set(q.qsize())
                # route through _admit_waiting's capacity/budget gate
                # next iteration — a direct _admit here would bypass the
                # pool's can_admit check and lean on a hidden
                # pool-empty-when-idle invariant
                self._requeue.append(req)
        except BaseException as e:
            # a device error mid-serving (OOM compiling a bucket, bad
            # params) must not leave clients parked on silent handles:
            # mark the engine dead (submit refuses from here on), fail
            # everything in flight, and re-raise so the thread's death is
            # loud in logs rather than a mystery hang
            self._error = e
            raise
        finally:
            self._stop.set()
            self._draining.set()
            # cancel loudly: in-flight slots and queued requests get a
            # terminal "cancelled" result instead of a hung handle
            for i, slot in self._table.active:
                self._table.release(i)
                if self.paged:
                    # settle block-seconds for the wide event; the pool
                    # itself is NOT released here (unchanged: the
                    # process is exiting, nothing re-admits)
                    self._settle_block_seconds(slot.request, i)
                self._finish_handle(
                    slot.request, slot.request.handle._all, "cancelled"
                )
            self._cancel_queued()
            self._drained.set()

    def _cancel_queued(self) -> None:
        """Drain-and-cancel everything in the submit queue. Called by the
        loop at exit AND by submit() when it loses the race against loop
        exit (its put landed after the loop's final sweep) — once
        ``_drained`` is set nothing services the queue, so cancelling is
        always correct, and the thread-safe ``get_nowait`` hands each
        request to exactly one canceller."""
        while self._requeue:
            try:
                req = self._requeue.popleft()
            except IndexError:
                break
            self._finish_handle(req, req.handle._all, "cancelled")
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            self._finish_handle(req, [], "cancelled")

    def _pop_waiting(self):
        """Next admission candidate: evicted continuations first (their
        tokens are already streaming to a client), then fresh arrivals."""
        if self._requeue:
            return self._requeue.popleft()
        req = self._queue.get_nowait()
        self._m_queue.set(self._queue.qsize())
        return req

    def _admit_waiting(self) -> None:
        while self._table.free_slot() is not None:
            try:
                req = self._pop_waiting()
            except queue.Empty:
                return
            plan = None
            if self.paged:
                from consensusml_tpu.serve.pool import blocks_for_tokens

                # the prefix plan is re-derived on EVERY attempt — a
                # deferred tick may see matched blocks recycled (or new
                # ones indexed) in the meantime, and the capacity/budget
                # charge below must match the plan that will actually run
                plan = self._prefix_plan(req)
                if plan is None:
                    bucket = self._bucket(len(req.ids))
                    need = blocks_for_tokens(
                        len(req.ids) + 1, self.config.block_size
                    )
                else:
                    # charge only what the prefix path consumes: fresh
                    # pops + free-list revivals of cached matched
                    # blocks, and the SUFFIX bucket against the budget
                    bucket = plan["bucket"]
                    need = plan["free_needed"]
                # defer (don't drop) when this tick's prefill budget is
                # spent or the pool can't hold the prompt yet; the
                # request keeps its place at the head of the line —
                # every deferred tick lands on the request's trace, so
                # a long admission wait is attributable, not invisible
                if not self._pool.can_admit(need):
                    self._rt.event(
                        self._rid(req), "admission.defer", reason="blocks"
                    )
                    self._requeue.appendleft(req)
                    return
                if not self._sched.try_admit(bucket):
                    self._rt.event(
                        self._rid(req), "admission.defer", reason="budget"
                    )
                    self._requeue.appendleft(req)
                    return
            self._admit(req, plan)

    @staticmethod
    def _rid(req) -> str | None:
        """The request's trace id, when it carries one (requests built
        outside submit() — direct Request() in tests — may not)."""
        return getattr(req.ctx, "request_id", None)

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(f"prompt length {n} exceeds max bucket {self.buckets[-1]}")

    def _prefix_plan(self, req) -> dict | None:
        """Resolve ``req``'s admission against the prefix index: None =
        run the full-causal prefill (index off, or nothing matched).
        Otherwise a plan naming the blocks to adopt, the copy-on-write
        source (a FULL match diverges inside its last block: the slot
        re-points at a fresh page, the jit copies the shared rows over,
        and only the final token recomputes), the suffix start/length,
        and the free-block cost (fresh pops + revivals of matched blocks
        currently parked on the free list)."""
        if self._prefix is None:
            return None
        from consensusml_tpu.serve.pool import blocks_for_tokens

        n = len(req.ids)
        match = self._prefix.lookup(req.tenant, self._generation, req.ids)
        if not match:
            return None
        bs = self.config.block_size
        full = len(match) * bs == n
        if full:
            # every prompt block is indexed; the admission still needs
            # last-token logits to sample from, and that recompute's
            # K/V write lands INSIDE the final matched block, which
            # other holders share — so the final block becomes the COW
            # pair and only the prefix before it is adopted outright.
            # (The first decode write at position n opens a fresh block
            # — n % bs == 0 on a full match — so it never collides.)
            adopted = match[:-1]
            cow_src = match[-1]
            start = n - 1
        else:
            adopted = match
            cow_src = None
            start = len(match) * bs
        suffix_len = n - start
        total = blocks_for_tokens(n + 1, bs)
        fresh = total - len(adopted)
        revive = sum(
            1 for b in adopted if self._pool.refcount(b) == 0
        )
        if cow_src is not None and self._pool.refcount(cow_src) == 0:
            revive += 1
        return {
            "match": match,
            "adopted": adopted,
            "cow_src": cow_src,
            "start": start,
            "suffix_len": suffix_len,
            "bucket": self._bucket(suffix_len),
            "fresh": fresh,
            "free_needed": fresh + revive,
        }

    def _admit(self, req, plan=None) -> None:
        """Prefill ``req`` into a free slot (admission = one bucketed
        forward that seeds the slot cache and the first token). A raise
        mid-admission cancels THIS request's handle before propagating —
        at that point it is out of the queue but not yet in the slot
        table, so neither of the loop's exit sweeps would reach it."""
        try:
            self._admit_inner(req, plan)
        except BaseException:
            self._finish_handle(req, req.handle._all, "cancelled")
            raise

    def _admit_inner(self, req, plan=None) -> None:
        import jax.numpy as jnp

        from consensusml_tpu.serve.batcher import Slot

        idx = self._table.free_slot()
        assert idx is not None, "admission with no free slot"
        n = len(req.ids)
        kind = "prefix" if plan is not None else "full"
        bucket = plan["bucket"] if plan is not None else self._bucket(n)
        # an evicted continuation re-prefills prompt + generated-so-far;
        # its TTFT already happened and its token count keeps running
        already = len(req.handle._all)
        # every admission's bucket feeds the wide event's cost join —
        # a continuation re-prefills (a real forward) into a possibly
        # larger bucket, and each one is paid for. The kind picks which
        # ledger row the bucket joins (full vs prefix executable).
        req.prefill_buckets.append(bucket)
        req.prefill_kinds.append(kind)
        ids = np.zeros((1, bucket), np.int32)
        if plan is not None:
            ids[0, : plan["suffix_len"]] = req.ids[plan["start"] :]
        else:
            ids[0, :n] = req.ids
        self._rt.event(
            self._rid(req), "admission", slot=idx, bucket=bucket,
            continuation=bool(already), prefix_blocks=(
                len(plan["match"]) if plan is not None else 0
            ),
        )
        t0 = time.perf_counter()
        samp = (
            jnp.float32(req.temperature),
            jnp.float32(req.top_p),
            jnp.uint32(req.seed),
        )
        with self._tracer.span("serve.prefill", bucket=bucket, slot=idx):
            if self.paged and plan is not None:
                tok_dev = self._prefix_admit(idx, req, plan, bucket, ids, samp)
            elif self.paged:
                from consensusml_tpu.serve.pool import blocks_for_tokens

                if self._prefix is not None:
                    self._prefix_misses += 1
                    self._m_prefix_misses.inc()
                bs = self.config.block_size
                # cover the prompt AND the first decode write (position n)
                self._pool.alloc(idx, blocks_for_tokens(n + 1, bs))
                try:
                    row = jnp.asarray(self._pool.block_row(idx, bucket // bs))
                    tok_dev, _logits, self._pages = self._prefill_fn(
                        self._params,
                        self._pages,
                        jnp.asarray(ids),
                        jnp.int32(n),
                        row,
                        *samp,
                    )
                    if self.spec is not None:
                        # the draft's pages need the prompt too: same
                        # block row, the draft's own page arrays (its
                        # sampled token is discarded — the target's is
                        # the stream's first token)
                        _dt, _dl, self._draft_pages = self._draft_prefill_fn(
                            self._draft_params,
                            self._draft_pages,
                            jnp.asarray(ids),
                            jnp.int32(n),
                            row,
                            *samp,
                        )
                except BaseException:
                    self._pool.release(idx)  # no leaked blocks on a raise
                    raise
            else:
                tok_dev, _logits, self._cache = self._prefill_fn(
                    self._params,
                    self._cache,
                    jnp.asarray(ids),
                    jnp.int32(n),
                    jnp.int32(idx),
                    *samp,
                )
            tok = int(tok_dev)  # device fence: the first token is real now
        if self.paged:
            # target-model tokens the prefill executable computed (the
            # padded bucket — what the device actually ran); prefix hits
            # shrink this to the suffix bucket
            self._prefill_tokens_computed += bucket
            if self._prefix is not None:
                # index this admission's full PROMPT chunks only —
                # positions a PREFILL trace wrote. A continuation's
                # decode-generated tokens stay unindexed: decode-written
                # K/V is only bit-identical to itself, and the index
                # must never serve bytes a fresh full prefill would not
                # reproduce exactly. First writer wins, so a hit
                # admission re-asserts its adopted entries at zero cost.
                self._prefix.insert(
                    req.tenant, self._generation,
                    req.ids[: req.handle.prompt_len],
                    self._pool.owned(idx),
                )
                self._m_prefix_entries.set(len(self._prefix))
                self._m_prefix_shared_blocks.set(self._pool.shared_blocks)
        now = time.perf_counter()
        rid = self._rid(req)
        self._m_prefill.observe(now - t0, exemplar=rid)
        self._rt.event(
            rid, "prefill", bucket=bucket, seconds=round(now - t0, 6)
        )
        ttft = now - req.arrival_t
        if already == 0:
            self._m_ttft.observe(ttft, exemplar=rid)
            self._ttfts.append(ttft)
            req.handle._ttft_s = ttft
            self._tokens_in += n
            self._tenant_metrics(req.tenant)["ttft"].observe(
                ttft, exemplar=rid
            )
        else:  # continuation: the stream's real TTFT already happened
            ttft = getattr(req.handle, "_ttft_s", 0.0)
        req.handle._emit(tok)
        self._m_tokens.inc()
        self._tokens_out += 1
        if already + 1 >= req.max_new_tokens or tok == req.eos_id:
            reason = "eos" if tok == req.eos_id else "max_tokens"
            if self.paged:
                self._settle_block_seconds(req, idx)
                self._pool.release(idx)
            self._finish_handle(req, req.handle._all, reason, ttft=ttft)
            return
        self._table.occupy(
            idx,
            Slot(
                request=req, next_pos=n, pending=tok, generated=already + 1,
                ttft_s=ttft, last_token_t=now, generation=self._generation,
            ),
        )

    def _settle_block_seconds(self, req, idx) -> None:
        """Fold slot ``idx``'s hold-time integral onto ``req`` before
        its references go back: unshared hold is CHARGED to the request
        (the wide event's block_seconds), prefix-shared hold is
        attributed separately (shared_block_seconds) — a request never
        pays for blocks the cache kept alive anyway."""
        unshared, shared = self._pool.block_seconds_split(idx)
        req.block_seconds += unshared
        req.shared_block_seconds += shared

    def _prefix_admit(self, idx, req, plan, bucket, ids, samp):
        """Run one prefix-hit admission's device work: adopt the
        matched blocks, pop fresh ones for the suffix, and dispatch the
        suffix-window prefill (plus the draft's, on a spec engine —
        draft pages share the block geometry, so the hit skips the
        draft prefill too). Returns the sampled first-token device
        value; on a raise the slot's references are fully unwound."""
        import jax.numpy as jnp

        from consensusml_tpu.serve.pool import TRASH_BLOCK

        bs = self.config.block_size
        pool = self._pool
        pool.begin(idx)  # outside the unwind: a double-alloc raise here
        pinned = None  # must not release the EXISTING owner's blocks
        try:
            pool.adopt(idx, plan["adopted"])
            if plan["cow_src"] is not None:
                # hold the source across the dispatch: the extend below
                # must not pop it off the free list (a cached-free
                # match) and hand it out as this slot's "fresh" page
                pool.pin(plan["cow_src"])
                pinned = plan["cow_src"]
            fresh = pool.extend(idx, plan["fresh"])
            if plan["cow_src"] is not None:
                cow_src, cow_dst = plan["cow_src"], fresh[0]
            else:
                cow_src = cow_dst = TRASH_BLOCK
            row = jnp.asarray(
                pool.block_row(idx, pool.blocks_per_slot + bucket // bs)
            )
            tok_dev, _logits, self._pages = self._prefix_prefill_fn(
                self._params,
                self._pages,
                jnp.asarray(ids),
                jnp.int32(plan["suffix_len"]),
                jnp.int32(plan["start"]),
                row,
                jnp.int32(cow_src),
                jnp.int32(cow_dst),
                *samp,
            )
            if self.spec is not None:
                _dt, _dl, self._draft_pages = self._draft_prefix_prefill_fn(
                    self._draft_params,
                    self._draft_pages,
                    jnp.asarray(ids),
                    jnp.int32(plan["suffix_len"]),
                    jnp.int32(plan["start"]),
                    row,
                    jnp.int32(cow_src),
                    jnp.int32(cow_dst),
                    *samp,
                )
        except BaseException:
            if pinned is not None:
                pool.unpin(pinned)
            pool.release(idx)  # no leaked references on a raise
            raise
        if pinned is not None:
            # the dispatch is in the device stream; any later write to
            # the source block is ordered after this read completes
            pool.unpin(pinned)
        hit_blocks = len(plan["match"])
        req.prefix_hit_blocks += hit_blocks
        self._prefix_hits += 1
        self._m_prefix_hits.inc()
        self._prefix_hit_blocks += hit_blocks
        self._m_prefix_hit_blocks.inc(hit_blocks)
        if plan["cow_src"] is not None:
            self._prefix_cow_copies += 1
            self._m_prefix_cow_copies.inc()
        saved = hit_blocks * self._block_nbytes
        if self.spec is not None:
            saved += hit_blocks * self._draft_block_nbytes
        self._prefix_bytes_saved += saved
        self._m_prefix_bytes_saved.inc(saved)
        return tok_dev

    def _youngest_active(self) -> int:
        """Eviction victim: the most recently arrived stream (it has the
        least sunk work to recompute and the fewest tokens streamed)."""
        return max(
            self._table.active,
            key=lambda t: (t[1].request.arrival_t, t[0]),
        )[0]

    def _evict(self, idx: int) -> None:
        """Recompute-preemption: free ``idx``'s blocks and re-enqueue its
        stream as prompt + everything generated so far. The re-prefill
        seeds the continuation's cache and next token, so the client's
        stream continues — tokens already emitted stand, none drop."""
        slot = self._table.release(idx)
        req = slot.request
        # settle the hold-time integral before the blocks go back; the
        # re-admission restarts the clock on a fresh allocation
        self._settle_block_seconds(req, idx)
        self._pool.release(idx)
        # req.ids may itself be a continuation; the first prompt_len ids
        # are always the original prompt
        req.ids = list(req.ids[: req.handle.prompt_len]) + list(
            req.handle._all
        )
        self._rt.event(
            self._rid(req), "preempt", reason="blocks_exhausted",
            generated=len(req.handle._all),
        )
        # head of the line, AHEAD of any budget-deferred fresh arrival
        # (its tokens are already streaming to a client; a fresh request
        # admitted first could consume the very blocks it needs)
        self._requeue.appendleft(req)
        self._evictions += 1
        self._m_evictions.inc()

    def _grow_blocks(self, extra_tokens: int = 0) -> None:
        """Before a paged step: give every lane the blocks its writes
        need — the next position, plus ``extra_tokens`` more for a
        speculative verify window — evicting youngest-first when the
        pool is exhausted (the lane needing the block may itself be the
        youngest — then it preempts itself and re-enters via requeue).
        Window positions past ``blocks_per_slot`` are NOT allocated:
        they overflow into the trash-padded table columns by design."""
        bs = self.config.block_size
        bps = self._pool.blocks_per_slot
        for i, _slot in self._table.active:
            while True:
                slot = self._table.slots[i]
                if slot is None:
                    break  # evicted while resolving an earlier lane
                target = min(
                    bps, (slot.next_pos + extra_tokens) // bs + 1
                )
                if len(self._pool.owned(i)) >= target:
                    break  # this step's write blocks are already owned
                from consensusml_tpu.serve.pool import NoFreeBlocks

                try:
                    self._pool.extend(i, 1)
                except NoFreeBlocks:
                    victim = self._youngest_active()
                    self._evict(victim)
                    if victim == i:
                        break

    def _decode_step(self) -> None:
        import jax.numpy as jnp

        if self.paged:
            self._grow_blocks()
            if not self._table.num_active:  # everything preempted
                return
        active = self._table.active
        s = self.config.num_slots
        tokens, positions, temps, tops, seeds = self._slot_arrays(active)
        t0 = time.perf_counter()
        with self._tracer.span("serve.decode_step", active=len(active)):
            if self.paged:
                next_dev, self._pages = self._decode_fn(
                    self._params,
                    self._pages,
                    self._pool.device_table(),
                    jnp.asarray(tokens),
                    jnp.asarray(positions),
                    jnp.asarray(temps),
                    jnp.asarray(tops),
                    jnp.asarray(seeds),
                )
            else:
                next_dev, self._cache = self._decode_fn(
                    self._params, self._cache, jnp.asarray(tokens),
                    jnp.asarray(positions), jnp.asarray(temps),
                    jnp.asarray(tops), jnp.asarray(seeds),
                )
            next_toks = np.asarray(next_dev)  # device fence per step
        dt = time.perf_counter() - t0
        now = time.perf_counter()
        # exemplar: the oldest resident stream — the one that has been
        # paying this step time the longest — stands in for the batch
        self._m_intertoken.observe(
            dt,
            exemplar=self._rid(
                min(active, key=lambda t: t[1].request.arrival_t)[1].request
            ),
        )
        self._step_times.append(dt)
        self._decode_time_s += dt
        self._decode_steps += 1
        self._occupancy_sum += len(active) / s
        self._m_occupancy.set(len(active) / s)
        if dt > 0:
            self._m_tps.set(len(active) / dt)
        if self.paged:
            occ = self._pool.used_blocks / self._pool.usable_blocks
            self._block_occupancy_sum += occ
            self._m_block_occ.set(occ)
            self._m_blocks_free.set(self._pool.free_blocks)
            self._m_pool_hbm_free.set(
                self._pool.free_blocks * self._block_nbytes
            )
        # one lock round-trip covers every resident slot's tick
        self._rt.decode_ticks(
            [self._rid(slot.request) for _i, slot in active]
        )
        for _i, slot in active:
            slot.request.decode_ticks += 1  # wide-event cost join
        for i, slot in active:
            self._emit_and_advance(i, slot, [int(next_toks[i])], now)

    def _slot_arrays(self, active):
        """Fixed-shape per-slot host arrays for one device step: pending
        tokens, write positions, and each lane's sampling triple (free
        lanes stay zero — greedy over garbage into the trash block)."""
        s = self.config.num_slots
        tokens = np.zeros((s,), np.int32)
        positions = np.zeros((s,), np.int32)
        temps = np.zeros((s,), np.float32)
        tops = np.ones((s,), np.float32)
        seeds = np.zeros((s,), np.uint32)
        for i, slot in active:
            tokens[i] = slot.pending
            positions[i] = slot.next_pos
            temps[i] = slot.request.temperature
            tops[i] = slot.request.top_p
            seeds[i] = slot.request.seed
        return tokens, positions, temps, tops, seeds

    def _emit_and_advance(self, i, slot, toks, now) -> int:
        """Emit ``toks`` (one decode token, or a verify round's accepted
        prefix + final) on lane ``i``, advancing position/generation
        bookkeeping one token at a time so eos / token-cap / length
        stops land at the exact right token — tokens past the stop are
        dropped, not emitted. Returns the number actually emitted."""
        req = slot.request
        for emitted, tok in enumerate(toks, start=1):
            req.handle._emit(tok)
            self._m_tokens.inc()
            self._tokens_out += 1
            slot.generated += 1
            slot.next_pos += 1
            slot.pending = tok
            slot.last_token_t = now
            reason = None
            if tok == req.eos_id:
                reason = "eos"
            elif slot.generated >= req.max_new_tokens:
                reason = "max_tokens"
            elif slot.next_pos >= self.max_len:
                reason = "length"  # safety net; submit() validation bounds it
            if reason is not None:
                self._table.release(i)
                if self.paged:
                    self._settle_block_seconds(req, i)
                    self._pool.release(i)
                self._finish_handle(
                    req, req.handle._all, reason,
                    ttft=slot.ttft_s, generation=slot.generation,
                )
                return emitted
        return len(toks)

    def _spec_step(self) -> None:
        """One speculative round: draft proposes ``k`` tokens per lane
        (one scan executable), the target verifies ALL lanes' windows in
        ONE fused forward, and each lane commits its accepted prefix +
        the replacement/bonus token — 1 to ``k + 1`` tokens per lane per
        round, two device dispatches, one host fence."""
        k = self.spec.k
        self._grow_blocks(extra_tokens=k)
        if not self._table.num_active:  # everything preempted
            return
        import jax.numpy as jnp

        active = self._table.active
        tokens, positions, temps, tops, seeds = self._slot_arrays(active)
        table = self._pool.device_table(self._spec_extra_cols)
        t0 = time.perf_counter()
        with self._tracer.span("serve.spec_step", active=len(active), k=k):
            props_dev, q_sel, q_probs, self._draft_pages = self._propose_fn(
                self._draft_params,
                self._draft_pages,
                table,
                jnp.asarray(tokens),
                jnp.asarray(positions),
                jnp.asarray(temps),
                jnp.asarray(tops),
                jnp.asarray(seeds),
            )
            n_acc_dev, final_dev, self._pages = self._verify_fn(
                self._params,
                self._pages,
                table,
                jnp.asarray(tokens),
                props_dev,
                q_sel,
                q_probs,
                jnp.asarray(positions),
                jnp.asarray(temps),
                jnp.asarray(tops),
                jnp.asarray(seeds),
            )
            props = np.asarray(props_dev)  # device fence per round
            n_acc = np.asarray(n_acc_dev)
            finals = np.asarray(final_dev)
        dt = time.perf_counter() - t0
        now = time.perf_counter()
        self._m_intertoken.observe(
            dt,
            exemplar=self._rid(
                min(active, key=lambda t: t[1].request.arrival_t)[1].request
            ),
        )
        self._step_times.append(dt)
        self._decode_time_s += dt
        self._decode_steps += 1
        s = self.config.num_slots
        self._occupancy_sum += len(active) / s
        self._m_occupancy.set(len(active) / s)
        self._rt.decode_ticks(
            [self._rid(slot.request) for _i, slot in active]
        )
        round_emitted = 0
        round_accepted = 0
        # per-stream accounting lands BEFORE emission: a request this
        # round finishes must carry its final round on its trace too
        spec_rows = []
        for i, slot in active:
            n = int(n_acc[i])
            req = slot.request
            req.spec_proposed += k
            req.spec_accepted += n
            req.decode_ticks += 1  # one spec round = one resident tick
            round_accepted += n
            spec_rows.append((self._rid(req), k, n))
        self._rt.spec_ticks(spec_rows)
        for i, slot in active:
            n = int(n_acc[i])
            toks = [int(props[i, j]) for j in range(n)] + [int(finals[i])]
            round_emitted += self._emit_and_advance(i, slot, toks, now)
        if self._pool.free_blocks == 0:
            # rejected-suffix rollback, lazily: positions rolled back
            # above (next_pos only advanced past the accepted prefix);
            # the over-allocated window-tail blocks are handed back only
            # under pool pressure — otherwise the very next round would
            # re-extend the same lanes and churn the device block table
            # every round for nothing
            bs = self.config.block_size
            for i, slot in self._table.active:
                self._pool.shrink(i, slot.next_pos // bs + 1)
        self._spec_rounds += 1
        self._spec_proposed += k * len(active)
        self._spec_accepted += round_accepted
        self._spec_tokens += round_emitted
        self._m_spec_rounds.inc()
        self._m_spec_proposed.inc(k * len(active))
        self._m_spec_accepted.inc(round_accepted)
        self._m_spec_rate.set(
            self._spec_accepted / self._spec_proposed
            if self._spec_proposed
            else 0.0
        )
        if dt > 0:
            self._m_tps.set(round_emitted / dt)
        occ = self._pool.used_blocks / self._pool.usable_blocks
        self._block_occupancy_sum += occ
        self._m_block_occ.set(occ)
        self._m_blocks_free.set(self._pool.free_blocks)
        self._m_pool_hbm_free.set(
            self._pool.free_blocks * self._block_nbytes
        )

    def _tenant_metrics(self, tenant: str) -> dict[str, Any]:
        """The ``consensusml_tenant_*`` labeled children for one tenant,
        created lazily on first touch and cached. Labeled by tenant so
        PR 14's labeled-children alert matching gives every tenant its
        OWN burn-rate SLO over ``consensusml_tenant_ttft_seconds`` with
        zero alert-engine changes (docs/observability.md)."""
        m = self._tenant_children.get(tenant)
        if m is not None:
            return m
        from consensusml_tpu.obs.metrics import DEFAULT_SLO_BUCKETS

        reg, labels = self._registry, {"tenant": tenant}
        m = self._tenant_children[tenant] = {
            "requests": reg.counter(
                "consensusml_tenant_requests_total",
                "terminal requests per tenant (any finish reason)",
                labels=labels,
            ),
            "tokens": reg.counter(
                "consensusml_tenant_tokens_total",
                "generated tokens per tenant",
                labels=labels,
            ),
            "tflops": reg.counter(
                "consensusml_tenant_tflops_total",
                "ledger-joined TFLOPs consumed per tenant",
                labels=labels,
            ),
            "block_seconds": reg.counter(
                "consensusml_tenant_block_seconds_total",
                "KV block-seconds held per tenant (pool hold-time integral)",
                labels=labels,
            ),
            "ttft": reg.histogram(
                "consensusml_tenant_ttft_seconds",
                "time to first token per tenant (the per-tenant SLO series)",
                buckets=DEFAULT_SLO_BUCKETS,
                labels=labels,
            ),
        }
        return m

    def _request_cost(self, req) -> dict[str, Any]:
        """Ledger-join one request's device cost: each admission's
        prefill-bucket row (+ the draft's on a speculative engine), plus
        ``decode_ticks`` × the per-step row — ``serve.decode`` on the
        plain path, ``serve.spec.propose + serve.spec.verify`` per
        round on the speculative path. The base (unsuffixed) rows are
        registered from the engine's OWN executables, so they price the
        executed attention tier whatever ``attn_impl`` resolved to.
        Costs are XLA's cost analysis, never guessed; with no ledger
        registered the event still emits, zeros + ``cost_joined:
        False``."""
        ledger = self._cost_ledger
        flops = bytes_ = 0.0
        joined = ledger is not None
        if ledger is not None:
            rows = []
            # kinds parallel the buckets: a prefix-hit admission joins
            # the SUFFIX bucket's prefix-prefill row — its actual
            # executable — not the full prefill's (requests minted
            # outside submit() may predate the kinds list; default full)
            kinds = req.prefill_kinds or ["full"] * len(req.prefill_buckets)
            for b, kind in zip(req.prefill_buckets, kinds):
                stem = (
                    "serve.prefix_prefill" if kind == "prefix"
                    else "serve.prefill"
                )
                rows.append(ledger.row(f"{stem}.b{b}"))
                if self.spec is not None:
                    dstem = (
                        "serve.draft_prefix_prefill" if kind == "prefix"
                        else "serve.draft_prefill"
                    )
                    rows.append(ledger.row(f"{dstem}.b{b}"))
            if self.spec is not None:
                step_rows = [
                    ledger.row("serve.spec.propose"),
                    ledger.row("serve.spec.verify"),
                ]
            else:
                step_rows = [ledger.row("serve.decode")]
            for row in rows:
                if row is None:
                    joined = False
                    continue
                flops += row.flops
                bytes_ += row.bytes_accessed
            for row in step_rows:
                if row is None:
                    joined = False
                    continue
                flops += req.decode_ticks * row.flops
                bytes_ += req.decode_ticks * row.bytes_accessed
        return {
            "flops": flops,
            "hbm_bytes": bytes_,
            "tflops": flops / 1e12,
            "cost_joined": joined,
        }

    _STAGES = ("submit", "admission", "prefill", "decode", "complete")

    def _emit_wide_event(
        self, req, tokens, reason, ttft, latency, generation
    ) -> None:
        """The terminal wide event: ONE record joining the request's
        trace timings, token counts, pool block-seconds, resolved
        attention tier, and ledger cost (obs/events.py). Called from
        :meth:`_finish_handle` so every terminal state — complete,
        truncated, error-drain — emits exactly once; rejected requests
        never reach here and emit nothing."""
        rid = self._rid(req)
        tr = self._rt.get(rid) if rid else None
        stages_us: dict[str, float] = {}
        defer_ticks = preemptions = 0
        if tr is not None:
            for ev in tr.events:
                name = ev.get("name")
                if name in self._STAGES and name not in stages_us:
                    stages_us[name] = round(
                        ev["ts_us"] - tr.t_start_us, 3
                    )
            defer_ticks = tr.defer_ticks
            preemptions = tr.preemptions
        ev = {
            "trace_id": getattr(req.ctx, "trace_id", ""),
            "request_id": rid or "",
            "tenant": req.tenant,
            "finish_reason": reason,
            "prompt_len": req.handle.prompt_len,
            "tokens_out": len(tokens),
            "ttft_s": round(ttft, 6) if tokens else None,
            "latency_s": round(latency, 6),
            "decode_ticks": req.decode_ticks,
            "defer_ticks": defer_ticks,
            "preemptions": preemptions,
            "generation": generation,
            "spec_proposed": req.spec_proposed,
            "spec_accepted": req.spec_accepted,
            # block_seconds charges only EXCLUSIVE holds; prefix-shared
            # hold time is attributed separately so N streams over one
            # system prompt don't each pay for the same blocks
            "block_seconds": round(req.block_seconds, 6),
            "shared_block_seconds": round(req.shared_block_seconds, 6),
            "prefix_hit_blocks": req.prefix_hit_blocks,
            "attn_impl": self.attn_impl,
            "kv_impl": self.config.kv_impl,
            "prefill_buckets": list(req.prefill_buckets),
            # stage offsets from submit, µs — the joined trace timeline
            "stages_us": stages_us,
        }
        ev.update(self._request_cost(req))
        self._events.emit(ev)
        tm = self._tenant_metrics(req.tenant)
        tm["requests"].inc()
        if tokens:
            tm["tokens"].inc(len(tokens))
        # consumption is real whatever the finish reason — a cancelled
        # stream still burned its flops and held its blocks
        if ev["tflops"] > 0:
            tm["tflops"].inc(ev["tflops"])
        if req.block_seconds > 0:
            tm["block_seconds"].inc(req.block_seconds)

    def _finish_handle(
        self, req, tokens, reason: str, ttft: float = 0.0,
        generation: int | None = None,
    ) -> None:
        from consensusml_tpu.serve.batcher import GenResult

        now = time.perf_counter()
        latency = now - req.arrival_t
        ctx = req.ctx
        gen = self._generation if generation is None else generation
        req.handle._finish(
            GenResult(
                tokens=list(tokens),
                finish_reason=reason,
                ttft_s=ttft,
                latency_s=latency,
                prompt_len=req.handle.prompt_len,
                generation=gen,
                trace_id=getattr(ctx, "trace_id", ""),
                request_id=getattr(ctx, "request_id", ""),
                temperature=req.temperature,
                top_p=req.top_p,
                seed=req.seed,
                spec_proposed=req.spec_proposed,
                spec_accepted=req.spec_accepted,
                tenant=req.tenant,
                block_seconds=req.block_seconds,
                shared_block_seconds=req.shared_block_seconds,
                prefix_hit_blocks=req.prefix_hit_blocks,
            )
        )
        self._rt.finish(
            self._rid(req), reason,
            tokens=len(tokens), ttft_s=round(ttft, 6),
            latency_s=round(latency, 6),
        )
        # the wide event reads the COMPLETED trace (the registry resolves
        # finished ids while the done-ring holds them), so emit after
        self._emit_wide_event(req, tokens, reason, ttft, latency, gen)
        if reason != "cancelled":
            self._m_completed.inc()


def load_engine(
    path: str, config: ServeConfig | None = None, *, spec_k: int = 0
) -> Engine:
    """Build an :class:`Engine` from a serving artifact directory: the
    meta names the config, :func:`configs.build` rebuilds the
    architecture, and the consensus-mean params load in. Raises on
    non-LM artifacts (only causal LMs have a decode path).

    ``spec_k > 0`` additionally loads the DRAFT artifact from the
    ``draft/`` subdirectory (:func:`consensusml_tpu.serve.export.
    export_draft`) and serves speculatively with that proposal depth;
    raises when no draft artifact rides the directory."""
    import os

    from consensusml_tpu import configs
    from consensusml_tpu.serve.export import DRAFT_SUBDIR, load_serving

    meta, params, _model_state = load_serving(path)
    bundle = configs.build(meta["config_name"], meta.get("scale", "smoke"))
    spec = None
    if spec_k:
        from consensusml_tpu.serve.pool import SpecConfig

        draft_dir = os.path.join(path, DRAFT_SUBDIR)
        dmeta, dparams, _dms = load_serving(draft_dir)  # raises w/ context
        dbundle = configs.build(
            dmeta["config_name"], dmeta.get("scale", "smoke")
        )
        spec = SpecConfig(model=dbundle.model, params=dparams, k=spec_k)
    engine = Engine(bundle.model, params, config, spec_decode=spec)
    # seed the hot-swap ordering key from the artifact: watch() must
    # reject re-reads of THIS generation, not just generation 0
    engine._generation = int(meta.get("generation", 0))
    engine._m_generation.set(engine._generation)
    return engine
