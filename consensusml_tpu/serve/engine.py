"""The serving engine: one decode loop thread over a slot table.

``Engine`` owns the three compiled program families from
:mod:`consensusml_tpu.serve.decode`, the KV slot caches, and a single
scheduler thread that interleaves prefill admissions with in-flight
decode (continuous batching, :mod:`consensusml_tpu.serve.batcher`).
Clients — the in-process API, the socket front-end, loadgen — only touch
the bounded submit queue and per-request handles; all device work stays
on the one engine thread, so the jit caches, the cache pytree, and the
slot table need no locking.

SLO instrumentation (docs/serving.md, docs/observability.md): every
request path stage lands on the ``consensusml_serve_*`` metric family
(TTFT, inter-token latency, queue depth, batch occupancy, tokens/s) and
``serve.prefill`` / ``serve.decode_step`` spans.

The steady-state contract: after :meth:`warmup` (one decode compile +
one prefill compile per prompt bucket), serving ANY admission order of
ANY mix of prompt lengths performs ZERO further compiles —
:meth:`compile_counts` exposes the jit cache sizes so tests and the
bench assert it, and cml-check's decode jaxpr contract pins the
step-over-step program hash.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Sequence

import numpy as np

__all__ = ["ServeConfig", "Engine", "load_engine"]


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine geometry + admission policy (all fixed at construction —
    shapes are compile-time)."""

    num_slots: int = 8  # decode batch lanes
    max_len: int = 0  # cache length; 0 = the model's max_len
    queue_depth: int = 64  # bounded admission queue
    max_new_tokens: int = 16  # default per-request generation cap
    eos_id: int | None = None  # None: generation stops on the token cap
    idle_wait_s: float = 0.02  # scheduler block when nothing is in flight


class Engine:
    """In-process serving engine over an exported consensus artifact.

    ``Engine(model, params)`` then :meth:`submit` from any thread;
    :meth:`score` is the prefill-only batch scoring path (golden parity
    with the evaluator's consensus-mean model). Use as a context manager
    or call :meth:`shutdown` — it drains in-flight work by default.
    """

    def __init__(self, model: Any, params: Any, config: ServeConfig | None = None):
        import jax

        from consensusml_tpu.obs import get_registry, get_tracer
        from consensusml_tpu.serve import decode as D
        from consensusml_tpu.serve.batcher import Request, RequestHandle, SlotTable

        self.config = cfg = config or ServeConfig()
        self._dm = dm = D.DecodeModel.wrap(model)
        self.max_len = cfg.max_len or dm.max_len
        if not 0 < self.max_len <= dm.max_len:
            raise ValueError(
                f"max_len {self.max_len} outside (0, {dm.max_len}] "
                "(the model's position table bounds the cache)"
            )
        if cfg.num_slots < 1:
            raise ValueError(f"num_slots must be positive, got {cfg.num_slots}")
        self.buckets = D.prefill_buckets(self.max_len)
        self._params = jax.device_put(params)
        self._cache = D.init_cache(dm, cfg.num_slots, self.max_len)
        self._prefill_fn = D.make_prefill_fn(dm)
        self._decode_fn = D.make_decode_fn(dm)
        self._score_fn = D.make_score_fn(dm)
        self._Request, self._RequestHandle = Request, RequestHandle

        self._queue: "queue.Queue" = queue.Queue(cfg.queue_depth)
        self._table = SlotTable(cfg.num_slots)
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._drained = threading.Event()

        self._tracer = get_tracer()
        reg = get_registry()
        self._m_requests = reg.counter(
            "consensusml_serve_requests_total", "requests accepted by submit()"
        )
        self._m_rejected = reg.counter(
            "consensusml_serve_rejected_total",
            "requests rejected (bounded queue full or engine draining)",
        )
        self._m_completed = reg.counter(
            "consensusml_serve_completed_total", "requests served to completion"
        )
        self._m_tokens = reg.counter(
            "consensusml_serve_tokens_total", "tokens generated (prefill + decode)"
        )
        self._m_ttft = reg.histogram(
            "consensusml_serve_ttft_seconds",
            "time to first token: arrival -> first generated token",
        )
        self._m_intertoken = reg.histogram(
            "consensusml_serve_intertoken_seconds",
            "per-decode-step latency (== inter-token gap for resident slots)",
        )
        self._m_prefill = reg.histogram(
            "consensusml_serve_prefill_seconds", "prefill forward wall time"
        )
        self._m_queue = reg.gauge(
            "consensusml_serve_queue_depth", "requests waiting for a slot"
        )
        self._m_occupancy = reg.gauge(
            "consensusml_serve_batch_occupancy",
            "active decode slots / num_slots (sampled per step)",
        )
        self._m_tps = reg.gauge(
            "consensusml_serve_tokens_per_sec",
            "decode throughput: active slots / step wall time (sampled)",
        )

        # host-side SLO accumulators for bench/loadgen percentiles —
        # BOUNDED rings (a serving process lives for weeks; the Prometheus
        # histograms carry the full-lifetime distributions, these lists
        # only feed stats() percentiles over the recent window)
        import collections

        self._ttfts: "collections.deque[float]" = collections.deque(maxlen=4096)
        self._step_times: "collections.deque[float]" = collections.deque(
            maxlen=4096
        )
        self._occupancy_sum = 0.0
        self._decode_steps = 0
        self._tokens_out = 0
        self._decode_time_s = 0.0
        self._error: BaseException | None = None

        self._thread = threading.Thread(
            target=self._loop, name="serve-engine", daemon=True
        )
        self._thread.start()

    # -- client API ---------------------------------------------------------

    def submit(
        self,
        ids: Sequence[int],
        max_new_tokens: int | None = None,
        *,
        block: bool = True,
        timeout: float | None = None,
    ):
        """Enqueue one request; returns a ``RequestHandle``.

        Raises ``queue.Full`` when the bounded queue is full (with
        ``block=False`` or after ``timeout``) and ``RuntimeError`` once
        the engine is draining — both count on
        ``consensusml_serve_rejected_total``.
        """
        max_new = (
            self.config.max_new_tokens if max_new_tokens is None else max_new_tokens
        )
        if self._draining.is_set() or self._stop.is_set():
            self._m_rejected.inc()
            if self._error is not None:
                raise RuntimeError(
                    f"engine died on {type(self._error).__name__}: "
                    f"{self._error}"
                ) from self._error
            raise RuntimeError("engine is draining/closed; not accepting requests")
        if len(ids) < 1:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new_tokens must be positive, got {max_new}")
        if len(ids) + max_new > self.max_len:
            raise ValueError(
                f"prompt ({len(ids)}) + max_new_tokens ({max_new}) exceeds "
                f"the cache length {self.max_len}; shorten one or build the "
                "engine with a larger ServeConfig.max_len"
            )
        handle = self._RequestHandle(len(ids))
        req = self._Request(list(map(int, ids)), max_new, handle)
        try:
            self._queue.put(req, block=block, timeout=timeout)
        except queue.Full:
            self._m_rejected.inc()
            raise
        if self._drained.is_set():
            # lost the race against loop exit: the put landed after the
            # loop's final cancellation sweep and nothing will ever
            # service it — sweep again ourselves and refuse
            self._cancel_queued()
            self._m_rejected.inc()
            raise RuntimeError(
                "engine is draining/closed; not accepting requests"
            )
        self._m_requests.inc()
        self._m_queue.set(self._queue.qsize())
        return handle

    def score(self, ids) -> Any:
        """Prefill-only batch scoring: f32 logits ``(B, S, V)`` for a full
        token batch — the forward is traced identically to the held-out
        evaluator's, so an exported artifact scores BIT-EXACTLY what
        ``evaluate()``'s mean model scores (the golden parity test)."""
        import jax.numpy as jnp

        return self._score_fn(self._params, jnp.asarray(ids, jnp.int32))

    def warmup(self, buckets: Sequence[int] | None = None) -> dict[str, int]:
        """Compile the steady-state program set: the decode step plus one
        prefill per prompt bucket. Returns :meth:`compile_counts`.

        Runs on the caller's thread against a THROWAWAY cache of the same
        shapes (jit caches key on shape, so the executables are shared
        with the live path) — the engine thread may already be serving,
        and warmup must not mutate (or donate away) the cache it is
        using. Transient cost: one extra cache's worth of memory.
        """
        import jax.numpy as jnp

        from consensusml_tpu.serve import decode as D

        cache = D.init_cache(self._dm, self.config.num_slots, self.max_len)
        for b in buckets if buckets is not None else self.buckets:
            ids = jnp.zeros((1, b), jnp.int32)
            _tok, _logits, cache = self._prefill_fn(
                self._params, cache, ids, jnp.int32(1), jnp.int32(0)
            )
        toks = jnp.zeros((self.config.num_slots,), jnp.int32)
        self._decode_fn(self._params, cache, toks, jnp.zeros_like(toks))
        return self.compile_counts()

    def compile_counts(self) -> dict[str, int]:
        """Jit-cache entry counts per program family — the
        zero-recompile-after-warmup assertion reads this."""
        out = {}
        for name, fn in (
            ("prefill", self._prefill_fn),
            ("decode", self._decode_fn),
            ("score", self._score_fn),
        ):
            size = getattr(fn, "_cache_size", None)
            out[name] = int(size()) if size is not None else -1
        return out

    def drain(self, timeout: float | None = None) -> bool:
        """Stop admitting; serve everything queued + in flight to
        completion. Returns True when fully drained (the SIGTERM path —
        see :class:`consensusml_tpu.serve.server.ServeServer`)."""
        self._draining.set()
        return self._drained.wait(timeout)

    def shutdown(self, drain: bool = True, timeout: float | None = None) -> None:
        if drain:
            self.drain(timeout)
        self._stop.set()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "Engine":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=exc == (None, None, None))

    def stats(self) -> dict[str, Any]:
        """Host-side SLO summary (the bench's serving section reads this;
        Prometheus scrapes the registry for the live families).
        Percentiles cover the last 4096 samples; totals are lifetime."""
        pct = lambda xs, q: (
            float(np.percentile(list(xs), q)) if xs else float("nan")
        )
        decode_time = self._decode_time_s
        return {
            "tokens_out": self._tokens_out,
            "decode_steps": self._decode_steps,
            "ttft_p50_ms": 1e3 * pct(self._ttfts, 50),
            "ttft_p99_ms": 1e3 * pct(self._ttfts, 99),
            "intertoken_p50_ms": 1e3 * pct(self._step_times, 50),
            "intertoken_p99_ms": 1e3 * pct(self._step_times, 99),
            "mean_batch_occupancy": (
                self._occupancy_sum / self._decode_steps
                if self._decode_steps
                else 0.0
            ),
            "decode_tokens_per_sec": (
                self._tokens_out / decode_time if decode_time > 0 else 0.0
            ),
            "compile_counts": self.compile_counts(),
        }

    # -- engine thread ------------------------------------------------------

    def _loop(self) -> None:
        q = self._queue
        try:
            while not self._stop.is_set():
                self._admit_waiting()
                if self._table.num_active:
                    self._decode_step()
                    continue
                if self._draining.is_set() and q.empty():
                    break
                try:
                    req = q.get(timeout=self.config.idle_wait_s)
                except queue.Empty:
                    continue
                self._m_queue.set(q.qsize())
                self._admit(req)
        except BaseException as e:
            # a device error mid-serving (OOM compiling a bucket, bad
            # params) must not leave clients parked on silent handles:
            # mark the engine dead (submit refuses from here on), fail
            # everything in flight, and re-raise so the thread's death is
            # loud in logs rather than a mystery hang
            self._error = e
            raise
        finally:
            self._stop.set()
            self._draining.set()
            # cancel loudly: in-flight slots and queued requests get a
            # terminal "cancelled" result instead of a hung handle
            for i, slot in self._table.active:
                self._table.release(i)
                self._finish_handle(
                    slot.request, slot.request.handle._all, "cancelled"
                )
            self._cancel_queued()
            self._drained.set()

    def _cancel_queued(self) -> None:
        """Drain-and-cancel everything in the submit queue. Called by the
        loop at exit AND by submit() when it loses the race against loop
        exit (its put landed after the loop's final sweep) — once
        ``_drained`` is set nothing services the queue, so cancelling is
        always correct, and the thread-safe ``get_nowait`` hands each
        request to exactly one canceller."""
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            self._finish_handle(req, [], "cancelled")

    def _admit_waiting(self) -> None:
        while self._table.free_slot() is not None:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            self._m_queue.set(self._queue.qsize())
            self._admit(req)

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(f"prompt length {n} exceeds max bucket {self.buckets[-1]}")

    def _admit(self, req) -> None:
        """Prefill ``req`` into a free slot (admission = one bucketed
        forward that seeds the slot cache and the first token). A raise
        mid-admission cancels THIS request's handle before propagating —
        at that point it is out of the queue but not yet in the slot
        table, so neither of the loop's exit sweeps would reach it."""
        try:
            self._admit_inner(req)
        except BaseException:
            self._finish_handle(req, req.handle._all, "cancelled")
            raise

    def _admit_inner(self, req) -> None:
        import jax.numpy as jnp

        from consensusml_tpu.serve.batcher import Slot

        idx = self._table.free_slot()
        assert idx is not None, "admission with no free slot"
        n = len(req.ids)
        bucket = self._bucket(n)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :n] = req.ids
        t0 = time.perf_counter()
        with self._tracer.span("serve.prefill", bucket=bucket, slot=idx):
            tok_dev, _logits, self._cache = self._prefill_fn(
                self._params,
                self._cache,
                jnp.asarray(ids),
                jnp.int32(n),
                jnp.int32(idx),
            )
            tok = int(tok_dev)  # device fence: the first token is real now
        now = time.perf_counter()
        self._m_prefill.observe(now - t0)
        ttft = now - req.arrival_t
        self._m_ttft.observe(ttft)
        self._ttfts.append(ttft)
        req.handle._emit(tok)
        self._m_tokens.inc()
        self._tokens_out += 1
        if req.max_new_tokens == 1 or tok == self.config.eos_id:
            reason = "eos" if tok == self.config.eos_id else "max_tokens"
            self._finish_handle(req, req.handle._all, reason, ttft=ttft)
            return
        self._table.occupy(
            idx,
            Slot(
                request=req, next_pos=n, pending=tok, generated=1,
                ttft_s=ttft, last_token_t=now,
            ),
        )

    def _decode_step(self) -> None:
        import jax.numpy as jnp

        active = self._table.active
        s = self.config.num_slots
        tokens = np.zeros((s,), np.int32)
        positions = np.zeros((s,), np.int32)
        for i, slot in active:
            tokens[i] = slot.pending
            positions[i] = slot.next_pos
        t0 = time.perf_counter()
        with self._tracer.span("serve.decode_step", active=len(active)):
            next_dev, self._cache = self._decode_fn(
                self._params, self._cache, jnp.asarray(tokens), jnp.asarray(positions)
            )
            next_toks = np.asarray(next_dev)  # device fence per step
        dt = time.perf_counter() - t0
        now = time.perf_counter()
        self._m_intertoken.observe(dt)
        self._step_times.append(dt)
        self._decode_time_s += dt
        self._decode_steps += 1
        self._occupancy_sum += len(active) / s
        self._m_occupancy.set(len(active) / s)
        if dt > 0:
            self._m_tps.set(len(active) / dt)
        for i, slot in active:
            tok = int(next_toks[i])
            slot.request.handle._emit(tok)
            self._m_tokens.inc()
            self._tokens_out += 1
            slot.generated += 1
            slot.next_pos += 1
            slot.pending = tok
            slot.last_token_t = now
            reason = None
            if tok == self.config.eos_id:
                reason = "eos"
            elif slot.generated >= slot.request.max_new_tokens:
                reason = "max_tokens"
            elif slot.next_pos >= self.max_len:
                reason = "length"  # safety net; submit() validation bounds it
            if reason is not None:
                self._table.release(i)
                self._finish_handle(
                    slot.request, slot.request.handle._all, reason,
                    ttft=slot.ttft_s,
                )

    def _finish_handle(self, req, tokens, reason: str, ttft: float = 0.0) -> None:
        from consensusml_tpu.serve.batcher import GenResult

        now = time.perf_counter()
        req.handle._finish(
            GenResult(
                tokens=list(tokens),
                finish_reason=reason,
                ttft_s=ttft,
                latency_s=now - req.arrival_t,
                prompt_len=len(req.ids),
            )
        )
        if reason != "cancelled":
            self._m_completed.inc()


def load_engine(path: str, config: ServeConfig | None = None) -> Engine:
    """Build an :class:`Engine` from a serving artifact directory: the
    meta names the config, :func:`configs.build` rebuilds the
    architecture, and the consensus-mean params load in. Raises on
    non-LM artifacts (only causal LMs have a decode path)."""
    from consensusml_tpu import configs
    from consensusml_tpu.serve.export import load_serving

    meta, params, _model_state = load_serving(path)
    bundle = configs.build(meta["config_name"], meta.get("scale", "smoke"))
    return Engine(bundle.model, params, config)
