"""KV-cache decode: bucketed prefill, slot insertion, fused decode step.

The compiled-program contract that makes serving cheap is the same one
training lives by (docs/static_analysis.md): FIXED shapes everywhere.
The engine runs exactly three jitted program families and nothing else:

- ``prefill(params, cache, ids (1, L), length, slot)`` — full causal
  forward over one padded prompt bucket ``L``, per-layer K/V written
  into cache row ``slot`` via ``dynamic_update_slice``, argmax of the
  last REAL token's logits as the first generated token. One compile per
  prompt-length bucket (warmup); the bucket set is static.
- ``decode(params, cache, tokens (S,), positions (S,))`` — one token for
  ALL ``S`` slots at once, each slot writing/attending at its own
  position (:func:`models.attention.update_kv_cache` /
  :func:`~consensusml_tpu.models.attention.cached_attention`). Slot fill
  level is DATA (the lengths vector), never shape, so every decode step
  of every mix of in-flight requests reuses one executable — the
  zero-recompile contract cml-check's decode jaxpr pass pins.
- ``score(params, ids (B, S))`` — the prefill-only batch scoring path:
  literally the eval forward, which is what makes the export→serve
  golden parity test bit-exact.

Free slots still compute (their lane is masked garbage) — wasted FLOPs
bounded by ``1/S``, the standard continuous-batching trade against
recompiling per occupancy pattern.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

__all__ = [
    "DecodeModel",
    "supports_decode",
    "init_cache",
    "prefill_buckets",
    "make_prefill_fn",
    "make_decode_fn",
    "make_score_fn",
]


def supports_decode(model: Any) -> bool:
    """Does this model implement the serving forward contract
    (``positions``/``kv_cache``/``return_kv`` kwargs)? True for the
    causal-LM families (GPT-2, Llama)."""
    from consensusml_tpu.models.gpt2 import GPT2LM
    from consensusml_tpu.models.llama import LlamaLM

    return isinstance(model, (GPT2LM, LlamaLM))


@dataclasses.dataclass(frozen=True)
class DecodeModel:
    """A causal LM plus the cache geometry the engine needs off it."""

    model: Any
    layers: int
    kv_heads: int
    head_dim: int
    max_len: int
    vocab_size: int
    cache_dtype: Any

    @classmethod
    def wrap(cls, model: Any) -> "DecodeModel":
        if not supports_decode(model):
            raise ValueError(
                f"{type(model).__name__} has no KV-cache decode path; "
                "serving needs a causal LM (GPT2LM / LlamaLM)"
            )
        c = model.config
        return cls(
            model=model,
            layers=c.layers,
            kv_heads=getattr(c, "kv_heads", c.heads),
            head_dim=getattr(c, "head_dim", c.hidden // c.heads),
            max_len=c.max_len,
            vocab_size=c.vocab_size,
            cache_dtype=c.dtype,
        )


def init_cache(dm: DecodeModel, num_slots: int, max_len: int) -> list[dict]:
    """Per-layer ``{"k", "v"}`` slot caches, ``(S, T, kv_heads, d)`` in the
    model's compute dtype. Llama-GQA caches pre-repeat heads (the read
    expands); ~2 * layers * S * T * kv_heads * d * itemsize bytes total."""
    shape = (num_slots, max_len, dm.kv_heads, dm.head_dim)
    return [
        {
            "k": jnp.zeros(shape, dm.cache_dtype),
            "v": jnp.zeros(shape, dm.cache_dtype),
        }
        for _ in range(dm.layers)
    ]


def prefill_buckets(max_len: int, smallest: int = 8) -> tuple[int, ...]:
    """Power-of-two prompt-length buckets up to ``max_len``: each prompt
    pads to the smallest bucket that fits, so prefill compiles once per
    bucket (log2 many programs) instead of once per prompt length."""
    buckets = []
    b = smallest
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return tuple(buckets)


def _donate_cache() -> tuple[int, ...]:
    # cache donation halves steady-state HBM traffic on the chip; the CPU
    # backend has no donation support and would warn every compile
    return (1,) if jax.default_backend() in ("tpu", "axon") else ()


def make_prefill_fn(dm: DecodeModel) -> Callable:
    """``prefill(params, cache, ids (1, L), length, slot, temperature,
    top_p, seed)`` -> ``(first_token, last_logits (V,), new_cache)``.

    One jit executable per padded bucket length ``L`` (compiled at
    warmup). Pad tokens DO run through the model — causal masking keeps
    every real position's logits exact, and the pad rows written into the
    cache sit beyond ``length`` where the decode mask never reads them.
    The first generated token samples IN-JIT under the request's
    ``(temperature, top_p, seed)`` at fold position ``length - 1``
    (:mod:`consensusml_tpu.serve.sampling`); ``temperature = 0`` is the
    original greedy argmax bit for bit.
    """
    from consensusml_tpu.serve.sampling import sample_token

    model = dm.model

    def prefill(params, cache, ids, length, slot, temperature, top_p, seed):
        logits, kvs = model.apply(
            {"params": params}, ids, deterministic=True, return_kv=True
        )
        last = logits[0, length - 1]  # (V,) — last REAL token's logits
        new_cache = []
        for layer_cache, (k, v) in zip(cache, kvs):
            new_cache.append(
                {
                    "k": jax.lax.dynamic_update_slice(
                        layer_cache["k"],
                        jnp.asarray(k, layer_cache["k"].dtype),
                        (slot, 0, 0, 0),
                    ),
                    "v": jax.lax.dynamic_update_slice(
                        layer_cache["v"],
                        jnp.asarray(v, layer_cache["v"].dtype),
                        (slot, 0, 0, 0),
                    ),
                }
            )
        tok = sample_token(
            last[None], temperature[None], top_p[None], seed[None],
            (length - 1)[None],
        )[0]
        return tok, last, new_cache

    return jax.jit(prefill, donate_argnums=_donate_cache())


def make_decode_fn(dm: DecodeModel) -> Callable:
    """``decode(params, cache, tokens (S,), positions (S,), temperature
    (S,), top_p (S,), seeds (S,))`` -> ``(next_tokens (S,), new_cache)``.
    Sampling happens inside the jit under per-slot fold keys — the host
    only ever fetches S int32s per step, and greedy lanes (temperature
    0) are the argmax special case of the SAME executable."""
    from consensusml_tpu.serve.sampling import sample_token

    model = dm.model

    def decode(params, cache, tokens, positions, temperature, top_p, seeds):
        logits, new_cache = model.apply(
            {"params": params},
            tokens[:, None],
            deterministic=True,
            positions=positions,
            kv_cache=cache,
        )
        toks = sample_token(
            logits[:, 0], temperature, top_p, seeds, positions
        )
        return toks, new_cache

    return jax.jit(decode, donate_argnums=_donate_cache())


def make_score_fn(dm: DecodeModel) -> Callable:
    """``score(params, ids (B, S))`` -> f32 logits ``(B, S, V)`` — the
    prefill-only scoring path, traced identically to the held-out eval
    forward (golden parity: export→serve == evaluate's mean model)."""
    model = dm.model

    def score(params, ids):
        return model.apply({"params": params}, ids, deterministic=True)

    return jax.jit(score)
