"""Threaded socket front-end over :class:`~consensusml_tpu.serve.engine.Engine`.

Line-delimited JSON over TCP — deliberately minimal (no HTTP dependency
in this environment) but shaped like a real serving front-end:

request (one line; ``trace_id``/``request_id`` are optional — anything
missing is minted server-side, so every request is traceable; the
sampling triple ``temperature``/``top_p``/``seed`` and the per-request
``eos_id`` stop override are optional too — omitted fields take the
engine's ``ServeConfig`` defaults; ``tenant`` labels the request for
per-workload attribution, ``"default"`` when omitted)::

    {"ids": [3, 17, 42], "max_new_tokens": 16,
     "temperature": 0.8, "top_p": 0.95, "seed": 12345, "eos_id": 50256,
     "tenant": "batch-eval",
     "trace_id": "lg0-00042", "request_id": "lg0-00042/0"}

response (streamed, one line per token, then a terminal record echoing
the trace identity, the resolved sampling triple — resubmitting with
the echoed seed replays the exact token stream — and the RESOLVED
tenant label, sanitized server-side, that the request's wide event and
``consensusml_tenant_*`` series carry)::

    {"token": 7}
    {"token": 19}
    {"done": true, "tokens": [7, 19, ...], "finish_reason": "max_tokens",
     "ttft_ms": 12.3, "latency_ms": 48.9,
     "temperature": 0.8, "top_p": 0.95, "seed": 12345,
     "spec_proposed": 12, "spec_accepted": 9, "tenant": "batch-eval",
     "trace_id": "lg0-00042", "request_id": "lg0-00042/0"}

errors land as ``{"error": "..."}`` and close the connection. One
request per connection keeps the protocol trivially load-generatable
(:mod:`tools.loadgen` opens a connection per Poisson arrival, exactly
how an L4-balanced fleet would see it).

Graceful shutdown: :meth:`install_sigterm` wires SIGTERM to DRAIN —
stop accepting, serve everything queued and in flight to completion,
then close the listener — so a rolling restart never drops an admitted
request.
"""

from __future__ import annotations

import json
import signal
import socket
import threading
from typing import Any

from consensusml_tpu.analysis import guarded_by

__all__ = ["ServeServer"]


@guarded_by("_conns_lock", "_conns")
class ServeServer:
    """Accept loop + one thread per connection; ``port=0`` picks a free
    port (read it back from :attr:`address`).

    ``metrics_port`` (``None`` = off, ``0`` = auto) additionally serves
    the live observability endpoints — ``/metrics`` Prometheus text,
    ``/traces`` merged Chrome trace, ``/requests`` request-trace
    snapshot, ``/alerts`` + ``/query`` + ``/healthz`` from the alert
    plane, ``/events`` + ``/tenants`` from the wide-event accounting
    plane — from :class:`consensusml_tpu.obs.MetricsServer`; read the
    bound address back from :attr:`metrics_address`. A serving process
    has no train loop to drive telemetry ticks, so the metrics server's
    ``obs-ticker`` thread records metric history and evaluates the
    alert ruleset every ``obs_tick_s`` seconds (the default ruleset
    unless ``alert_rules`` overrides it); ``/healthz`` is the readiness
    probe a fleet router polls (docs/observability.md
    "Alerting & history"). The 2 s default tick keeps the history
    ring's retention (``keep`` samples x tick) LONGER than the default
    ruleset's 300 s slow burn window — lower ticks need a bigger
    ``MetricsHistory(keep=)`` or the slow window silently truncates to
    the ring span and loses its single-blip-suppression property.
    """

    def __init__(
        self,
        engine: Any,
        host: str = "127.0.0.1",
        port: int = 0,
        metrics_port: int | None = None,
        obs_tick_s: float = 2.0,
        alert_rules: Any | None = None,
    ):
        self.engine = engine
        self.metrics = None
        self.metrics_address = None
        # bind the front-end listener FIRST: if the port is taken, the
        # constructor raises before any side server thread exists
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        if metrics_port is not None:
            from consensusml_tpu.obs import (
                MetricsServer,
                get_alert_engine,
                get_history,
            )

            alerts = get_alert_engine()
            if alert_rules is not None:
                alerts.replace_rules(list(alert_rules))
            try:
                self.metrics = MetricsServer(
                    port=metrics_port,
                    host=host,
                    history=get_history(),
                    alerts=alerts,
                    tick_s=obs_tick_s,
                    # readiness gates on warmup completion: until the
                    # engine's warmup() finishes, /healthz reports 503
                    # and a fleet router places zero streams here
                    # (engines without the flag stay always-ready)
                    ready_fn=lambda: bool(getattr(engine, "warmed", True)),
                )
            except OSError:
                self._sock.close()
                raise
            self.metrics_address = self.metrics.address
        self._sock.settimeout(0.2)  # accept loop polls the stop flag
        self.address = self._sock.getsockname()
        self._stop = threading.Event()
        # mutated by the accept loop (add), every connection thread
        # (discard on exit) and shutdown (snapshot for the join sweep):
        # an unlocked set here could blow up shutdown's iteration with
        # "set changed size during iteration" when an accept races the
        # drain — the exact seam the threads/lockorder passes audit
        self._conns_lock = threading.Lock()
        self._conns: set[threading.Thread] = set()
        self._thread = threading.Thread(
            target=self._accept_loop, name="serve-accept", daemon=True
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed under us during shutdown
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            with self._conns_lock:
                self._conns.add(t)
            t.start()
        self._sock.close()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                f = conn.makefile("rwb")
                line = f.readline()
                if not line:
                    return
                try:
                    req = json.loads(line)
                    trace = None
                    if req.get("trace_id"):
                        from consensusml_tpu.obs import TraceContext

                        trace = TraceContext(
                            req["trace_id"], req.get("request_id")
                        )
                    handle = self.engine.submit(
                        req["ids"], req.get("max_new_tokens"), trace=trace,
                        temperature=req.get("temperature"),
                        top_p=req.get("top_p"),
                        seed=req.get("seed"),
                        eos_id=req.get("eos_id"),
                        tenant=req.get("tenant"),
                    )
                except Exception as e:  # bad JSON, validation, draining
                    f.write(json.dumps({"error": str(e)}).encode() + b"\n")
                    f.flush()
                    return
                for tok in handle.tokens():
                    f.write(json.dumps({"token": int(tok)}).encode() + b"\n")
                    f.flush()  # per-token flush IS the streaming
                r = handle.result()
                f.write(
                    json.dumps(
                        {
                            "done": True,
                            "tokens": r.tokens,
                            "finish_reason": r.finish_reason,
                            "ttft_ms": round(1e3 * r.ttft_s, 3),
                            "latency_ms": round(1e3 * r.latency_s, 3),
                            "temperature": r.temperature,
                            "top_p": r.top_p,
                            "seed": r.seed,
                            "spec_proposed": r.spec_proposed,
                            "spec_accepted": r.spec_accepted,
                            "tenant": r.tenant,
                            "trace_id": r.trace_id,
                            "request_id": r.request_id,
                        }
                    ).encode()
                    + b"\n"
                )
                f.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-stream; the engine still finishes
        finally:
            with self._conns_lock:
                self._conns.discard(threading.current_thread())

    def install_sigterm(self) -> None:
        """SIGTERM (and SIGINT) => graceful drain-then-exit."""
        def handler(signum, frame):
            self.shutdown(drain=True)

        signal.signal(signal.SIGTERM, handler)
        signal.signal(signal.SIGINT, handler)

    def shutdown(self, drain: bool = True, timeout: float | None = None) -> None:
        """Stop accepting; optionally drain the engine (default) so every
        admitted request completes before the process exits."""
        self._stop.set()
        self.engine.shutdown(drain=drain, timeout=timeout)
        with self._conns_lock:
            conns = list(self._conns)
        for t in conns:  # let response streams flush
            t.join(timeout=2.0)
        self._thread.join(timeout=2.0)
        if self.metrics is not None:
            self.metrics.close()
