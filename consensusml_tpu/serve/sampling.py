"""In-jit token sampling: temperature/top-p with per-slot PRNG keys.

Every sampling decision the serving engine makes happens INSIDE the
compiled step (decode, prefill first-token, draft propose, speculative
verify) — the host only ever passes three small per-slot arrays
(``temperature``, ``top_p``, ``seed``) as DATA and fetches the sampled
int32 tokens back. No host round-trips (cml-check's host-sync lint stays
clean), no shape changes between greedy and sampled traffic (one
executable serves any mix — the step-over-step canonical-jaxpr contract
holds across sampled ticks), and greedy decoding is exactly the
``temperature == 0`` special case of the same program.

**The key schedule.** The token sampled from the logits row at absolute
sequence position ``p`` of a request with per-request ``seed`` always
uses::

    fold_in(fold_in(PRNGKey(seed), p), tag)

with ``tag = SAMPLE_TAG`` for ordinary next-token draws, ``ACCEPT_TAG``
for speculative acceptance uniforms, and ``RESIDUAL_TAG`` for
rejection-resampling draws. Keying on the request's own ``(seed,
position)`` — not on slot index, engine step count, or batch
composition — is what makes token streams **deterministically
replayable** (same seed ⇒ same stream, regardless of what else is in
flight) and what makes speculative decode with a draft that equals the
target reproduce target-only sampling **bit for bit** (the
distribution-equality fixture): the draft's proposal at position ``p``
draws with exactly the key the plain decode step would have used.

``jnp.float32`` throughout (the f64-promotion contract); the nucleus
mask sorts the vocab once per row — O(V log V) inside a step that
already runs an O(V·H) logits matmul.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "SAMPLE_TAG",
    "ACCEPT_TAG",
    "RESIDUAL_TAG",
    "sampling_keys",
    "adjusted_probs",
    "categorical_from_probs",
    "sample_token",
]

# fold-in tags separating the three independent random streams a
# position can consume (draw / accept-uniform / residual re-draw)
SAMPLE_TAG = 0
ACCEPT_TAG = 1
RESIDUAL_TAG = 2

_PROB_FLOOR = 1e-38  # log() guard; masked entries stay exactly -inf


def sampling_keys(
    seeds: jax.Array, positions: jax.Array, tag: int
) -> jax.Array:
    """Per-slot PRNG keys: ``fold_in(fold_in(PRNGKey(seed), pos), tag)``
    vmapped over the slot lane. ``seeds``/``positions`` may be any
    matching shape; the key array has that shape."""
    flat_seeds = seeds.reshape(-1)
    flat_pos = positions.reshape(-1)

    def one(seed, pos):
        return jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(seed), pos), tag
        )

    keys = jax.vmap(one)(flat_seeds, flat_pos)
    return keys.reshape(seeds.shape + keys.shape[1:])


def adjusted_probs(
    logits: jax.Array, temperature: jax.Array, top_p: jax.Array
) -> jax.Array:
    """The sampling distribution as explicit probabilities ``(..., V)``.

    ``temperature > 0``: softmax of ``logits / temperature`` with the
    nucleus (top-p) mask applied and renormalized — the smallest set of
    highest-probability tokens whose mass reaches ``top_p`` keeps its
    (renormalized) probability, everything else gets exactly 0.
    ``temperature <= 0``: the greedy one-hot at ``argmax(logits)`` (ties
    break to the lowest index, matching ``jnp.argmax`` — bit-compatible
    with the engine's original greedy path).

    Returning probabilities rather than sampled tokens is deliberate:
    speculative verify needs the full target AND draft distributions for
    the rejection-sampling acceptance ratio and the residual
    ``max(p - q, 0)`` re-draw (docs/serving.md "Speculative decode").
    """
    logits = jnp.asarray(logits, jnp.float32)
    temperature = jnp.asarray(temperature, jnp.float32)
    top_p = jnp.asarray(top_p, jnp.float32)
    t = jnp.where(temperature > 0, temperature, 1.0)[..., None]
    probs = jax.nn.softmax(logits / t, axis=-1)
    # nucleus mask: tokens whose cumulative mass BEFORE them is < top_p
    # survive (the top token always does: its prefix mass is 0)
    p_keep = jnp.clip(top_p, 1e-6, 1.0)[..., None]
    order = jnp.argsort(-logits, axis=-1)
    sorted_probs = jnp.take_along_axis(probs, order, axis=-1)
    prefix = jnp.cumsum(sorted_probs, axis=-1) - sorted_probs
    keep_sorted = prefix < p_keep
    inv = jnp.argsort(order, axis=-1)
    keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
    masked = jnp.where(keep, probs, 0.0)
    masked = masked / jnp.sum(masked, axis=-1, keepdims=True)
    greedy = jax.nn.one_hot(
        jnp.argmax(logits, axis=-1), logits.shape[-1], dtype=jnp.float32
    )
    return jnp.where((temperature > 0)[..., None], masked, greedy)


def categorical_from_probs(keys: jax.Array, probs: jax.Array) -> jax.Array:
    """Sample one token id per row from explicit probabilities.

    Zero-probability entries are exactly ``-inf`` in the gumbel race so
    a masked token can never win; a greedy one-hot row therefore returns
    its argmax deterministically, key regardless — which is what lets
    ONE program serve greedy and sampled lanes side by side.
    """
    logp = jnp.where(
        probs > 0, jnp.log(jnp.maximum(probs, _PROB_FLOOR)), -jnp.inf
    )
    flat_keys = keys.reshape(-1, *keys.shape[len(probs.shape) - 1:])
    flat_logp = logp.reshape(-1, logp.shape[-1])
    toks = jax.vmap(jax.random.categorical)(flat_keys, flat_logp)
    return toks.reshape(probs.shape[:-1]).astype(jnp.int32)


def sample_token(
    logits: jax.Array,
    temperature: jax.Array,
    top_p: jax.Array,
    seeds: jax.Array,
    positions: jax.Array,
) -> jax.Array:
    """Next-token draw for ``logits (..., V)`` rows at their absolute
    ``positions``, under the canonical key schedule (``SAMPLE_TAG``).
    The single entry point the decode / prefill / verify-bonus paths
    share, so every path that samples "the token after position p" is
    bit-identical by construction."""
    probs = adjusted_probs(logits, temperature, top_p)
    keys = sampling_keys(seeds, positions, SAMPLE_TAG)
    return categorical_from_probs(keys, probs)
