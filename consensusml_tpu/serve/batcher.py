"""Slot-based continuous batching: requests, handles, slot table.

The scheduling model (docs/serving.md): the decode batch has ``S`` fixed
slots. A request is admitted into a free slot by a PREFILL (one bucketed
forward that also seeds the slot's KV cache and first token), then rides
the shared per-token DECODE step with whatever else is in flight —
admission never waits for the batch to drain, and a finishing sequence
frees its slot for the next queued request between two decode steps
(continuous batching, not static batching). All host-side bookkeeping
lives here; the device-facing jits are :mod:`consensusml_tpu.serve.decode`
and the loop that ties them together is :class:`.engine.Engine`.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Iterator

__all__ = ["Request", "RequestHandle", "GenResult", "SlotTable", "Slot"]

_DONE = object()  # stream sentinel


@dataclasses.dataclass
class GenResult:
    """Terminal record of one request."""

    tokens: list[int]
    finish_reason: str  # "eos" | "max_tokens" | "length" | "cancelled"
    ttft_s: float  # arrival -> first token
    latency_s: float  # arrival -> completion
    prompt_len: int
    generation: int = 0  # artifact generation that finished the stream
    # request-scoped tracing identity (obs/requests.py): clients join
    # their own observations to the server's trace on these
    trace_id: str = ""
    request_id: str = ""
    # resolved sampling parameters, echoed for deterministic replay:
    # re-submitting the same prompt with this exact (temperature, top_p,
    # seed) triple reproduces the same token stream (docs/serving.md)
    temperature: float = 0.0
    top_p: float = 1.0
    seed: int = 0
    # speculative-decode accounting for THIS stream (0/0 on a non-spec
    # engine): proposed = draft tokens offered, accepted = survivors
    spec_proposed: int = 0
    spec_accepted: int = 0
    # per-tenant attribution (obs/events.py): the workload label the
    # request carried through submit(); "default" for unlabeled clients
    tenant: str = "default"
    # pool block-seconds this stream held EXCLUSIVELY, integrated over
    # hold time (survives preemption + re-admission; 0.0 on the
    # slot-cache path). Prefix-shared holds land in
    # shared_block_seconds instead — a request is charged only for
    # blocks it kept alive on its own.
    block_seconds: float = 0.0
    shared_block_seconds: float = 0.0
    # prefix-cache accounting: pool blocks this request's admissions
    # mapped in from the index instead of prefilling (0 = every token
    # was computed)
    prefix_hit_blocks: int = 0


class RequestHandle:
    """Client-side view of an in-flight request: a token stream plus the
    final :class:`GenResult`. Thread-safe; one consumer per handle."""

    def __init__(self, prompt_len: int):
        self._stream: "queue.Queue[Any]" = queue.Queue()
        self._done = threading.Event()
        self._result: GenResult | None = None
        self._all: list[int] = []  # engine-thread only until _finish
        self.prompt_len = prompt_len

    # engine side -----------------------------------------------------------
    def _emit(self, token: int) -> None:
        self._all.append(token)
        self._stream.put(token)

    def _finish(self, result: GenResult) -> None:
        self._result = result
        self._done.set()
        self._stream.put(_DONE)

    # client side -----------------------------------------------------------
    def tokens(self, timeout: float | None = None) -> Iterator[int]:
        """Stream generated tokens as they land (blocks between tokens)."""
        while True:
            item = self._stream.get(timeout=timeout)
            if item is _DONE:
                return
            yield item

    def result(self, timeout: float | None = None) -> GenResult:
        if not self._done.wait(timeout):
            raise TimeoutError("request still in flight")
        assert self._result is not None
        return self._result

    @property
    def done(self) -> bool:
        return self._done.is_set()


@dataclasses.dataclass
class Request:
    ids: list[int]
    max_new_tokens: int
    handle: RequestHandle
    arrival_t: float = dataclasses.field(default_factory=time.perf_counter)
    # TraceContext (obs/requests.py); the engine mints one when the
    # client didn't send one, so ctx is always set post-submit
    ctx: Any = None
    # per-request sampling (serve/sampling.py): temperature 0 = greedy,
    # seed feeds the (seed, position) fold keys — same seed, same stream
    temperature: float = 0.0
    top_p: float = 1.0
    seed: int = 0
    # per-request stop token (submit() resolves the engine default in);
    # None = stop on the token cap only
    eos_id: int | None = None
    # speculative accounting (engine-thread writes, _finish echoes)
    spec_proposed: int = 0
    spec_accepted: int = 0
    # tenant label for per-workload attribution (submit() resolves;
    # obs/events.py sanitizes at the boundary)
    tenant: str = "default"
    # wide-event accounting accumulators (engine-thread writes; they
    # survive recompute-preemption because the REQUEST re-enqueues):
    # decode steps this stream was resident for, pool block-seconds
    # held, and the prefill bucket of every admission (a continuation
    # re-prefills into a possibly larger bucket)
    decode_ticks: int = 0
    block_seconds: float = 0.0
    prefill_buckets: list[int] = dataclasses.field(default_factory=list)
    # prefix-cache accounting (engine-thread writes): blocks adopted
    # from the index across this request's admissions, block-seconds of
    # SHARED holds (split out of block_seconds — the request is charged
    # only unshared time), and the admission kind per prefill_buckets
    # entry ("full" | "prefix" — _request_cost joins each bucket
    # against the ledger row of the executable that actually ran)
    prefix_hit_blocks: int = 0
    shared_block_seconds: float = 0.0
    prefill_kinds: list[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Slot:
    """One decode lane. ``next_pos`` is where the PENDING token will be
    written/attended on the next decode step; ``pending`` is that token
    (the newest generated one, already emitted to the client)."""

    request: Request
    next_pos: int  # == prompt_len right after prefill
    pending: int
    generated: int = 1  # prefill produced token #1
    ttft_s: float = 0.0
    last_token_t: float = 0.0
    # artifact generation currently decoding this lane; a hot swap flips
    # every resident slot's tag between two decode steps (serve/pool/)
    generation: int = 0


class SlotTable:
    """Fixed-size slot bookkeeping (engine-thread only, no locking)."""

    def __init__(self, num_slots: int):
        self.num_slots = num_slots
        self.slots: list[Slot | None] = [None] * num_slots

    @property
    def active(self) -> list[tuple[int, Slot]]:
        return [(i, s) for i, s in enumerate(self.slots) if s is not None]

    @property
    def num_active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def free_slot(self) -> int | None:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def occupy(self, idx: int, slot: Slot) -> None:
        assert self.slots[idx] is None, f"slot {idx} already occupied"
        self.slots[idx] = slot

    def release(self, idx: int) -> Slot:
        slot = self.slots[idx]
        assert slot is not None, f"slot {idx} already free"
        self.slots[idx] = None
        return slot
