"""Serving artifact: the consensus-mean of a training checkpoint.

A decentralized run ends with W disagreeing replicas; what you deploy is
their consensus mean — the same model :func:`train.evaluate` scores as
``mean_model`` and elastic grows bootstrap joiners from. The export
collapses the stacked ``TrainState`` with the SHARED
:func:`consensusml_tpu.utils.consensus_mean` (the serve golden parity
test asserts export→serve logits match the eval path bit for bit) and
writes:

- ``<dir>/model/`` — orbax pytree ``{"params", "model_state"}`` with the
  worker axis collapsed (per-worker init shapes);
- ``<dir>/serve_meta.json`` — config name + scale (enough to rebuild the
  architecture via :func:`consensusml_tpu.configs.build`), the training
  round and world size the artifact came from (provenance for the
  serving fleet's rollout logs). Written atomically, meta LAST: a
  partial export never parses as a valid artifact.

``train.py --export-serving DIR`` writes one at end of run (and at every
``--checkpoint-every`` boundary) so training hands off to serving
without a manual conversion step.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax

from consensusml_tpu.utils.checkpoint import replicated_scalar
from consensusml_tpu.utils.tree import consensus_mean

__all__ = ["export_serving", "load_serving", "serving_meta", "META_NAME"]

META_NAME = "serve_meta.json"
_MODEL_SUBDIR = "model"


def _host_value(v: Any):
    """Host numpy value of one mean leaf, shard-aware (see export)."""
    import numpy as np

    if hasattr(v, "is_fully_addressable") and not v.is_fully_addressable:
        shard = v.addressable_shards[0]
        if tuple(shard.data.shape) == tuple(v.shape):  # replicated
            return np.asarray(shard.data)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(v, tiled=True))
    return np.asarray(jax.device_get(v))


def export_serving(
    path: str,
    state: Any,
    *,
    config_name: str,
    scale: str = "smoke",
    round: int | None = None,
) -> str:
    """Collapse ``state`` (stacked TrainState) to a serving artifact.

    Returns the artifact directory. Safe to call repeatedly on the same
    ``path`` (checkpoint-boundary exports overwrite: latest wins, and the
    meta rewrite is atomic so a reader never sees a torn artifact).
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    world = int(state.step.shape[0])
    if round is None:
        round = replicated_scalar(state.step)
    mean = consensus_mean(
        {"params": state.params, "model_state": state.model_state}
    )
    # host fetch before the write: collective-backend states are sharded
    # over the worker mesh and the mean is tiny (1/W of the checkpoint).
    # Multi-controller: the worker-axis mean is replicated, so any
    # addressable shard IS the value (device_get on a cross-process
    # array raises); non-replicated layouts allgather like evaluate.
    mean = jax.tree.map(_host_value, mean)
    if jax.process_count() > 1 and jax.process_index() != 0:
        return path  # one writer; peers return the same path
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(os.path.join(path, _MODEL_SUBDIR), mean, force=True)
    meta = {
        "config_name": config_name,
        "scale": scale,
        "round": int(round),
        "world_size": world,
    }
    tmp = os.path.join(path, META_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=2)
    os.replace(tmp, os.path.join(path, META_NAME))
    return path


def serving_meta(path: str) -> dict[str, Any]:
    """The artifact's metadata dict; raises with a clear message when
    ``path`` is not a serving artifact (meta missing/corrupt)."""
    meta_path = os.path.join(os.path.abspath(path), META_NAME)
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        raise ValueError(
            f"{path} is not a serving artifact ({META_NAME} unreadable: "
            f"{e}); produce one with train.py --export-serving or "
            "serve.export_serving()"
        ) from None
    if "config_name" not in meta:
        raise ValueError(f"{meta_path} has no config_name field")
    return meta


def load_serving(path: str) -> tuple[dict[str, Any], Any, Any]:
    """Load an artifact: ``(meta, params, model_state)``.

    The model tree restores structurally (it was saved as a plain dict),
    so no shape template is needed — the caller rebuilds the architecture
    from ``meta["config_name"]`` / ``meta["scale"]``.
    """
    import orbax.checkpoint as ocp

    meta = serving_meta(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        tree = ckptr.restore(os.path.join(os.path.abspath(path), _MODEL_SUBDIR))
    return meta, tree["params"], tree.get("model_state", {})
