"""Serving artifact: the consensus-mean of a training checkpoint.

A decentralized run ends with W disagreeing replicas; what you deploy is
their consensus mean — the same model :func:`train.evaluate` scores as
``mean_model`` and elastic grows bootstrap joiners from. The export
collapses the stacked ``TrainState`` with the SHARED
:func:`consensusml_tpu.utils.consensus_mean` (the serve golden parity
test asserts export→serve logits match the eval path bit for bit) and
writes:

- ``<dir>/model/`` — orbax pytree ``{"params", "model_state"}`` with the
  worker axis collapsed (per-worker init shapes);
- ``<dir>/serve_meta.json`` — config name + scale (enough to rebuild the
  architecture via :func:`consensusml_tpu.configs.build`), the training
  round and world size the artifact came from (provenance for the
  serving fleet's rollout logs), and a monotonically increasing
  ``generation`` counter — the hot-swap protocol's ordering key
  (:mod:`consensusml_tpu.serve.pool.hotswap`): each export at the same
  path bumps it, and readers reject a meta whose generation goes
  backwards. Written atomically, meta LAST: a partial export never
  parses as a valid artifact, and a reader that sees generation g+1 is
  guaranteed to see generation g+1's model directory.

``train.py --export-serving DIR`` writes one at end of run (and at every
``--checkpoint-every`` boundary) so training hands off to serving
without a manual conversion step — a watching engine picks each
generation up mid-traffic.
"""

from __future__ import annotations

import contextlib
import fcntl
import json
import os
from typing import Any

import jax

from consensusml_tpu.utils.checkpoint import replicated_scalar
from consensusml_tpu.utils.tree import consensus_mean

__all__ = [
    "export_serving",
    "export_draft",
    "load_serving",
    "serving_meta",
    "bump_generation",
    "pin_generation",
    "META_NAME",
    "DRAFT_SUBDIR",
]

META_NAME = "serve_meta.json"
_MODEL_SUBDIR = "model"
# the speculative DRAFT artifact rides inside the target artifact dir;
# the PARENT meta's generation orders both (the watcher restages the
# pair whenever the parent generation advances — serve/pool/hotswap.py)
DRAFT_SUBDIR = "draft"


def _host_value(v: Any):
    """Host numpy value of one mean leaf, shard-aware (see export)."""
    import numpy as np

    if hasattr(v, "is_fully_addressable") and not v.is_fully_addressable:
        shard = v.addressable_shards[0]
        if tuple(shard.data.shape) == tuple(v.shape):  # replicated
            return np.asarray(shard.data)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(v, tiled=True))
    return np.asarray(jax.device_get(v))


def _next_generation(path: str) -> int:
    """One past the generation already at ``path`` (0 when absent/torn):
    repeated exports to one artifact dir count monotonically upward."""
    try:
        return int(serving_meta(path).get("generation", 0)) + 1
    except ValueError:
        return 1


@contextlib.contextmanager
def _generation_lock(path: str):
    """Exclusive cross-process lock for the generation read-modify-write.

    ``os.replace`` makes each meta WRITE atomic, but the increment is
    read-then-write: a trainer export racing a ``bump_generation`` (same
    dir, different processes) could mint the same generation twice, and
    a watcher that staged the first would silently skip the second —
    new weights never served. One flock per artifact dir serializes the
    writers; readers never take it."""
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, ".generation.lock"), "w") as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        yield  # unlocked by close


def export_serving(
    path: str,
    state: Any,
    *,
    config_name: str,
    scale: str = "smoke",
    round: int | None = None,
    generation: int | None = None,
) -> str:
    """Collapse ``state`` (stacked TrainState) to a serving artifact.

    Returns the artifact directory. Safe to call repeatedly on the same
    ``path`` (checkpoint-boundary exports overwrite: latest wins, and the
    meta rewrite is atomic so a reader never sees a torn artifact). Each
    overwrite advances ``generation`` (auto-incremented from the meta
    already on disk unless given explicitly) — the counter a hot-swapping
    engine orders reloads by.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if generation is not None and generation < 1:
        raise ValueError(f"generation must be >= 1, got {generation}")
    world = int(state.step.shape[0])
    if round is None:
        round = replicated_scalar(state.step)
    mean = consensus_mean(
        {"params": state.params, "model_state": state.model_state}
    )
    # host fetch before the write: collective-backend states are sharded
    # over the worker mesh and the mean is tiny (1/W of the checkpoint).
    # Multi-controller: the worker-axis mean is replicated, so any
    # addressable shard IS the value (device_get on a cross-process
    # array raises); non-replicated layouts allgather like evaluate.
    mean = jax.tree.map(_host_value, mean)
    if jax.process_count() > 1 and jax.process_index() != 0:
        return path  # one writer; peers return the same path
    # the lock covers decide-generation THROUGH meta write: concurrent
    # writers (trainer export vs a bump_generation) serialize instead of
    # minting the same generation twice
    with _generation_lock(path):
        if generation is None:
            generation = _next_generation(path)
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(os.path.join(path, _MODEL_SUBDIR), mean, force=True)
        meta = {
            "config_name": config_name,
            "scale": scale,
            "round": int(round),
            "world_size": world,
            "generation": int(generation),
        }
        _write_meta(path, meta)
    return path


def export_draft(
    path: str,
    params: Any,
    *,
    config_name: str,
    scale: str = "smoke",
) -> str:
    """Install a speculative DRAFT artifact alongside the target at
    ``path`` (``<path>/draft/`` — itself a complete serving artifact, so
    :func:`load_serving` reads it directly).

    The draft rides the PARENT's generation protocol: it carries no
    ordering of its own (its meta mirrors the parent generation at write
    time, provenance only), and a hot-swapping engine restages
    target + draft as a pair whenever the parent's generation advances —
    one counter, one flip, never a half-swapped pair. Written under the
    parent's generation lock so a concurrent target export cannot
    observe a torn draft directory.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    draft_dir = os.path.join(path, DRAFT_SUBDIR)
    host = jax.tree.map(_host_value, params)
    with _generation_lock(path):
        try:
            parent_gen = int(serving_meta(path).get("generation", 0))
        except ValueError:
            parent_gen = 0  # draft installed before the first export
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(
                os.path.join(draft_dir, _MODEL_SUBDIR),
                {"params": host, "model_state": {}},
                force=True,
            )
        _write_meta(
            draft_dir,
            {
                "config_name": config_name,
                "scale": scale,
                "role": "draft",
                "generation": parent_gen,
            },
        )
    return draft_dir


def _write_meta(path: str, meta: dict[str, Any]) -> None:
    tmp = os.path.join(path, META_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=2)
    os.replace(tmp, os.path.join(path, META_NAME))


def bump_generation(path: str) -> int:
    """Rewrite an existing artifact's meta with ``generation + 1`` (model
    untouched, atomic). The loadgen ``--swap-every`` knob uses this to
    exercise the hot-swap machinery under load without retraining; a
    trainer re-export does the same thing implicitly with new weights.
    Returns the new generation."""
    path = os.path.abspath(path)
    with _generation_lock(path):
        meta = serving_meta(path)  # raises on non-artifacts
        gen = int(meta.get("generation", 0)) + 1
        meta["generation"] = gen
        _write_meta(path, meta)
    return gen


def pin_generation(path: str, meta: dict[str, Any]) -> int:
    """Re-pin ``path`` to ``meta``'s content at a generation STRICTLY
    above the current one. This is canary ROLLBACK (fleet/controller.py):
    watchers reject regressed generations, so going "back" to a known
    meta is a forward write — the old content under a new generation,
    stamped ``rolled_back_from`` so the swap log shows why. Returns the
    pinned generation."""
    path = os.path.abspath(path)
    with _generation_lock(path):
        cur = int(serving_meta(path).get("generation", 0))
        pinned = dict(meta)
        pinned["generation"] = cur + 1
        pinned["rolled_back_from"] = cur
        _write_meta(path, pinned)
    return cur + 1


def serving_meta(path: str) -> dict[str, Any]:
    """The artifact's metadata dict; raises with a clear message when
    ``path`` is not a serving artifact (meta missing/corrupt)."""
    meta_path = os.path.join(os.path.abspath(path), META_NAME)
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        raise ValueError(
            f"{path} is not a serving artifact ({META_NAME} unreadable: "
            f"{e}); produce one with train.py --export-serving or "
            "serve.export_serving()"
        ) from None
    if "config_name" not in meta:
        raise ValueError(f"{meta_path} has no config_name field")
    return meta


def load_serving(path: str) -> tuple[dict[str, Any], Any, Any]:
    """Load an artifact: ``(meta, params, model_state)``.

    The model tree restores structurally (it was saved as a plain dict),
    so no shape template is needed — the caller rebuilds the architecture
    from ``meta["config_name"]`` / ``meta["scale"]``.
    """
    import orbax.checkpoint as ocp

    meta = serving_meta(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        tree = ckptr.restore(os.path.join(os.path.abspath(path), _MODEL_SUBDIR))
    return meta, tree["params"], tree.get("model_state", {})
