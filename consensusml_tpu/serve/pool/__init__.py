"""Serving memory + scheduling subsystem: paged KV blocks, refcounted
prefix sharing, disaggregated prefill/decode stages, drain-free hot
checkpoint swap.

Four pieces, one contract (fixed shapes, zero recompiles after warmup,
no host sync in the decode hot loop):

- :mod:`.blocks` — the paged block pool: slot occupancy bounded by total
  live tokens instead of ``num_slots * max_len``; blocks are refcounted
  so several streams (and the prefix index) can hold one physical block;
- :mod:`.prefix` — the content-addressed prefix index: shared
  block-aligned prompt prefixes prefill once, later requests adopt the
  matched blocks and prefill only the unshared suffix (tenant-keyed,
  generation-invalidated, copy-on-write on divergence);
- :mod:`.stages` — separately-jitted prefill/decode programs plus the
  per-tick admission budget that keeps decode from waiting on long
  prefills (TTFT p99 is the target metric);
- :mod:`.hotswap` — generation-tagged artifact reload: the engine flips
  to a newly exported consensus mean between decode steps with no drain
  and no dropped streams.

The engine (:class:`consensusml_tpu.serve.Engine`) runs this path by
default (``ServeConfig.kv_impl="paged"``); the PR 5 per-slot path stays
as ``kv_impl="slot"`` — the parity baseline the tests compare against
bit for bit and the bench measures occupancy gains over.
"""

from consensusml_tpu.serve.pool.blocks import (  # noqa: F401
    BlockPool,
    NoFreeBlocks,
    TRASH_BLOCK,
    blocks_for_tokens,
    init_pages,
)
from consensusml_tpu.serve.pool.prefix import (  # noqa: F401
    PrefixIndex,
)
from consensusml_tpu.serve.pool.stages import (  # noqa: F401
    AdmissionScheduler,
    make_paged_decode_fn,
    make_paged_prefill_fn,
    make_prefix_prefill_fn,
    prefix_prefill_cost_args,
)
from consensusml_tpu.serve.pool.hotswap import (  # noqa: F401
    GenerationWatcher,
    StagedSwap,
)
from consensusml_tpu.serve.pool.spec import (  # noqa: F401
    SpecConfig,
    make_draft_propose_fn,
    make_verify_fn,
    spec_table_cols,
)

__all__ = [
    "BlockPool",
    "NoFreeBlocks",
    "TRASH_BLOCK",
    "blocks_for_tokens",
    "init_pages",
    "PrefixIndex",
    "AdmissionScheduler",
    "make_paged_decode_fn",
    "make_paged_prefill_fn",
    "make_prefix_prefill_fn",
    "prefix_prefill_cost_args",
    "GenerationWatcher",
    "StagedSwap",
    "SpecConfig",
    "make_draft_propose_fn",
    "make_verify_fn",
    "spec_table_cols",
]
