"""Content-addressed prefix index over paged KV blocks (host side).

The engine's shared-prefix fast path: identical block-aligned prompt
prefixes (system prompts, per-tenant templates, a preempted stream's own
history) are prefilled ONCE and every later request maps the matched
physical blocks straight into its slot's block table via
``BlockPool.adopt`` — prefill then computes only the unshared suffix.
vLLM's hash-block prefix caching and SGLang's RadixAttention are the
reference designs; this index takes the hash-chain form because the
pool's pages are already fixed-size blocks.

**Hash scheme.** For a token sequence the index derives one digest per
FULL block chunk: ``digest_i = sha256(tokens[0 : (i+1) * block_size])``
— a running hash over the whole prefix, so a chunk's key commits to
everything before it and two sequences share an entry only if they
share the entire aligned prefix (no per-chunk collisions across
different histories). Keys are ``(tenant, generation, digest)``:

- ``tenant`` namespaces the index — tenant A's prompt bytes never map
  into tenant B's table, even for identical token ids (cross-tenant KV
  timing/communication isolation, PR 16's accounting boundary).
- ``generation`` is the hot-swap weight generation; entries minted
  under old weights are unreachable BY CONSTRUCTION after a swap
  (lookups key on the current generation), and ``drop_stale`` garbage-
  collects them at swap time.

**Residency.** An entry maps a digest to a physical block id, not to a
snapshot of its bytes — validity is maintained eagerly: the pool fires
``reuse_hook`` (:meth:`invalidate_block`) the moment a fresh pop is
about to recycle a block, so any id still present in the index holds
exactly the bytes its digest names. A FREE block can therefore stay
indexed (nothing scatters into free blocks — free lanes write the trash
block) and adoption revives it off the free list; ``cached_hook``
(:meth:`cached`) parks such blocks at the bottom of the free stack so
they are recycled last.

The index is engine-thread-only host state, like ``BlockPool``: pure
dict lookups at admission, never inside the jit, zero device syncs.
"""

from __future__ import annotations

import hashlib

import numpy as np

__all__ = ["PrefixIndex"]


class PrefixIndex:
    """Maps ``(tenant, generation, chain-digest)`` -> physical block."""

    def __init__(self, block_size: int):
        if block_size < 1:
            raise ValueError(f"block_size must be positive, got {block_size}")
        self.block_size = block_size
        self._chains: dict[tuple, int] = {}
        self._by_block: dict[int, set[tuple]] = {}
        self.invalidations = 0  # entries dropped by block reuse

    def __len__(self) -> int:
        return len(self._chains)

    @property
    def indexed_blocks(self) -> int:
        """Distinct physical blocks the index currently references."""
        return len(self._by_block)

    def _digests(self, ids) -> list[bytes]:
        """One running-hash digest per FULL block chunk of ``ids``
        (partial tail chunks are never indexed — their bytes keep
        changing as the stream decodes)."""
        bs = self.block_size
        n_full = len(ids) // bs
        if n_full == 0:
            return []
        h = hashlib.sha256()
        out = []
        for i in range(n_full):
            chunk = np.asarray(ids[i * bs : (i + 1) * bs], np.int64)
            h.update(chunk.tobytes())
            out.append(h.digest())
        return out

    def lookup(self, tenant: str, generation: int, ids) -> list[int]:
        """Longest indexed block-aligned prefix of ``ids`` under
        ``(tenant, generation)``: the physical block per matched chunk,
        in order, stopping at the first miss. Empty list = no match."""
        out: list[int] = []
        for d in self._digests(ids):
            b = self._chains.get((tenant, generation, d))
            if b is None:
                break
            out.append(b)
        return out

    def insert(self, tenant: str, generation: int, ids, blocks) -> int:
        """Index ``ids``'s full block chunks against the physical blocks
        that hold them (``blocks[i]`` holds chunk ``i`` — the admitting
        slot's owned list). First writer wins: an existing entry keeps
        its block (the new holder adopted it anyway on the hit path, and
        on a near-miss re-prefill both copies hold identical bytes).
        Returns the number of NEW entries."""
        added = 0
        for i, d in enumerate(self._digests(ids)):
            if i >= len(blocks):
                break
            key = (tenant, generation, d)
            if key in self._chains:
                continue
            b = int(blocks[i])
            self._chains[key] = b
            self._by_block.setdefault(b, set()).add(key)
            added += 1
        return added

    def invalidate_block(self, block: int) -> int:
        """Forget every entry naming ``block`` (wired as the pool's
        ``reuse_hook``: the block's bytes are about to be overwritten).
        Returns the number of entries dropped."""
        keys = self._by_block.pop(int(block), None)
        if not keys:
            return 0
        for k in keys:
            self._chains.pop(k, None)
        self.invalidations += len(keys)
        return len(keys)

    def cached(self, block: int) -> bool:
        """True if ``block``'s bytes are still named by the index
        (wired as the pool's ``cached_hook`` — freed-but-indexed blocks
        park at the bottom of the free stack)."""
        return int(block) in self._by_block

    def drop_stale(self, current_generation: int) -> int:
        """Garbage-collect entries minted under any OTHER weight
        generation (hot-swap invalidation). Stale entries were already
        unreachable — lookups key on the current generation — so this
        only reclaims index memory and lets the pool stop treating
        their blocks as cached. Returns the number dropped."""
        stale = [k for k in self._chains if k[1] != current_generation]
        for k in stale:
            b = self._chains.pop(k)
            keys = self._by_block.get(b)
            if keys is not None:
                keys.discard(k)
                if not keys:
                    del self._by_block[b]
        return len(stale)
