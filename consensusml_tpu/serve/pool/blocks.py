"""Paged KV-cache block pool: fixed-shape pages + host block accounting.

The PR 5 engine gives every slot a private ``(max_len, H, D)`` cache row,
so HBM occupancy is bounded by ``num_slots * max_len`` tokens whether the
streams are long or short. The pool replaces the rows with a SHARED set
of fixed-shape physical blocks:

- **Pages** (device): per-layer ``{"k", "v"}`` arrays of shape
  ``(num_blocks, block_size, kv_heads, head_dim)``. One allocation for
  the life of the engine; never reshaped, so the zero-recompile contract
  the per-slot path pins (``analysis/jaxpr_contracts.py``) carries over
  unchanged — the paged stages get their own contracts.
- **Block table** (device): ``(num_slots, blocks_per_slot)`` int32 —
  slot-logical block index → physical block id. All gather/scatter
  indices derive from it INSIDE the jit
  (:func:`consensusml_tpu.models.attention.paged_update_kv_cache` /
  :func:`~consensusml_tpu.models.attention.gather_paged_kv`); the decode
  hot loop performs zero host syncs on pool state.
- **Free list** (host-authoritative, device-mirrored): allocation
  decisions happen at admission / block-boundary crossings — host events
  on host ints, off the per-token path. :class:`BlockPool` enforces the
  invariants the tests pin: no double-allocate, no double-free, no leak
  (free ∪ Σ-owned always partitions the physical blocks exactly,
  counting multiplicity now that blocks are shareable).

These invariants are also proven over EVERY interleaving of admissions,
adoptions, pins and releases — not just the schedules the tests run —
by the ``pool-refcount`` abstract model in
:mod:`consensusml_tpu.analysis.protocol_models` (cml-check pass 8),
with recorded-trace conformance tying the model to this class
block-id-exactly (:mod:`consensusml_tpu.analysis.conformance`). Change
the ownership protocol here and the model must change with it, or
replay fails in ``tests/test_model_check.py``.

**Refcounted sharing (prefix cache).** A physical block may appear in
MORE than one slot's owned list: the prefix cache (``prefix.py``) maps a
matched block-aligned prompt prefix straight into a new slot's table via
:meth:`adopt`, bumping the per-block refcount instead of popping fresh
blocks. Shared blocks are read-only by construction — every holder's
writes land at positions ≥ its own prompt length, past the shared
prefix — except the copy-on-write divergence case, which the engine
resolves INSIDE the jit (``paged_cow_copy``) after re-pointing the
diverging slot's table entry at a fresh page. :meth:`shrink` and
:meth:`release` decrement; a block returns to the free list only when
its last holder lets go. Freed blocks whose bytes are still referenced
by the prefix index re-enter the LIFO free stack at the BOTTOM
(``cached_hook``), so cached prefixes survive as long as pool pressure
allows and a preempted stream usually re-admits for free.

**The trash block.** Physical block 0 is reserved and never allocated.
Freed slots' table rows reset to 0, so the decode step's fixed-shape
scatter (every lane writes every step, free lanes included) lands free
lanes' garbage in the trash block instead of in pages another slot now
owns. Garbage gathered from trash (or from an owned block's
not-yet-written tail) sits beyond the length mask, which zeroes its
probability exactly — same argument the per-slot path makes for stale
rows, so slot/block reuse needs no cache clearing.

Occupancy is bounded by total LIVE tokens (``(num_blocks - 1) *
block_size``), not by ``num_slots * max_len``: with a heavy-tail length
mix, a pool sized for the MEAN length serves far more concurrent streams
than per-slot rows sized for the max (the bench serving section measures
exactly this). Prefix sharing tightens the bound further: N streams over
a shared prompt hold its blocks once, not N times.
"""

from __future__ import annotations

import time
from collections import Counter
from typing import Any, Callable, Iterable

import numpy as np

__all__ = ["BlockPool", "NoFreeBlocks", "init_pages", "blocks_for_tokens"]

TRASH_BLOCK = 0  # reserved physical block; free lanes scatter here


class NoFreeBlocks(RuntimeError):
    """The pool cannot satisfy an allocation; callers evict or defer."""


def blocks_for_tokens(tokens: int, block_size: int) -> int:
    """Physical blocks needed to hold ``tokens`` logical positions."""
    return -(-tokens // block_size)


def init_pages(dm: Any, num_blocks: int, block_size: int) -> list[dict]:
    """Per-layer ``{"k", "v"}`` page pools,
    ``(num_blocks, block_size, kv_heads, head_dim)`` in the model's
    compute dtype (Llama-GQA pages pre-repeat heads, like the slot
    cache). ~2 * layers * num_blocks * block_size * kv_heads * d *
    itemsize bytes total — sized by live tokens, not slots * max_len."""
    import jax.numpy as jnp

    shape = (num_blocks, block_size, dm.kv_heads, dm.head_dim)
    return [
        {
            "k": jnp.zeros(shape, dm.cache_dtype),
            "v": jnp.zeros(shape, dm.cache_dtype),
        }
        for _ in range(dm.layers)
    ]


class BlockPool:
    """Host-side block accounting for one engine (engine-thread only).

    LIFO free list (hot blocks reuse hot HBM lines), per-slot owned
    lists, per-block refcounts, and the host-authoritative block table
    mirrored to device on mutation. All methods raise on invariant
    violations rather than corrupting silently — a double-free here
    would hand one physical block to two live slots WITHOUT the
    refcount knowing, the paged equivalent of a use-after-free.

    Two optional hooks wire the prefix index in without a dependency
    cycle:

    - ``reuse_hook(block)`` fires when a FRESH pop is about to recycle a
      physical block (extend): the index drops any entries still naming
      it, before new content overwrites the bytes.
    - ``cached_hook(block) -> bool`` is consulted when a block's
      refcount hits zero: ``True`` parks it at the BOTTOM of the LIFO
      free stack (reused last, so indexed prefix bytes stay resident as
      long as pressure allows), ``False`` keeps the plain hot-reuse LIFO
      order.
    """

    def __init__(
        self,
        num_slots: int,
        max_len: int,
        block_size: int,
        num_blocks: int = 0,
        clock: Callable[[], float] | None = None,
    ):
        if block_size < 1:
            raise ValueError(f"block_size must be positive, got {block_size}")
        if max_len % block_size != 0:
            raise ValueError(
                f"block_size {block_size} must divide max_len {max_len} "
                "(keeps the gathered view bit-identical to the per-slot "
                "cache layout and prompt buckets block-aligned)"
            )
        self.num_slots = num_slots
        self.max_len = max_len
        self.block_size = block_size
        self.blocks_per_slot = max_len // block_size
        auto = num_slots * self.blocks_per_slot + 1
        self.num_blocks = num_blocks or auto
        if self.num_blocks < self.blocks_per_slot + 1:
            raise ValueError(
                f"num_blocks {self.num_blocks} cannot hold even one "
                f"max-length stream ({self.blocks_per_slot} blocks "
                "+ the trash block); the engine could never admit"
            )
        # LIFO stack of free physical ids; block 0 (trash) never enters
        self._free: list[int] = list(range(self.num_blocks - 1, 0, -1))
        self._owned: dict[int, list[int]] = {}
        # holders per physical block: Σ slot-owned multiplicity + pins.
        # refcnt == 0 <=> on the free list (check() proves it).
        self._refcnt = np.zeros((self.num_blocks,), np.int32)
        # per-slot subset of owned blocks acquired via adopt() (prefix
        # hits) — drives the shared/unshared block-second split
        self._adopted: dict[int, set[int]] = {}
        # pin multiset: blocks held alive with no slot owner (the COW
        # source for the duration of one prefill dispatch)
        self._pinned: Counter = Counter()
        self._table = np.zeros((num_slots, self.blocks_per_slot), np.int32)
        self._dev_table = None  # invalidated on mutation, rebuilt lazily
        self.reuse_hook: Callable[[int], Any] | None = None
        self.cached_hook: Callable[[int], bool] | None = None
        # block-second accounting (docs/observability.md "Wide events &
        # tenant accounting"): per-slot ∫ held_blocks dt, integrated at
        # every mutation — each alloc/extend/shrink/release first adds
        # held × elapsed at the OLD holding, then mutates, so the
        # integral is exact piecewise-constant occupancy over hold time.
        # Adopted (prefix-shared) blocks integrate into a SEPARATE
        # accumulator so the engine charges only unshared block-seconds.
        # The clock is injectable so tests pin the math deterministically.
        self._clock = clock if clock is not None else time.monotonic
        self._bs_acc: dict[int, float] = {}
        self._bs_sh_acc: dict[int, float] = {}
        self._bs_t: dict[int, float] = {}

    # -- introspection ------------------------------------------------------

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1  # trash excluded

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        """DISTINCT physical blocks held (shared blocks count once) —
        the honest occupancy number under prefix sharing."""
        return self.usable_blocks - len(self._free)

    @property
    def shared_blocks(self) -> int:
        """Physical blocks currently held by more than one holder."""
        return int(np.count_nonzero(self._refcnt > 1))

    def owned(self, slot: int) -> list[int]:
        return list(self._owned.get(slot, ()))

    def refcount(self, block: int) -> int:
        return int(self._refcnt[block])

    def can_admit(self, n_blocks: int) -> bool:
        return len(self._free) >= n_blocks

    def _integrate(self, slot: int) -> None:
        """Advance ``slot``'s block-second integrals to now at its
        CURRENT holding (call before any mutation of the holding)."""
        t = self._bs_t.get(slot)
        if t is None:
            return
        now = self._clock()
        dt = now - t
        self._bs_acc[slot] += len(self._owned.get(slot, ())) * dt
        self._bs_sh_acc[slot] += len(self._adopted.get(slot, ())) * dt
        self._bs_t[slot] = now

    def block_seconds(self, slot: int) -> float:
        """``slot``'s TOTAL block-seconds held so far (∫ owned_blocks dt
        since its alloc, integrated to now, shared holds included). 0.0
        for a slot that owns nothing — the engine reads this immediately
        BEFORE :meth:`release` and accumulates it onto the request, so
        the total survives recompute-preemption and re-admission."""
        if slot not in self._owned:
            return 0.0
        self._integrate(slot)
        return self._bs_acc.get(slot, 0.0)

    def block_seconds_split(self, slot: int) -> tuple[float, float]:
        """``(unshared, shared)`` block-seconds for ``slot``: ``shared``
        integrates only blocks the slot ADOPTED from the prefix index
        (held jointly with other streams / the cache), ``unshared`` the
        rest. ``unshared + shared == block_seconds()``. Wide events
        charge the request only the unshared part."""
        if slot not in self._owned:
            return 0.0, 0.0
        self._integrate(slot)
        total = self._bs_acc.get(slot, 0.0)
        shared = self._bs_sh_acc.get(slot, 0.0)
        return total - shared, shared

    # -- mutation -----------------------------------------------------------

    def begin(self, slot: int) -> None:
        """Open ``slot``'s holding without allocating anything yet —
        the prefix-hit admission path adopts matched blocks first, then
        extends with fresh ones. :meth:`alloc` = begin + extend."""
        if slot in self._owned:
            raise RuntimeError(
                f"slot {slot} already owns blocks (double-alloc); "
                "release before re-admitting"
            )
        self._owned[slot] = []
        self._adopted[slot] = set()
        self._bs_acc[slot] = 0.0
        self._bs_sh_acc[slot] = 0.0
        self._bs_t[slot] = self._clock()

    def alloc(self, slot: int, n_blocks: int) -> list[int]:
        """Give ``slot`` its first ``n_blocks`` blocks (admission)."""
        if slot in self._owned:
            raise RuntimeError(
                f"slot {slot} already owns blocks (double-alloc); "
                "release before re-admitting"
            )
        if n_blocks > self.blocks_per_slot:
            raise ValueError(
                f"slot {slot} asked for {n_blocks} blocks "
                f"> blocks_per_slot {self.blocks_per_slot}"
            )
        if len(self._free) < n_blocks:
            raise NoFreeBlocks(
                f"need {n_blocks} blocks, {len(self._free)} free"
            )
        self.begin(slot)
        return self.extend(slot, n_blocks)

    def _acquire_ref(self, b: int) -> None:
        """Bump ``b``'s refcount, reviving it off the free list if it
        currently has no holder (a cached prefix block being re-shared)."""
        if self._refcnt[b] == 0:
            try:
                self._free.remove(b)
            except ValueError:
                raise RuntimeError(
                    f"corrupt refcount: block {b} has no holder "
                    "but is not on the free list"
                ) from None
        self._refcnt[b] += 1

    def _release_ref(self, b: int) -> bool:
        """Drop one reference to ``b``; returns True when the LAST
        holder let go and the block went back on the free list."""
        if self._refcnt[b] < 1:
            raise RuntimeError(
                f"corrupt refcount: block {b} released below zero"
            )
        self._refcnt[b] -= 1
        if self._refcnt[b] != 0:
            return False
        if b in self._free:
            raise RuntimeError(f"corrupt free list: block {b}")
        if self.cached_hook is not None and self.cached_hook(b):
            # indexed prefix bytes: park at the BOTTOM of the LIFO
            # stack so fresh pops recycle this block LAST
            self._free.insert(0, b)
        else:
            self._free.append(b)
        return True

    def adopt(self, slot: int, blocks: Iterable[int]) -> list[int]:
        """Map already-materialized physical blocks (a prefix-index
        match) into ``slot``'s table, bumping refcounts instead of
        popping fresh blocks. The slot must have been opened with
        :meth:`begin`; adopted blocks precede any extend in the row."""
        owned = self._owned.get(slot)
        if owned is None:
            raise RuntimeError(f"slot {slot} owns nothing; begin first")
        blocks = [int(b) for b in blocks]
        if len(owned) + len(blocks) > self.blocks_per_slot:
            raise ValueError(
                f"slot {slot} would exceed blocks_per_slot "
                f"({len(owned)} + {len(blocks)} > {self.blocks_per_slot})"
            )
        self._integrate(slot)
        adopted = self._adopted.setdefault(slot, set())
        for b in blocks:
            if b == TRASH_BLOCK or not 0 < b < self.num_blocks:
                raise ValueError(f"cannot adopt physical block {b}")
            if b in adopted or b in owned:
                raise RuntimeError(
                    f"slot {slot} already holds block {b} (double-adopt)"
                )
            self._acquire_ref(b)
            self._table[slot, len(owned)] = b
            owned.append(b)
            adopted.add(b)
        if blocks:
            self._dev_table = None
        return blocks

    def pin(self, block: int) -> None:
        """Hold ``block`` alive with no slot owner — the engine pins the
        COW source across one prefill dispatch so a concurrent extend
        cannot pop and overwrite it before the in-jit copy reads it."""
        b = int(block)
        if b == TRASH_BLOCK or not 0 < b < self.num_blocks:
            raise ValueError(f"cannot pin physical block {b}")
        self._acquire_ref(b)
        self._pinned[b] += 1

    def unpin(self, block: int) -> None:
        b = int(block)
        if self._pinned[b] < 1:
            raise RuntimeError(f"block {b} is not pinned")
        self._pinned[b] -= 1
        if self._pinned[b] == 0:
            del self._pinned[b]
        self._release_ref(b)

    def extend(self, slot: int, n_blocks: int = 1) -> list[int]:
        """Grow ``slot`` by ``n_blocks`` FRESH blocks (admission tail /
        decode crossing a boundary). Each pop fires ``reuse_hook`` so
        the prefix index forgets the recycled bytes before the slot
        overwrites them."""
        owned = self._owned.get(slot)
        if owned is None:
            raise RuntimeError(f"slot {slot} owns nothing; alloc first")
        if len(owned) + n_blocks > self.blocks_per_slot:
            raise ValueError(
                f"slot {slot} would exceed blocks_per_slot "
                f"({len(owned)} + {n_blocks} > {self.blocks_per_slot})"
            )
        if len(self._free) < n_blocks:
            raise NoFreeBlocks(
                f"need {n_blocks} blocks, {len(self._free)} free"
            )
        self._integrate(slot)
        got = []
        for _ in range(n_blocks):
            b = self._free.pop()
            self._refcnt[b] = 1
            if self.reuse_hook is not None:
                self.reuse_hook(b)
            self._table[slot, len(owned)] = b
            owned.append(b)
            got.append(b)
        if got:
            self._dev_table = None
        return got

    def shrink(self, slot: int, keep_blocks: int) -> list[int]:
        """Relinquish ``slot``'s blocks BEYOND the first ``keep_blocks``
        (speculative rollback: a rejected draft suffix hands its
        over-allocated tail back; the kept prefix — committed tokens
        plus the next write — is untouched). Relinquished table entries
        reset to trash; each block returns to the free list only when
        its LAST holder lets go. Returns the relinquished ids (possibly
        empty)."""
        owned = self._owned.get(slot)
        if owned is None:
            raise RuntimeError(f"slot {slot} owns nothing; alloc first")
        if keep_blocks < 1:
            raise ValueError(
                f"keep_blocks must be >= 1, got {keep_blocks} (release() "
                "frees a slot outright)"
            )
        self._integrate(slot)
        adopted = self._adopted.get(slot)
        dropped = []
        while len(owned) > keep_blocks:
            b = owned.pop()
            if b == TRASH_BLOCK:
                raise RuntimeError(f"corrupt free list: block {b}")
            if adopted:
                adopted.discard(b)
            self._release_ref(b)
            self._table[slot, len(owned)] = TRASH_BLOCK
            dropped.append(b)
        if dropped:
            self._dev_table = None
        return dropped

    def release(self, slot: int) -> list[int]:
        """Drop all of ``slot``'s references and reset its table row to
        the trash block. Returns the relinquished ids; blocks shared
        with other holders stay allocated to them."""
        owned = self._owned.pop(slot, None)
        if owned is None:
            raise RuntimeError(f"slot {slot} owns nothing (double-free)")
        self._adopted.pop(slot, None)
        self._bs_acc.pop(slot, None)
        self._bs_sh_acc.pop(slot, None)
        self._bs_t.pop(slot, None)
        for b in owned:
            if b == TRASH_BLOCK:
                raise RuntimeError(f"corrupt free list: block {b}")
            self._release_ref(b)
        self._table[slot, :] = TRASH_BLOCK
        self._dev_table = None
        return owned

    # -- views --------------------------------------------------------------

    def block_row(self, slot: int, width: int) -> np.ndarray:
        """``slot``'s physical ids padded with trash to ``width`` entries
        (the prefill scatter's fixed-shape index vector: pad blocks
        beyond the owned prefix land in trash)."""
        owned = self._owned.get(slot, ())
        row = np.full((width,), TRASH_BLOCK, np.int32)
        n = min(len(owned), width)
        row[:n] = owned[:n]
        return row

    def device_table(self, extra_cols: int = 0):
        """The block table as a device array (cached; host→device copy
        only after a mutation, never inside the decode step).

        ``extra_cols > 0`` appends that many TRASH columns — the
        speculative verify window's overflow guard: a stream within
        ``k`` tokens of ``max_len`` computes window positions past its
        real row, and ``table[s, pos // bs]`` must resolve those to the
        trash block rather than index-clamp into the slot's LAST owned
        block (which holds live tokens). Cached per width."""
        if self._dev_table is None:  # invalidated by a mutation
            self._dev_table = {}
        if extra_cols not in self._dev_table:
            import jax.numpy as jnp

            table = self._table
            if extra_cols:
                pad = np.full(
                    (self.num_slots, extra_cols), TRASH_BLOCK, np.int32
                )
                table = np.concatenate([table, pad], axis=1)
            self._dev_table[extra_cols] = jnp.asarray(table)
        return self._dev_table[extra_cols]

    def check(self) -> None:
        """Invariant sweep (tests + debug): free ∪ Σ-owned ∪ pins
        partitions the non-trash physical blocks COUNTING MULTIPLICITY —
        every block's refcount equals the number of holders naming it,
        free blocks have refcount 0 and no holder, and every non-trash
        block is accounted for exactly (free XOR held)."""
        holders: Counter = Counter(self._pinned)
        for slot, blocks in self._owned.items():
            if len(set(blocks)) != len(blocks):
                raise AssertionError(
                    f"slot {slot} holds a block twice: {blocks}"
                )
            holders.update(blocks)
            row = self._table[slot, : len(blocks)]
            if list(row) != blocks:
                raise AssertionError(
                    f"slot {slot} table row {list(row)} != owned {blocks}"
                )
            if not self._adopted.get(slot, set()) <= set(blocks):
                raise AssertionError(
                    f"slot {slot} adopted set escapes its owned list"
                )
        free_set = set(self._free)
        if len(free_set) != len(self._free):
            raise AssertionError("duplicate entry on the free list")
        if not all(0 < b < self.num_blocks for b in free_set):
            raise AssertionError("free list entry out of range")
        for b in free_set:
            if holders[b]:
                raise AssertionError(f"block {b} is both free and held")
        for b in range(1, self.num_blocks):
            if int(self._refcnt[b]) != holders[b]:
                raise AssertionError(
                    f"block {b} refcount {int(self._refcnt[b])} != "
                    f"{holders[b]} holders"
                )
            if holders[b] == 0 and b not in free_set:
                raise AssertionError(
                    f"block leak: block {b} has no holder and is not free"
                )
        if TRASH_BLOCK in free_set or holders[TRASH_BLOCK]:
            raise AssertionError("trash block was allocated")
        if int(self._refcnt[TRASH_BLOCK]) != 0:
            raise AssertionError("trash block was allocated")
