"""Paged KV-cache block pool: fixed-shape pages + host block accounting.

The PR 5 engine gives every slot a private ``(max_len, H, D)`` cache row,
so HBM occupancy is bounded by ``num_slots * max_len`` tokens whether the
streams are long or short. The pool replaces the rows with a SHARED set
of fixed-shape physical blocks:

- **Pages** (device): per-layer ``{"k", "v"}`` arrays of shape
  ``(num_blocks, block_size, kv_heads, head_dim)``. One allocation for
  the life of the engine; never reshaped, so the zero-recompile contract
  the per-slot path pins (``analysis/jaxpr_contracts.py``) carries over
  unchanged — the paged stages get their own contracts.
- **Block table** (device): ``(num_slots, blocks_per_slot)`` int32 —
  slot-logical block index → physical block id. All gather/scatter
  indices derive from it INSIDE the jit
  (:func:`consensusml_tpu.models.attention.paged_update_kv_cache` /
  :func:`~consensusml_tpu.models.attention.gather_paged_kv`); the decode
  hot loop performs zero host syncs on pool state.
- **Free list** (host-authoritative, device-mirrored): allocation
  decisions happen at admission / block-boundary crossings — host events
  on host ints, off the per-token path. :class:`BlockPool` enforces the
  invariants the tests pin: no double-allocate, no double-free, no leak
  (free + owned always partitions the physical blocks exactly).

**The trash block.** Physical block 0 is reserved and never allocated.
Freed slots' table rows reset to 0, so the decode step's fixed-shape
scatter (every lane writes every step, free lanes included) lands free
lanes' garbage in the trash block instead of in pages another slot now
owns. Garbage gathered from trash (or from an owned block's
not-yet-written tail) sits beyond the length mask, which zeroes its
probability exactly — same argument the per-slot path makes for stale
rows, so slot/block reuse needs no cache clearing.

Occupancy is bounded by total LIVE tokens (``(num_blocks - 1) *
block_size``), not by ``num_slots * max_len``: with a heavy-tail length
mix, a pool sized for the MEAN length serves far more concurrent streams
than per-slot rows sized for the max (the bench serving section measures
exactly this).
"""

from __future__ import annotations

import time
from typing import Any, Callable

import numpy as np

__all__ = ["BlockPool", "NoFreeBlocks", "init_pages", "blocks_for_tokens"]

TRASH_BLOCK = 0  # reserved physical block; free lanes scatter here


class NoFreeBlocks(RuntimeError):
    """The pool cannot satisfy an allocation; callers evict or defer."""


def blocks_for_tokens(tokens: int, block_size: int) -> int:
    """Physical blocks needed to hold ``tokens`` logical positions."""
    return -(-tokens // block_size)


def init_pages(dm: Any, num_blocks: int, block_size: int) -> list[dict]:
    """Per-layer ``{"k", "v"}`` page pools,
    ``(num_blocks, block_size, kv_heads, head_dim)`` in the model's
    compute dtype (Llama-GQA pages pre-repeat heads, like the slot
    cache). ~2 * layers * num_blocks * block_size * kv_heads * d *
    itemsize bytes total — sized by live tokens, not slots * max_len."""
    import jax.numpy as jnp

    shape = (num_blocks, block_size, dm.kv_heads, dm.head_dim)
    return [
        {
            "k": jnp.zeros(shape, dm.cache_dtype),
            "v": jnp.zeros(shape, dm.cache_dtype),
        }
        for _ in range(dm.layers)
    ]


class BlockPool:
    """Host-side block accounting for one engine (engine-thread only).

    LIFO free list (hot blocks reuse hot HBM lines), per-slot owned
    lists, and the host-authoritative block table mirrored to device on
    mutation. All methods raise on invariant violations rather than
    corrupting silently — a double-free here would hand one physical
    block to two live slots, the paged equivalent of a use-after-free.
    """

    def __init__(
        self,
        num_slots: int,
        max_len: int,
        block_size: int,
        num_blocks: int = 0,
        clock: Callable[[], float] | None = None,
    ):
        if block_size < 1:
            raise ValueError(f"block_size must be positive, got {block_size}")
        if max_len % block_size != 0:
            raise ValueError(
                f"block_size {block_size} must divide max_len {max_len} "
                "(keeps the gathered view bit-identical to the per-slot "
                "cache layout and prompt buckets block-aligned)"
            )
        self.num_slots = num_slots
        self.max_len = max_len
        self.block_size = block_size
        self.blocks_per_slot = max_len // block_size
        auto = num_slots * self.blocks_per_slot + 1
        self.num_blocks = num_blocks or auto
        if self.num_blocks < self.blocks_per_slot + 1:
            raise ValueError(
                f"num_blocks {self.num_blocks} cannot hold even one "
                f"max-length stream ({self.blocks_per_slot} blocks "
                "+ the trash block); the engine could never admit"
            )
        # LIFO stack of free physical ids; block 0 (trash) never enters
        self._free: list[int] = list(range(self.num_blocks - 1, 0, -1))
        self._owned: dict[int, list[int]] = {}
        self._table = np.zeros((num_slots, self.blocks_per_slot), np.int32)
        self._dev_table = None  # invalidated on mutation, rebuilt lazily
        # block-second accounting (docs/observability.md "Wide events &
        # tenant accounting"): per-slot ∫ held_blocks dt, integrated at
        # every mutation — each alloc/extend/shrink/release first adds
        # held × elapsed at the OLD holding, then mutates, so the
        # integral is exact piecewise-constant occupancy over hold time.
        # The clock is injectable so tests pin the math deterministically.
        self._clock = clock if clock is not None else time.monotonic
        self._bs_acc: dict[int, float] = {}
        self._bs_t: dict[int, float] = {}

    # -- introspection ------------------------------------------------------

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1  # trash excluded

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return sum(len(b) for b in self._owned.values())

    def owned(self, slot: int) -> list[int]:
        return list(self._owned.get(slot, ()))

    def can_admit(self, n_blocks: int) -> bool:
        return len(self._free) >= n_blocks

    def _integrate(self, slot: int) -> None:
        """Advance ``slot``'s block-second integral to now at its
        CURRENT holding (call before any mutation of the holding)."""
        t = self._bs_t.get(slot)
        if t is None:
            return
        now = self._clock()
        self._bs_acc[slot] += len(self._owned.get(slot, ())) * (now - t)
        self._bs_t[slot] = now

    def block_seconds(self, slot: int) -> float:
        """``slot``'s block-seconds held so far (∫ owned_blocks dt since
        its alloc, integrated to now). 0.0 for a slot that owns nothing
        — the engine reads this immediately BEFORE :meth:`release` and
        accumulates it onto the request, so the total survives
        recompute-preemption and re-admission."""
        if slot not in self._owned:
            return 0.0
        self._integrate(slot)
        return self._bs_acc.get(slot, 0.0)

    # -- mutation -----------------------------------------------------------

    def alloc(self, slot: int, n_blocks: int) -> list[int]:
        """Give ``slot`` its first ``n_blocks`` blocks (admission)."""
        if slot in self._owned:
            raise RuntimeError(
                f"slot {slot} already owns blocks (double-alloc); "
                "release before re-admitting"
            )
        if n_blocks > self.blocks_per_slot:
            raise ValueError(
                f"slot {slot} asked for {n_blocks} blocks "
                f"> blocks_per_slot {self.blocks_per_slot}"
            )
        if len(self._free) < n_blocks:
            raise NoFreeBlocks(
                f"need {n_blocks} blocks, {len(self._free)} free"
            )
        self._owned[slot] = []
        self._bs_acc[slot] = 0.0
        self._bs_t[slot] = self._clock()
        return self.extend(slot, n_blocks)

    def extend(self, slot: int, n_blocks: int = 1) -> list[int]:
        """Grow ``slot`` by ``n_blocks`` (decode crossing a boundary)."""
        owned = self._owned.get(slot)
        if owned is None:
            raise RuntimeError(f"slot {slot} owns nothing; alloc first")
        if len(owned) + n_blocks > self.blocks_per_slot:
            raise ValueError(
                f"slot {slot} would exceed blocks_per_slot "
                f"({len(owned)} + {n_blocks} > {self.blocks_per_slot})"
            )
        if len(self._free) < n_blocks:
            raise NoFreeBlocks(
                f"need {n_blocks} blocks, {len(self._free)} free"
            )
        self._integrate(slot)
        got = []
        for _ in range(n_blocks):
            b = self._free.pop()
            self._table[slot, len(owned)] = b
            owned.append(b)
            got.append(b)
        self._dev_table = None
        return got

    def shrink(self, slot: int, keep_blocks: int) -> list[int]:
        """Return ``slot``'s blocks BEYOND the first ``keep_blocks`` to
        the free list (speculative rollback: a rejected draft suffix
        hands its over-allocated tail back; the kept prefix — committed
        tokens plus the next write — is untouched). Freed table entries
        reset to trash. Returns the freed ids (possibly empty)."""
        owned = self._owned.get(slot)
        if owned is None:
            raise RuntimeError(f"slot {slot} owns nothing; alloc first")
        if keep_blocks < 1:
            raise ValueError(
                f"keep_blocks must be >= 1, got {keep_blocks} (release() "
                "frees a slot outright)"
            )
        self._integrate(slot)
        freed = []
        while len(owned) > keep_blocks:
            b = owned.pop()
            if b == TRASH_BLOCK or b in self._free:
                raise RuntimeError(f"corrupt free list: block {b}")
            self._free.append(b)
            self._table[slot, len(owned)] = TRASH_BLOCK
            freed.append(b)
        if freed:
            self._dev_table = None
        return freed

    def release(self, slot: int) -> list[int]:
        """Return all of ``slot``'s blocks to the free list and reset its
        table row to the trash block."""
        owned = self._owned.pop(slot, None)
        if owned is None:
            raise RuntimeError(f"slot {slot} owns nothing (double-free)")
        self._bs_acc.pop(slot, None)
        self._bs_t.pop(slot, None)
        for b in owned:
            if b == TRASH_BLOCK or b in self._free:
                raise RuntimeError(f"corrupt free list: block {b}")
            self._free.append(b)
        self._table[slot, :] = TRASH_BLOCK
        self._dev_table = None
        return owned

    # -- views --------------------------------------------------------------

    def block_row(self, slot: int, width: int) -> np.ndarray:
        """``slot``'s physical ids padded with trash to ``width`` entries
        (the prefill scatter's fixed-shape index vector: pad blocks
        beyond the owned prefix land in trash)."""
        owned = self._owned.get(slot, ())
        row = np.full((width,), TRASH_BLOCK, np.int32)
        n = min(len(owned), width)
        row[:n] = owned[:n]
        return row

    def device_table(self, extra_cols: int = 0):
        """The block table as a device array (cached; host→device copy
        only after a mutation, never inside the decode step).

        ``extra_cols > 0`` appends that many TRASH columns — the
        speculative verify window's overflow guard: a stream within
        ``k`` tokens of ``max_len`` computes window positions past its
        real row, and ``table[s, pos // bs]`` must resolve those to the
        trash block rather than index-clamp into the slot's LAST owned
        block (which holds live tokens). Cached per width."""
        if self._dev_table is None:  # invalidated by a mutation
            self._dev_table = {}
        if extra_cols not in self._dev_table:
            import jax.numpy as jnp

            table = self._table
            if extra_cols:
                pad = np.full(
                    (self.num_slots, extra_cols), TRASH_BLOCK, np.int32
                )
                table = np.concatenate([table, pad], axis=1)
            self._dev_table[extra_cols] = jnp.asarray(table)
        return self._dev_table[extra_cols]

    def check(self) -> None:
        """Invariant sweep (tests + debug): free ∪ owned partitions the
        non-trash physical blocks with no overlap and no leak."""
        seen = list(self._free)
        for slot, blocks in self._owned.items():
            seen.extend(blocks)
            row = self._table[slot, : len(blocks)]
            if list(row) != blocks:
                raise AssertionError(
                    f"slot {slot} table row {list(row)} != owned {blocks}"
                )
        if sorted(seen) != list(range(1, self.num_blocks)):
            raise AssertionError(
                f"block leak/duplicate: {len(seen)} accounted of "
                f"{self.num_blocks - 1} usable"
            )
        if TRASH_BLOCK in seen:
            raise AssertionError("trash block was allocated")
