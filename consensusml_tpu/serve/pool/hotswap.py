"""Drain-free hot checkpoint swap: generation-tagged artifact reload.

The trainer re-exports the consensus mean at every ``--checkpoint-every``
boundary (``serve/export.py``, atomic meta-last writes, monotonically
increasing ``generation``). The serving side closes the loop WITHOUT a
drain: a watcher thread polls the artifact directory, stages any new
generation off the engine thread (orbax restore + ``device_put`` happen
here, never between decode steps), and the engine flips its params
pointer — and every resident slot's generation tag — between two decode
steps. No stream drops, no request drains, and because the new mean tree
has byte-identical leaf shapes/dtypes, the staged params hit the SAME
compiled executables: zero recompiles across a swap (the e2e test pins
both).

Streams that straddle a swap keep their KV cache (prefix computed under
generation g, suffix under g+1). For consensus checkpoints of one
converging run the trees are deliberately close — this is the standard
serving trade for continuous deployment, and the per-slot tags +
``consensusml_serve_generation`` make the boundary observable instead of
silent.

Monotonicity is enforced on the READ side too: a meta whose generation
goes backwards (a stale artifact rsynced over a newer one, a clock-reset
re-export) is rejected and counted on
``consensusml_serve_swap_rejected_total``, never served.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable

from consensusml_tpu.analysis import guarded_by

__all__ = ["StagedSwap", "GenerationWatcher"]


@dataclasses.dataclass(frozen=True)
class StagedSwap:
    """A generation staged on device, ready for an atomic pointer flip."""

    generation: int
    params: Any
    meta: dict[str, Any]
    # meta-file mtime at stage time: the flip-rejection marker uses it
    # to tell "same bad artifact still on disk" from "corrected artifact
    # rewritten at the same generation"
    meta_mtime: float = 0.0
    # speculative DRAFT params staged alongside the target (watcher
    # built with stage_draft=True and a draft/ subartifact present);
    # None on non-speculative engines — the engine flips target-only
    draft_params: Any = None


@guarded_by(
    "_lock", "_staged", "_generation", "_rejected_gen", "_flip_rejected"
)
class GenerationWatcher:
    """Polls a serving-artifact dir and stages new generations.

    ``take()`` (engine thread, between decode steps) returns the newest
    staged swap and clears it — if two generations land within one poll
    window the engine flips straight to the newest. The loader runs on
    the watcher thread; a torn/corrupt artifact read (export in flight)
    is retried next poll, never propagated into the serving loop.

    The watcher thread (``poll_once``) and the engine thread (``take``/
    ``reject``) share four fields — the staged swap, the accepted-
    generation mark and the two rejection markers — ALL moved under
    ``_lock`` (enforced by ``@guarded_by``): the engine's flip-time
    ``reject()`` rolls the generation mark BACK while a poll may be
    mid-flight, so the old lock-free reads could stage against a stale
    mark or miss a rejection marker entirely. The artifact load itself
    (orbax restore + ``device_put`` + fence, the seconds-long part)
    stays OUTSIDE the lock; ``take()`` is one uncontended lock per
    decode step.
    """

    def __init__(
        self,
        path: str,
        *,
        current_generation: int = 0,
        poll_s: float = 0.25,
        loader: Callable[[str], tuple[dict, Any, Any]] | None = None,
        stage_draft: bool = False,
    ):
        from consensusml_tpu.obs import get_registry

        self.path = path
        self.poll_s = poll_s
        # speculative engines: restage the draft/ subartifact with every
        # parent-generation advance (the parent counter orders the pair)
        self.stage_draft = stage_draft
        self._generation = current_generation  # newest ACCEPTED generation
        self._loader = loader
        self._staged: StagedSwap | None = None
        self._rejected_gen: int | None = None  # last regression counted
        # (generation, meta_mtime) the ENGINE rejected at flip time —
        # poll_once skips that exact artifact instead of restaging it
        # every poll, but a rewrite (new mtime) retries
        self._flip_rejected: tuple[int, float] | None = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        reg = get_registry()
        self._m_staged = reg.counter(
            "consensusml_serve_swap_staged_total",
            "new artifact generations loaded and staged by the watcher",
        )
        self._m_rejected = reg.counter(
            "consensusml_serve_swap_rejected_total",
            "artifact metas rejected (generation not strictly increasing, "
            "or params tree mismatch at flip time)",
        )
        self._m_load = reg.histogram(
            "consensusml_serve_swap_stage_seconds",
            "artifact restore + device staging wall time (watcher thread)",
        )
        self._thread = threading.Thread(
            target=self._run, name="serve-hotswap", daemon=True
        )
        self._thread.start()

    @property
    def generation(self) -> int:
        """Newest ACCEPTED generation (staged or already flipped)."""
        with self._lock:
            return self._generation

    @generation.setter
    def generation(self, value: int) -> None:
        with self._lock:
            self._generation = int(value)

    # -- watcher thread -----------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:
                # a half-written artifact or transient IO error is not a
                # serving incident; the next poll sees the finished write
                continue

    def poll_once(self) -> bool:
        """One poll: stage the artifact iff its generation advanced.
        Public for deterministic tests; returns True when staged."""
        from consensusml_tpu.serve.export import META_NAME, serving_meta

        try:
            meta = serving_meta(self.path)
        except ValueError:
            return False  # no artifact yet / torn write in progress
        gen = int(meta.get("generation", 0))
        with self._lock:
            behind = gen <= self._generation
            # count each observed regression ONCE, not once per poll — a
            # stale artifact sits on disk until replaced, and a counter
            # ramping 4/s would read as a flood of bad exports
            regressed = gen < self._generation and gen != self._rejected_gen
            if regressed:
                self._rejected_gen = gen
        if behind:
            if regressed:
                self._m_rejected.inc()
            return False
        import os

        try:
            mtime = os.path.getmtime(os.path.join(self.path, META_NAME))
        except OSError:
            return False  # replaced between read and stat; next poll
        with self._lock:
            if self._flip_rejected == (gen, mtime):
                # engine rejected THIS artifact; await a rewrite
                return False
        import jax

        t0 = time.perf_counter()
        if self._loader is not None:
            _meta, params, _ms = self._loader(self.path)
        else:
            from consensusml_tpu.serve.export import load_serving

            _meta, params, _ms = load_serving(self.path)
        draft_params = None
        if self.stage_draft:
            from consensusml_tpu.serve.export import DRAFT_SUBDIR, load_serving

            draft_dir = os.path.join(self.path, DRAFT_SUBDIR)
            if os.path.isdir(draft_dir):
                # a torn draft read raises -> _run retries next poll;
                # the pair stages together or not at all
                _dmeta, draft_params, _dms = load_serving(draft_dir)
                draft_params = jax.device_put(draft_params)
        params = jax.device_put(params)
        # force the H2D copies HERE, not lazily at the engine's first
        # post-flip step (that would be a hidden prefill-sized stall)
        jax.block_until_ready(params)
        if draft_params is not None:
            jax.block_until_ready(draft_params)
        self._m_load.observe(time.perf_counter() - t0)
        with self._lock:
            # re-check: the engine may have rejected THIS (gen, mtime)
            # during the seconds-long load above — staging it anyway
            # would re-run the doomed flip/reject cycle once per poll
            if self._flip_rejected == (gen, mtime):
                return False
            self._staged = StagedSwap(gen, params, meta, mtime, draft_params)
            self._generation = gen
        self._m_staged.inc()
        return True

    # -- engine thread ------------------------------------------------------

    def take(self) -> StagedSwap | None:
        with self._lock:  # uncontended except in the staging instant
            staged, self._staged = self._staged, None
        return staged

    def reject(self, staged: StagedSwap | None = None) -> None:
        """Engine-side rejection (tree mismatch at flip time).

        Rolls the accepted-generation mark BACK so a corrected artifact
        re-exported at the SAME generation number is staged on a later
        poll — without the rollback, one bad artifact would poison its
        generation forever and the engine would silently serve stale
        params until some writer bumped past it. The (generation, meta
        mtime) marker keeps the watcher from restaging the exact bad
        artifact every poll window."""
        self._m_rejected.inc()
        if staged is None:
            return
        with self._lock:
            self._flip_rejected = (staged.generation, staged.meta_mtime)
            if self._generation == staged.generation:
                self._generation = staged.generation - 1

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
