"""Speculative decode: draft-propose-k, ONE fused k-token verify.

The paged decode stage emits one token per target forward; raw tokens/s
is bounded by the target model's step latency however many lanes ride
the batch. Speculative decoding (Leviathan et al. / Chen et al.) breaks
that bound with two fixed-shape executables per engine tick:

- **propose** — a small DRAFT model (same forward hooks as the target,
  its own smaller KV pages over the SAME block table) runs ``k``
  single-token decode steps as one ``lax.scan`` jit, sampling each
  proposal under the canonical ``(seed, position)`` fold keys
  (:mod:`consensusml_tpu.serve.sampling`) and returning the proposals
  plus the draft's full sampling distributions;
- **verify** — ONE target forward over the ``k + 1``-token window
  ``[pending, x_1 .. x_k]`` per slot (the fixed-shape widening of the
  decode stage: same paged gather, same length-mask argument), followed
  IN-JIT by rejection-sampling acceptance:

  accept ``x_i`` iff ``u_i * q_i(x_i) <= p_i(x_i)`` (``u_i`` uniform
  under the ``ACCEPT_TAG`` key at position ``p0 + i - 1``); on the first
  rejection, emit a replacement drawn from the residual
  ``max(p_i - q_i, 0)`` renormalized (``RESIDUAL_TAG``); if all ``k``
  survive, emit the bonus token from ``p_{k+1}`` under the SAME
  ``SAMPLE_TAG`` key plain decode would have used at that position.

  The emitted stream is therefore distributed EXACTLY as target-only
  sampling — and when the draft IS the target (the test fixture), every
  acceptance ratio is 1 and the stream is bit-for-bit the target-only
  stream, because every draw reuses the plain path's key schedule.

KV bookkeeping: verify scatters all ``k + 1`` rows; the accepted prefix
is committed by advancing ``next_pos`` on the host (ints only, no
device sync), and the rejected suffix needs no device rollback — its
rows sit past the committed length, masked to exactly zero probability,
and the next window overwrites them. The draft cache self-heals the
same way. Overflow positions near ``max_len`` route through the
engine's trash-padded block-table columns
(:meth:`~consensusml_tpu.serve.pool.blocks.BlockPool.device_table`).

Prefix sharing composes for free: the draft's pages mirror the pool's
block GEOMETRY (same physical ids, same offsets), so when an admission
adopts indexed prefix blocks the draft adopts them too — the engine
runs the draft's prefix-prefill over the same block row, and both
models skip the shared prompt (``serve/pool/prefix.py``). Spec writes
land at positions ≥ the committed length, never inside a shared prompt
block, and the engine's lazy shrink only pops the owned TAIL — the
refcounted pool (``blocks.py``) keeps shared front blocks alive until
their last holder releases.

Both executables are step-over-step jaxpr-contract-pinned
(``analysis/jaxpr_contracts.py``: no host callbacks, no f64, canonical
hash stable across sampled ticks) and registered in the cost ledger
(``Engine.register_costs``: ``serve.spec.propose`` /
``serve.spec.verify`` rows).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

__all__ = [
    "SpecConfig",
    "make_draft_propose_fn",
    "make_verify_fn",
    "propose_cost_args",
    "verify_cost_args",
    "spec_table_cols",
]


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Speculative-decode configuration for :class:`~consensusml_tpu.
    serve.engine.Engine`.

    ``model``/``params`` are the draft causal LM (GPT2LM/LlamaLM — any
    model the serving forward contract covers; it must share the
    target's vocab). ``k`` is the proposal depth: each engine tick costs
    one draft scan of ``k`` steps plus ONE target verify, and emits
    between 1 and ``k + 1`` tokens per live lane. Higher ``k`` amortizes
    the target forward further but wastes more draft work per
    rejection — tune against the measured acceptance rate
    (``consensusml_spec_acceptance_rate``; docs/serving.md "Choosing
    k")."""

    model: Any
    params: Any
    k: int = 4

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"spec k must be >= 1, got {self.k}")


def spec_table_cols(blocks_per_slot: int, block_size: int, k: int) -> int:
    """Block-table width the speculative stages index: the real columns
    plus enough TRASH padding that the verify window's worst-case
    position (``max_len - 1 + k``) still resolves in-bounds."""
    return blocks_per_slot + (block_size - 1 + k) // block_size


def propose_cost_args(num_slots: int, table_cols: int) -> tuple:
    """Abstract ``(block_table, tokens, positions, temperature, top_p,
    seeds)`` shape structs of the draft-propose executable for the cost
    ledger's AOT lowering."""
    import jax
    import jax.numpy as jnp

    return (
        jax.ShapeDtypeStruct((num_slots, table_cols), jnp.int32),
        jax.ShapeDtypeStruct((num_slots,), jnp.int32),
        jax.ShapeDtypeStruct((num_slots,), jnp.int32),
        jax.ShapeDtypeStruct((num_slots,), jnp.float32),
        jax.ShapeDtypeStruct((num_slots,), jnp.float32),
        jax.ShapeDtypeStruct((num_slots,), jnp.uint32),
    )


def verify_cost_args(
    num_slots: int, table_cols: int, k: int, vocab: int
) -> tuple:
    """Abstract ``(block_table, tokens, proposals, q_sel, q_probs,
    positions, temperature, top_p, seeds)`` shape structs of the ONE
    k-verify executable."""
    import jax
    import jax.numpy as jnp

    return (
        jax.ShapeDtypeStruct((num_slots, table_cols), jnp.int32),
        jax.ShapeDtypeStruct((num_slots,), jnp.int32),
        jax.ShapeDtypeStruct((num_slots, k), jnp.int32),
        jax.ShapeDtypeStruct((num_slots, k), jnp.float32),
        jax.ShapeDtypeStruct((num_slots, k, vocab), jnp.float32),
        jax.ShapeDtypeStruct((num_slots,), jnp.int32),
        jax.ShapeDtypeStruct((num_slots,), jnp.float32),
        jax.ShapeDtypeStruct((num_slots,), jnp.float32),
        jax.ShapeDtypeStruct((num_slots,), jnp.uint32),
    )


def make_draft_propose_fn(
    draft_dm: Any, k: int, attn_impl: str = "gather"
) -> Callable:
    """``propose(draft_params, draft_pages, block_table, tokens (S,),
    positions (S,), temperature (S,), top_p (S,), seeds (S,))`` ->
    ``(proposals (S, k), q_sel (S, k), q_probs (S, k, V),
    new_draft_pages)``.

    ``lax.scan`` of ``k + 1`` draft decode steps in ONE executable: step
    ``i`` writes the current token's draft K/V at position ``p0 + i``
    (same paged scatter as the target decode stage, against the draft's
    own pages) and samples proposal ``x_{i+1}`` under the plain path's
    ``SAMPLE_TAG`` key at that position — so a draft that equals the
    target proposes exactly the tokens target-only decode would emit.
    The ``k + 1``-th step exists for its WRITE, not its sample (which is
    discarded): it commits ``x_k``'s draft K/V at ``p0 + k``, so after a
    fully-accepted round the draft cache has no gap at the next round's
    prefix (every draft row is written exactly once, by the same
    decode-step math the plain path would use — which is what keeps the
    self-draft fixture bit-exact). ``q_sel`` is the draft probability of
    each chosen token (the acceptance ratio's denominator), ``q_probs``
    the full distributions (the residual re-draw's subtrahend).

    ``attn_impl`` (static) selects the paged-attention tier for every
    scanned draft step (:mod:`consensusml_tpu.models.paged_attention`;
    all impls bit-exact, so the self-draft fixture stays bit-exact on
    the kernel tier too).
    """
    import jax
    import jax.numpy as jnp

    from consensusml_tpu.serve.decode import _donate_cache
    from consensusml_tpu.serve.sampling import (
        SAMPLE_TAG,
        adjusted_probs,
        categorical_from_probs,
        sampling_keys,
    )

    model = draft_dm.model

    def propose(
        draft_params, draft_pages, block_table, tokens, positions,
        temperature, top_p, seeds,
    ):
        def body(carry, _):
            tok, pos, pages = carry
            logits, pages = model.apply(
                {"params": draft_params},
                tok[:, None],
                deterministic=True,
                positions=pos,
                kv_cache=pages,
                block_table=block_table,
                attn_impl=attn_impl,
            )
            probs = adjusted_probs(logits[:, 0], temperature, top_p)
            nxt = categorical_from_probs(
                sampling_keys(seeds, pos, SAMPLE_TAG), probs
            )
            q = jnp.take_along_axis(probs, nxt[:, None], axis=1)[:, 0]
            return (nxt, pos + 1, pages), (nxt, q, probs)

        (_, _, new_pages), (props, q_sel, q_probs) = jax.lax.scan(
            body, (tokens, positions, draft_pages), None, length=k + 1
        )
        # scan stacks along axis 0; slots lead outside. The final step's
        # sample is the write-only tail — dropped here.
        return (
            jnp.moveaxis(props[:k], 0, 1),
            jnp.moveaxis(q_sel[:k], 0, 1),
            jnp.moveaxis(q_probs[:k], 0, 1),
            new_pages,
        )

    return jax.jit(propose, donate_argnums=_donate_cache())


def make_verify_fn(dm: Any, k: int, attn_impl: str = "gather") -> Callable:
    """``verify(params, pages, block_table, tokens (S,), proposals
    (S, k), q_sel (S, k), q_probs (S, k, V), positions (S,), temperature
    (S,), top_p (S,), seeds (S,))`` -> ``(n_accept (S,), final (S,),
    new_pages)``.

    The one fused verify: a ``k + 1``-token target forward per slot
    (window ``[pending, x_1 .. x_k]`` at positions ``p0 .. p0 + k``),
    then branchless rejection-sampling acceptance entirely in-jit. The
    emitted tokens for a lane are ``x_1 .. x_{n_accept}`` followed by
    ``final`` (the residual replacement at the first rejected row, or
    the bonus draw when everything survived); the host reads back three
    small arrays and does pure int bookkeeping.

    ``attn_impl`` (static) selects the paged-attention tier for the
    k+1-window forward (:mod:`consensusml_tpu.models.paged_attention`:
    the fused window kernel vs the gather reference — bit-exact either
    way, so acceptance decisions are impl-independent).
    """
    import jax
    import jax.numpy as jnp

    from consensusml_tpu.serve.decode import _donate_cache
    from consensusml_tpu.serve.sampling import (
        ACCEPT_TAG,
        RESIDUAL_TAG,
        SAMPLE_TAG,
        adjusted_probs,
        categorical_from_probs,
        sampling_keys,
    )

    model = dm.model

    def verify(
        params, pages, block_table, tokens, proposals, q_sel, q_probs,
        positions, temperature, top_p, seeds,
    ):
        window = jnp.concatenate([tokens[:, None], proposals], axis=1)
        pos_mat = positions[:, None] + jnp.arange(k + 1)[None, :]
        logits, new_pages = model.apply(
            {"params": params},
            window,
            deterministic=True,
            positions=pos_mat,
            kv_cache=pages,
            block_table=block_table,
            attn_impl=attn_impl,
        )
        # target distributions for every window row, same temp/top-p
        # transform as the draft applied (the acceptance ratio compares
        # like with like — docs/serving.md "Acceptance math")
        p_dist = adjusted_probs(
            logits, temperature[:, None], top_p[:, None]
        )  # (S, k+1, V)
        p_sel = jnp.take_along_axis(
            p_dist[:, :k], proposals[:, :, None], axis=2
        )[:, :, 0]  # (S, k)
        u = jax.vmap(jax.random.uniform)(
            sampling_keys(
                seeds[:, None] + jnp.zeros((1, k), jnp.uint32),
                pos_mat[:, :k],
                ACCEPT_TAG,
            ).reshape(-1, 2)
        ).reshape(p_sel.shape)
        # accept x_i with prob min(1, p_i/q_i); u*q <= p avoids the
        # division (q == p — the self-draft fixture — accepts at u < 1,
        # i.e. always, which is what makes that stream bit-exact)
        accept = u * q_sel <= p_sel
        prefix = jnp.cumprod(accept.astype(jnp.int32), axis=1)
        n_accept = jnp.sum(prefix, axis=1)  # (S,) in [0, k]
        # one fallback draw per row: rows < k resample the residual
        # max(p - q, 0) (renormalized; an all-zero residual — p under q
        # everywhere mass sits — degenerates to p, unreachable when a
        # rejection actually happened there), row k draws the BONUS from
        # p_k under the plain SAMPLE_TAG key at position p0 + k
        resid = jnp.maximum(p_dist[:, :k] - q_probs, 0.0)
        rsum = jnp.sum(resid, axis=-1, keepdims=True)
        resid = jnp.where(rsum > 0, resid / jnp.maximum(rsum, 1e-38),
                          p_dist[:, :k])
        fall_rows = categorical_from_probs(
            sampling_keys(
                seeds[:, None] + jnp.zeros((1, k), jnp.uint32),
                pos_mat[:, :k],
                RESIDUAL_TAG,
            ),
            resid,
        )  # (S, k)
        bonus = categorical_from_probs(
            sampling_keys(seeds, positions + k, SAMPLE_TAG),
            p_dist[:, k],
        )  # (S,)
        fallback = jnp.concatenate([fall_rows, bonus[:, None]], axis=1)
        final = jnp.take_along_axis(
            fallback, n_accept[:, None], axis=1
        )[:, 0]
        return n_accept.astype(jnp.int32), final.astype(jnp.int32), new_pages

    return jax.jit(verify, donate_argnums=_donate_cache())
