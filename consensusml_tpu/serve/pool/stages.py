"""Disaggregated prefill/decode stages over the paged block pool.

The PR 5 engine ran ONE fused step family per admission pattern: when
several requests arrived together, `_admit_waiting` prefilled every free
slot back-to-back before the next decode step, so one long prompt — or a
burst of them — stalled every in-flight stream (inter-token latency
spikes exactly when traffic peaks). This module splits the two phases
into separately-jitted, separately-scheduled stages:

- :func:`make_paged_prefill_fn` — one executable per prompt bucket, full
  causal forward, K/V scattered into the slot's OWNED pool blocks (pad
  blocks beyond the owned prefix land in the trash block);
- :func:`make_paged_decode_fn` — ONE executable for all slots at every
  occupancy/length mix, block indices computed inside the jit from the
  block table (no host sync, no recompile — contract-pinned per stage by
  ``analysis/jaxpr_contracts.py``);
- :class:`AdmissionScheduler` — the host-side policy between them: every
  engine tick runs AT MOST ``prefill_budget`` tokens of prefill, and the
  decode step runs every tick regardless, so decode never waits behind
  more than one budget's worth of prefill. (On one host the stages share
  a device; a multi-replica deployment would place them on disjoint
  replicas — the program split here is the prerequisite either way.)

TTFT p99 (``consensusml_serve_ttft_seconds``) is the target metric; the
bench serving section compares the fused baseline against the staged
path at an equal token budget.
"""

from __future__ import annotations

from typing import Any, Callable

__all__ = [
    "make_paged_prefill_fn",
    "make_paged_decode_fn",
    "make_prefix_prefill_fn",
    "prefill_cost_args",
    "decode_cost_args",
    "prefix_prefill_cost_args",
    "AdmissionScheduler",
]


def prefill_cost_args(bucket: int, block_size: int) -> tuple:
    """Abstract non-tree arguments of one paged-prefill invocation at
    ``bucket`` tokens — ``(ids, length, block_row, temperature, top_p,
    seed)`` shape structs for the cost ledger's AOT lowering
    (``Engine.register_costs``). Shapes mirror exactly what the live
    path passes, so the ledger's compiled row IS the serving
    executable's cost, not a lookalike's."""
    import jax
    import jax.numpy as jnp

    return (
        jax.ShapeDtypeStruct((1, bucket), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.int32),
        jax.ShapeDtypeStruct((bucket // block_size,), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.float32),
        jax.ShapeDtypeStruct((), jnp.float32),
        jax.ShapeDtypeStruct((), jnp.uint32),
    )


def decode_cost_args(num_slots: int, blocks_per_slot: int) -> tuple:
    """Abstract ``(block_table, tokens, positions, temperature, top_p,
    seeds)`` shape structs of the ONE paged-decode executable (every
    occupancy/length/sampling mix runs this same program — one ledger
    row covers all of serving decode)."""
    import jax
    import jax.numpy as jnp

    return (
        jax.ShapeDtypeStruct((num_slots, blocks_per_slot), jnp.int32),
        jax.ShapeDtypeStruct((num_slots,), jnp.int32),
        jax.ShapeDtypeStruct((num_slots,), jnp.int32),
        jax.ShapeDtypeStruct((num_slots,), jnp.float32),
        jax.ShapeDtypeStruct((num_slots,), jnp.float32),
        jax.ShapeDtypeStruct((num_slots,), jnp.uint32),
    )


def make_paged_prefill_fn(dm: Any) -> Callable:
    """``prefill(params, pages, ids (1, L), length, block_row (L//bs,),
    temperature, top_p, seed)`` -> ``(first_token, last_logits (V,),
    new_pages)``.

    One executable per padded bucket length ``L`` (block-aligned by
    construction: the engine's paged buckets start at the block size).
    The forward is the SAME ``return_kv`` trace the per-slot prefill
    uses; only the cache insertion differs — each ``block_size`` chunk of
    the prompt's K/V scatters to the physical block its table row names.
    ``block_row`` entries past the owned prefix are the trash block, so
    pad chunks never touch pages another slot owns; duplicate trash
    indices are benign (last-write-wins over garbage). The first token
    samples in-jit at fold position ``length - 1``
    (:mod:`consensusml_tpu.serve.sampling`; ``temperature = 0`` = the
    original greedy argmax).
    """
    import jax
    import jax.numpy as jnp

    from consensusml_tpu.serve.decode import _donate_cache
    from consensusml_tpu.serve.sampling import sample_token

    model = dm.model

    def prefill(params, pages, ids, length, block_row, temperature, top_p, seed):
        logits, kvs = model.apply(
            {"params": params}, ids, deterministic=True, return_kv=True
        )
        last = logits[0, length - 1]  # (V,) — last REAL token's logits
        bs = pages[0]["k"].shape[1]
        nblk = ids.shape[1] // bs
        new_pages = []
        for pg, (k, v) in zip(pages, kvs):
            # (1, L, H, D) -> (nblk, bs, H, D): chunk per physical block
            kr = jnp.asarray(k[0], pg["k"].dtype).reshape(
                nblk, bs, *k.shape[2:]
            )
            vr = jnp.asarray(v[0], pg["v"].dtype).reshape(
                nblk, bs, *v.shape[2:]
            )
            new_pages.append(
                {
                    "k": pg["k"].at[block_row].set(kr),
                    "v": pg["v"].at[block_row].set(vr),
                }
            )
        tok = sample_token(
            last[None], temperature[None], top_p[None], seed[None],
            (length - 1)[None],
        )[0]
        return tok, last, new_pages

    return jax.jit(prefill, donate_argnums=_donate_cache())


def prefix_prefill_cost_args(
    bucket: int, block_size: int, blocks_per_slot: int
) -> tuple:
    """Abstract non-tree arguments of one prefix-prefill invocation at
    suffix bucket ``bucket`` — ``(ids, suffix_len, start_pos, block_row,
    cow_src, cow_dst, temperature, top_p, seed)`` shape structs for the
    cost ledger's AOT lowering. The block row spans the slot's full
    table width plus ``bucket // block_size`` trash overflow columns
    (see :func:`make_prefix_prefill_fn`)."""
    import jax
    import jax.numpy as jnp

    cols = blocks_per_slot + bucket // block_size
    return (
        jax.ShapeDtypeStruct((1, bucket), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.int32),
        jax.ShapeDtypeStruct((cols,), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.float32),
        jax.ShapeDtypeStruct((), jnp.float32),
        jax.ShapeDtypeStruct((), jnp.uint32),
    )


def make_prefix_prefill_fn(dm: Any, attn_impl: str = "gather") -> Callable:
    """``prefix_prefill(params, pages, ids (1, B), suffix_len,
    start_pos, block_row (cols,), cow_src, cow_dst, temperature, top_p,
    seed)`` -> ``(first_token, last_logits (V,), new_pages)``.

    The prefix-cache admission stage: the matched prefix is ALREADY in
    the slot's pool blocks (adopted from the index), so only the
    unshared suffix — ``ids[0, :suffix_len]`` at absolute positions
    ``start_pos + i``, right-padded to bucket ``B`` — runs the forward.
    This reuses the speculative verify's window machinery
    (2-D positions → ``paged_update_kv_cache_window`` +
    windowed paged attention): each suffix token's K/V scatters to
    ``block_row[pos // bs]`` and its query attends the gathered pages
    under the mask ``key_pos <= pos``, which reads the adopted prefix
    KV bit-exactly as the full causal prefill would have recomputed it.

    Where the split is mid-prefix (a FULL-match hit recomputing only the
    last token, or a future partial-block split), the slot's first write
    would land in a block other streams still share; ``cow_src`` /
    ``cow_dst`` resolve that copy-on-write INSIDE the jit
    (:func:`consensusml_tpu.models.attention.paged_cow_copy`): the shared
    source block's rows copy to the slot's fresh block BEFORE the window
    scatter, and ``block_row`` already names the fresh block — no host
    sync, no cache read-back. Passing ``cow_src == cow_dst == 0`` (the
    trash block) disables the copy (a trash self-copy is a benign no-op
    lane, same trick as the decode scatter's free lanes).

    One executable per SUFFIX bucket ``B`` — the same bucket ladder the
    full prefill compiles, so prefix splits change which executable runs,
    never its shape (zero-recompile contract). ``block_row`` carries
    ``B // block_size`` extra trash columns beyond ``blocks_per_slot``:
    bucket pad positions past the real suffix can reach
    ``start_pos + B - 1``, and ``pos // bs`` must resolve past-the-row
    chunks to trash instead of index-clamping into the slot's last owned
    block (same overflow guard as ``spec_table_cols``).
    """
    import jax
    import jax.numpy as jnp

    from consensusml_tpu.models.attention import paged_cow_copy
    from consensusml_tpu.serve.decode import _donate_cache
    from consensusml_tpu.serve.sampling import sample_token

    model = dm.model

    def prefix_prefill(
        params, pages, ids, suffix_len, start_pos, block_row,
        cow_src, cow_dst, temperature, top_p, seed,
    ):
        pages = [paged_cow_copy(pg, cow_src, cow_dst) for pg in pages]
        b = ids.shape[1]
        pos = start_pos + jnp.arange(b, dtype=jnp.int32)[None, :]
        logits, new_pages = model.apply(
            {"params": params},
            ids,
            deterministic=True,
            positions=pos,
            kv_cache=pages,
            block_table=block_row[None, :],
            attn_impl=attn_impl,
        )
        last = logits[0, suffix_len - 1]  # (V,) — last REAL suffix token
        fold = start_pos + suffix_len - 1  # absolute position n - 1:
        # the SAME fold key the full prefill derives, so sampled streams
        # stay bit-identical whichever admission path ran
        tok = sample_token(
            last[None], temperature[None], top_p[None], seed[None],
            fold[None],
        )[0]
        return tok, last, new_pages

    return jax.jit(prefix_prefill, donate_argnums=_donate_cache())


def make_paged_decode_fn(dm: Any, attn_impl: str = "gather") -> Callable:
    """``decode(params, pages, block_table (S, nb), tokens (S,),
    positions (S,), temperature (S,), top_p (S,), seeds (S,))`` ->
    ``(next_tokens (S,), new_pages)``.

    One token for ALL slots; each lane's write/read indices derive from
    its block-table row inside the jit
    (:func:`consensusml_tpu.models.attention.paged_update_kv_cache`),
    and each lane samples under its own ``(seed, position)`` fold key
    (:mod:`consensusml_tpu.serve.sampling`). Occupancy, lengths, block
    assignments, AND sampling parameters are all DATA — one executable
    serves every greedy/sampled mix, the zero-recompile contract. Only
    the pages donate; the block table is reused across steps.

    ``attn_impl`` is a construction-time static: "gather" keeps the
    two-step gather + dense attention; "jnp"/"interpret"/"pallas" run
    the fused paged-attention kernel tier
    (:mod:`consensusml_tpu.models.paged_attention`) — one pallas pass
    per layer, bit-exact vs gather, same zero-recompile contract.
    """
    import jax
    import jax.numpy as jnp

    from consensusml_tpu.serve.decode import _donate_cache
    from consensusml_tpu.serve.sampling import sample_token

    model = dm.model

    def decode(params, pages, block_table, tokens, positions, temperature, top_p, seeds):
        logits, new_pages = model.apply(
            {"params": params},
            tokens[:, None],
            deterministic=True,
            positions=positions,
            kv_cache=pages,
            block_table=block_table,
            attn_impl=attn_impl,
        )
        toks = sample_token(
            logits[:, 0], temperature, top_p, seeds, positions
        )
        return toks, new_pages

    return jax.jit(decode, donate_argnums=_donate_cache())


class AdmissionScheduler:
    """Per-tick prefill admission budget (host ints only, no device).

    One engine tick = one decode step + whatever prefills fit the token
    budget. ``try_admit`` charges a candidate's BUCKET length (what the
    device actually computes) against the tick's remaining budget:

    - the first admission of a tick always fits (otherwise a prompt
      longer than the budget would starve forever);
    - later admissions must fit the remaining budget, so a burst of
      arrivals spreads over several ticks instead of stalling decode for
      the whole burst — bounded added TTFT for the tail of the burst,
      bounded inter-token latency for everyone already decoding.
    """

    def __init__(self, prefill_budget: int):
        if prefill_budget < 1:
            raise ValueError(
                f"prefill_budget must be positive, got {prefill_budget}"
            )
        self.prefill_budget = prefill_budget
        self._remaining = prefill_budget
        self._admitted_this_tick = 0

    def start_tick(self) -> None:
        self._remaining = self.prefill_budget
        self._admitted_this_tick = 0

    def try_admit(self, bucket_tokens: int) -> bool:
        """Charge one prefill of ``bucket_tokens`` against this tick;
        False = defer the request to the next tick."""
        if self._admitted_this_tick and bucket_tokens > self._remaining:
            return False
        self._remaining = max(0, self._remaining - bucket_tokens)
        self._admitted_this_tick += 1
        return True
