"""Consensus-checkpoint serving: export, KV-cache decode, batching.

The train→serve handoff for the decentralized stack (ROADMAP north star:
"serve heavy traffic"). A training run's stacked per-worker replicas
collapse — via the SAME :func:`consensusml_tpu.utils.consensus_mean` the
evaluator and elastic resume use — into a single serving artifact
(:mod:`.export`), which a KV-cache decode engine (:mod:`.decode`) serves
through a slot-based continuous batcher (:mod:`.batcher`,
:class:`.engine.Engine`) and an optional threaded socket front-end
(:mod:`.server`). KV memory lives in a paged block pool by default
(:mod:`.pool` — slot occupancy bounded by live tokens, disaggregated
prefill/decode stages, drain-free hot checkpoint swap via the
artifact's ``generation`` counter); the PR 5 per-slot layout stays as
``ServeConfig(kv_impl="slot")``, the bit-exact parity baseline. The
whole request path is SLO-instrumented (``consensusml_serve_*`` /
``consensusml_pool_*`` metric families + spans, docs/serving.md) and
every serving stage carries its own cml-check jaxpr contract: no host
callbacks and ZERO recompiles across steady-state steps.
"""

from consensusml_tpu.serve.export import (  # noqa: F401
    bump_generation,
    export_draft,
    export_serving,
    load_serving,
    serving_meta,
)
from consensusml_tpu.serve.pool.spec import SpecConfig  # noqa: F401
from consensusml_tpu.serve.decode import (  # noqa: F401
    DecodeModel,
    init_cache,
    supports_decode,
)
from consensusml_tpu.serve.engine import Engine, ServeConfig, load_engine  # noqa: F401
from consensusml_tpu.serve.server import ServeServer  # noqa: F401
