"""Overlapped host→device feed: a double-buffered round prefetcher.

The fed-input bench (docs/perf.md) showed the input path as the dominant
bottleneck: the best feed delivered ~1/8 of what the chip consumes, and
every feed ran its host work (batch assembly, H2D staging) serialized
with device compute. This module closes that gap structurally:

- :class:`DevicePrefetcher` pulls host round-batches from a source
  iterator on a *background thread* and stages each one on device via
  non-blocking :func:`jax.device_put` — so while the jitted round for
  batch ``r`` executes, the transfer for ``r+1`` (and the host-side
  assembly for ``r+2``) are already in flight. The consumer's
  ``__next__`` is a queue pop: no host work, no transfer, **no
  ``block_until_ready``** on the critical path between rounds.
- :class:`FeedItem` carries an optional ``on_done`` callback with each
  batch, invoked once the device transfer for that batch has completed
  — this is what lets the native C++ ring hand out *zero-copy views of
  its own slots* (``NativeLoader.acquire_view``): the slot is pinned as
  the staging buffer and released straight back to the producer threads
  the moment the bytes are on device, eliminating the per-batch
  allocation+copy the consume side used to pay.

Feed-stall telemetry (docs/observability.md) goes to the PR-2 metrics
registry: ``consensusml_feed_stall_seconds`` (gauge, the wait the last
round paid for its batch — ~0 when the overlap is working),
``consensusml_feed_stall_seconds_total`` / ``consensusml_feed_batches_total``
(counters, for overlap ratios over a window), and
``consensusml_feed_inflight`` (queue occupancy at pop — the double
buffer's fill level).

Staging-buffer safety, by backend:

- Accelerator backends: ``jax.device_put`` *copies* host memory to the
  device asynchronously. A host buffer may be rewritten only after that
  transfer completed, so the prefetcher keeps a bounded in-flight window
  and, before pulling a new item from the source, blocks (on the
  *background* thread) until the oldest in-flight transfer is done —
  then fires its ``on_done``. Sources that rotate their own host buffers
  must rotate more than ``depth + 1`` of them (the native ring sizes its
  slot count accordingly, see ``native_pipeline.plan_ring``).
- CPU backend: ``device_put``/``jnp.asarray`` may *alias* numpy memory
  instead of copying, so buffer reuse can never be made safe after the
  fact. The prefetcher therefore copies numpy leaves before placement on
  CPU. The copy happens on the background thread — still overlapped —
  and keeps the CPU test backend byte-exact under any reuse pattern.

Determinism: one producer thread, a FIFO queue, and sources that are
pure functions of ``(seed, round)`` — the delivered batch sequence is
byte-identical regardless of prefetch depth, ring threads, or whether
overlap is on at all (pinned by tests/test_prefetch.py).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, NamedTuple

import numpy as np

from consensusml_tpu.analysis import guarded_by
from consensusml_tpu.obs import get_registry

__all__ = ["FeedItem", "DevicePrefetcher", "prefetch_to_device"]

# feed-path telemetry (docs/observability.md): is the round ever waiting
# on its data, and how full is the double buffer
_STALL = get_registry().gauge(
    "consensusml_feed_stall_seconds",
    "host wait for the current round's batch at the prefetch queue "
    "(~0 when transfer fully overlaps compute)",
)
_STALL_TOTAL = get_registry().counter(
    "consensusml_feed_stall_seconds_total",
    "cumulative feed-stall wait across rounds",
)
_BATCHES_OUT = get_registry().counter(
    "consensusml_feed_batches_total",
    "round batches delivered by the device prefetcher",
)
_INFLIGHT = get_registry().gauge(
    "consensusml_feed_inflight",
    "staged round batches ready in the prefetch queue (sampled at pop)",
)
# the prefetch window's device-byte tag for the live HBM accounting
# (obs/memviz.py): staged-ahead batches are real resident HBM the
# three-way reconciliation must be able to name, not anonymous "live"
_STAGED_BYTES = get_registry().gauge(
    "consensusml_feed_staged_bytes",
    "device bytes of round batches staged ahead by the prefetcher "
    "(queue occupancy x per-batch bytes, sampled at pop)",
)


class FeedItem(NamedTuple):
    """One source item: a host batch plus an optional completion hook.

    ``on_done()`` is called (on the prefetcher's background thread) once
    the device transfer of ``batch`` has completed — the point where the
    host memory backing the batch may be reused. The native ring's
    zero-copy view feed passes its slot-release here.

    ``pool`` declares how many host buffers the source rotates (the
    native ring's slot count): every undrained transfer pins one, so the
    prefetcher clamps its in-flight window to ``pool - 1`` — the
    deadlock invariant lives here, next to the pinning, not in each
    caller's sizing arithmetic.
    """

    batch: Any
    on_done: Callable[[], None] | None = None
    pool: int | None = None


class _Stop(Exception):
    """Internal: consumer closed while the producer was blocked."""


@guarded_by(
    "_lock", "_closed", "_exhausted", "_error",
    "stall_seconds_total", "last_stall_s", "batches_out",
)
class DevicePrefetcher:
    """Stage the next round-batches on device while the current round runs.

    Wraps a host batch iterator (plain trees or :class:`FeedItem`s) and
    yields *device-resident* batches. ``depth`` is the double-buffer
    depth: how many staged batches may sit ready ahead of the consumer
    (2 = classic double buffering; the transfer for round r+1 overlaps
    the compute of round r).

    ``placement`` controls where leaves land: ``None`` (default device),
    a ``jax.sharding.Sharding`` / device applied to every leaf (e.g.
    ``WorkerMesh.stacked_sharding()`` so collective-backend batches land
    pre-sharded and the jitted step does no second transfer), or a
    callable ``batch -> per-leaf tree`` evaluated once on the first
    batch. ``place=False`` skips device placement entirely (multi-
    controller runs, where global arrays are assembled downstream) —
    the prefetcher then only overlaps the host-side work.

    Iterate it (it is its own iterator) or use it as a context manager;
    it closes itself when the source is exhausted, and ``close()`` is
    idempotent for early exits.
    """

    def __init__(
        self,
        source: Iterable[Any],
        depth: int = 2,
        *,
        placement: Any = None,
        place: bool = True,
        max_inflight: int | None = None,
    ):
        self.depth = max(1, int(depth))
        # how many H2D transfers may be outstanding before the producer
        # blocks on the oldest one (and fires its on_done). Sources that
        # recycle a fixed pool of host buffers — the native ring above
        # all — need this capped BELOW their pool size or the pool
        # drains and the pipeline deadlocks; pooled sources declare
        # FeedItem.pool and _run clamps the window to pool-1 itself, so
        # this knob only ever shrinks the window further. 0 = fence
        # every transfer immediately (serialized but never deadlocked).
        self.max_inflight = (
            self.depth if max_inflight is None else max(0, int(max_inflight))
        )
        self._source = iter(source)
        self._placement = placement
        self._place = place
        self._queue: queue.Queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        # shared between the consumer thread, the producer thread and
        # whatever thread GC runs __del__ on — see @guarded_by above;
        # enforced by the cml-check lock-discipline pass
        self._lock = threading.Lock()
        self._error: BaseException | None = None
        self._closed = False
        self._exhausted = False
        # stats mirrored outside the registry so benches/tests can read
        # this feed's numbers without diffing process-global counters
        self.stall_seconds_total = 0.0
        self.last_stall_s = 0.0
        self.batches_out = 0
        # per-batch device bytes (fixed round shape), measured on the
        # first delivered batch for the staged-bytes HBM tag
        self._batch_nbytes: int | None = None
        import jax

        self._jax = jax
        # CPU backend: jnp.asarray/device_put may ALIAS numpy memory, so
        # reused host buffers must be copied before placement (see module
        # docstring); the copy runs on the background thread.
        self._copy_host = jax.default_backend() == "cpu"
        self._thread = threading.Thread(
            target=self._run, name="device-prefetch", daemon=True
        )
        self._thread.start()

    # -- producer side (background thread) --------------------------------

    def _leaf_placement(self, batch: Any) -> Any:
        # a callable placement (sharding factory) resolves once, on the
        # first batch's structure; Sharding/Device instances are not
        # callable so the check is unambiguous
        if callable(self._placement):
            self._placement = self._placement(batch)
        return self._placement

    def _put_leaf(self, x: Any, target: Any):
        jax = self._jax
        if isinstance(x, jax.Array) and (
            target is None or getattr(x, "sharding", None) == target
        ):
            return x  # already placed — never a second transfer
        if self._copy_host and isinstance(x, np.ndarray):
            x = x.copy()
        return jax.device_put(x) if target is None else jax.device_put(x, target)

    def _stage(self, batch: Any) -> Any:
        if not self._place:
            return batch
        jax = self._jax
        placement = self._leaf_placement(batch)
        if placement is None or not isinstance(placement, (dict, list, tuple)):
            return jax.tree.map(lambda x: self._put_leaf(x, placement), batch)
        return jax.tree.map(self._put_leaf, batch, placement)

    def _enqueue(self, item: Any) -> None:
        while True:
            if self._stop.is_set():
                raise _Stop
            try:
                self._queue.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def _drain_one(self, pending: list) -> None:
        staged, on_done = pending.pop(0)
        if self._place:
            # block on the BACKGROUND thread until the H2D transfer of
            # this batch completed — only then may its host buffer be
            # rewritten / its ring slot released
            self._jax.block_until_ready(staged)
        if on_done is not None:
            on_done()

    def _run(self) -> None:
        pending: list = []  # (staged device batch, on_done), oldest first
        window = self.max_inflight
        try:
            for item in self._source:
                if self._stop.is_set():
                    break
                if not isinstance(item, FeedItem):
                    item = FeedItem(item)
                if item.on_done is not None and not self._place:
                    # zero-copy sources pin host memory until the
                    # transfer completes; without placement there is no
                    # transfer event to key the release on, and firing
                    # it early would hand out buffers still in use
                    raise RuntimeError(
                        "FeedItem sources (zero-copy views) require "
                        "device placement (place=True)"
                    )
                if item.pool is not None:
                    # each undrained transfer pins one buffer of the
                    # source's pool — always leave >= 1 free for its
                    # producers, whatever the caller configured
                    window = min(window, max(0, item.pool - 1))
                staged = self._stage(item.batch)
                pending.append((staged, item.on_done))
                # bound the in-flight transfer window: sources rotating K
                # host buffers are safe for K > window + 1
                while len(pending) > window:
                    self._drain_one(pending)
                self._enqueue(staged)
        except _Stop:
            pass
        except BaseException as e:  # surfaced to the consumer
            with self._lock:
                self._error = e
        finally:
            try:
                while pending:
                    self._drain_one(pending)
            except BaseException as e:
                with self._lock:
                    if self._error is None:
                        self._error = e
            try:
                self._enqueue(None)  # end-of-stream sentinel
            except _Stop:
                pass

    # -- consumer side -----------------------------------------------------

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        with self._lock:
            if self._exhausted:
                raise StopIteration
        _INFLIGHT.set(self._queue.qsize())
        t0 = time.perf_counter()
        item = self._queue.get()
        wait = time.perf_counter() - t0
        if item is None:
            with self._lock:
                self._exhausted = True
            self.close()
            with self._lock:
                err = self._error
            if err is not None:
                raise err
            raise StopIteration
        with self._lock:
            self.last_stall_s = wait
            self.stall_seconds_total += wait
            self.batches_out += 1
        _STALL.set(wait)
        _STALL_TOTAL.inc(wait)
        _BATCHES_OUT.inc()
        if self._batch_nbytes is None:
            self._batch_nbytes = sum(
                int(getattr(x, "nbytes", 0))
                for x in self._jax.tree.leaves(item)
            )
        _STAGED_BYTES.set(self._batch_nbytes * (self._queue.qsize() + 1))
        return item

    def close(self) -> None:
        """Stop the background thread and close the source. Idempotent
        — and safe against the consumer and a GC-driven ``__del__``
        racing into it from two threads (the check-and-set is atomic)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            # a next() after close() must see StopIteration, not block
            # on a queue no producer will ever feed again
            self._exhausted = True
        self._stop.set()
        # unblock a producer stuck in queue.put by draining
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        # ... and a CONSUMER blocked in __next__'s queue.get(): the
        # drain above may have swallowed the producer's end-of-stream
        # sentinel (and the producer, once stopped, never re-posts it),
        # so re-post one best-effort — a waiter wakes to StopIteration
        # instead of blocking forever on a dead queue
        try:
            self._queue.put_nowait(None)
        except queue.Full:
            pass
        self._thread.join(timeout=30)
        if self._thread.is_alive():
            # wedged producer (hung transfer): closing the source now
            # would raise "generator already executing" over whatever
            # error the caller is propagating — leave it to the thread
            return
        close = getattr(self._source, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass  # teardown must never mask the caller's exception

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def prefetch_to_device(
    source: Iterable[Any],
    depth: int = 2,
    *,
    placement: Any = None,
    place: bool = True,
) -> Iterable[Any]:
    """Wrap ``source`` in a :class:`DevicePrefetcher`; ``depth <= 0``
    returns the source unchanged (overlap off — the A/B lever the
    determinism tests and ``train.py --prefetch-depth 0`` use)."""
    if depth <= 0:
        return source
    return DevicePrefetcher(source, depth, placement=placement, place=place)
