"""File-based dataset ingestion: real MNIST / CIFAR-10 / token corpora.

Reference parity: BASELINE.json configs 1-5 name MNIST, CIFAR-10 and
MLM/LM pretraining corpora; the north star's parity condition is
"matching top-1 accuracy", which needs real data. This environment has no
network, so these readers consume files a user drops into ``--data-dir``
(nothing is downloaded); every config falls back to the procedural
datasets in :mod:`consensusml_tpu.data.synthetic` when the files are
absent. Formats are the standard on-disk layouts:

- **MNIST**: idx ubyte files (``train-images-idx3-ubyte`` /
  ``train-labels-idx1-ubyte`` + ``t10k-*`` for the held-out split),
  optionally gzipped. Pixels normalized to [0, 1).
- **CIFAR-10**: the binary batches (``data_batch_1..5.bin`` +
  ``test_batch.bin``, 3073-byte records, CHW uint8), either directly in
  ``data_dir`` or under ``cifar-10-batches-bin/``. Converted to NHWC f32.
- **Token corpora**: a flat binary of token ids (``tokens.bin``, uint16
  little-endian by default — the common memmapped-pretraining layout —
  or uint32), with an optional ``tokens.val.bin`` held-out file. Sampling
  draws random ``seq_len`` windows from the memmap; workers draw from
  disjoint contiguous regions so replicas drift exactly as with the
  procedural data.

The classification readers duck-type :class:`SyntheticClassification`
(``n`` / ``image_shape`` / ``worker_shard`` / ``holdout`` /
``eval_batch``), and the token reader duck-types :class:`SyntheticLM`
(``sample`` / ``vocab_size`` / ``seq_len`` / ``mask_token``), so the
existing ``round_batches`` / ``lm_round_batches`` iterators — and the
trainer above them — work unchanged on real files.
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import struct
from typing import Iterator

import jax.numpy as jnp
import numpy as np

__all__ = [
    "FileClassification",
    "TokenFileDataset",
    "read_idx",
    "load_mnist",
    "load_cifar10",
    "load_tokens",
    "find_classification",
    "find_tokens",
]


# ---------------------------------------------------------------------------
# MNIST idx format
# ---------------------------------------------------------------------------


def read_idx(path: str) -> np.ndarray:
    """Read one idx-format array (the MNIST container format).

    Handles ``.gz`` transparently. Layout: 4-byte magic (2 zero bytes,
    dtype code, ndim), then ndim big-endian uint32 dims, then row-major
    data.
    """
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        raw = f.read()
    zero, dtype_code, ndim = raw[0] << 8 | raw[1], raw[2], raw[3]
    if zero != 0:
        raise ValueError(f"{path}: bad idx magic {raw[:4]!r}")
    dtypes = {
        0x08: np.uint8, 0x09: np.int8, 0x0B: np.dtype(">i2"),
        0x0C: np.dtype(">i4"), 0x0D: np.dtype(">f4"), 0x0E: np.dtype(">f8"),
    }
    if dtype_code not in dtypes:
        raise ValueError(f"{path}: unknown idx dtype code {dtype_code:#x}")
    dims = struct.unpack(f">{ndim}I", raw[4 : 4 + 4 * ndim])
    data = np.frombuffer(raw, dtypes[dtype_code], offset=4 + 4 * ndim)
    return data.reshape(dims)


def _first_existing(data_dir: str, names: list[str]) -> str | None:
    for name in names:
        for cand in (name, name + ".gz"):
            p = os.path.join(data_dir, cand)
            if os.path.exists(p):
                return p
    return None


@dataclasses.dataclass
class FileClassification:
    """In-memory labeled image set with the SyntheticClassification API."""

    images: np.ndarray  # (N, H, W, C) float32
    labels: np.ndarray  # (N,) int32
    holdout_images: np.ndarray | None = None
    holdout_labels: np.ndarray | None = None
    source: str = "file"

    @property
    def n(self) -> int:
        return len(self.images)

    @property
    def image_shape(self) -> tuple[int, ...]:
        return tuple(self.images.shape[1:])

    @property
    def classes(self) -> int:
        return int(self.labels.max()) + 1

    def worker_shard(self, rank: int, world_size: int) -> tuple[np.ndarray, np.ndarray]:
        per = self.n // world_size
        lo = rank * per
        return self.images[lo : lo + per], self.labels[lo : lo + per]

    def __post_init__(self):
        # no test files on disk: carve the last 10% off the TRAIN set now,
        # so worker_shard (which partitions self.images) can never hand a
        # training worker data that later scores as "held-out"
        if self.holdout_images is None:
            cut = max(1, len(self.images) // 10)
            self.holdout_images = self.images[-cut:]
            self.holdout_labels = self.labels[-cut:]
            self.images = self.images[:-cut]
            self.labels = self.labels[:-cut]
            self.source += ":tail-carved"

    def holdout(self) -> "FileClassification":
        """The dataset's test split (real held-out files when present, else
        the tail carved off train at construction — never overlapping)."""
        return FileClassification(
            images=np.asarray(self.holdout_images),
            labels=np.asarray(self.holdout_labels),
            holdout_images=np.asarray(self.holdout_images),
            holdout_labels=np.asarray(self.holdout_labels),
            source=self.source + ":holdout",
        )

    def eval_batch(self, size: int = 1024) -> dict[str, jnp.ndarray]:
        return {
            "image": jnp.asarray(self.images[:size]),
            "label": jnp.asarray(self.labels[:size]),
        }


def load_mnist(data_dir: str) -> FileClassification | None:
    """MNIST from idx files in ``data_dir`` (or ``data_dir/mnist``)."""
    for root in (data_dir, os.path.join(data_dir, "mnist")):
        if not os.path.isdir(root):
            continue
        img_p = _first_existing(root, ["train-images-idx3-ubyte", "train-images.idx3-ubyte"])
        lab_p = _first_existing(root, ["train-labels-idx1-ubyte", "train-labels.idx1-ubyte"])
        if img_p is None or lab_p is None:
            continue
        images = read_idx(img_p).astype(np.float32) / 255.0
        labels = read_idx(lab_p).astype(np.int32)
        images = images.reshape(*images.shape[:3], 1)  # (N, 28, 28, 1)
        hi = _first_existing(root, ["t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"])
        hl = _first_existing(root, ["t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"])
        holdout_images = holdout_labels = None
        if hi is not None and hl is not None:
            holdout_images = read_idx(hi).astype(np.float32) / 255.0
            holdout_images = holdout_images.reshape(*holdout_images.shape[:3], 1)
            holdout_labels = read_idx(hl).astype(np.int32)
        return FileClassification(
            images=images,
            labels=labels,
            holdout_images=holdout_images,
            holdout_labels=holdout_labels,
            source=f"mnist:{root}",
        )
    return None


def load_cifar10(data_dir: str) -> FileClassification | None:
    """CIFAR-10 from the binary batch files."""
    for root in (data_dir, os.path.join(data_dir, "cifar-10-batches-bin")):
        if not os.path.isdir(root):
            continue
        train_paths = [
            os.path.join(root, f"data_batch_{i}.bin") for i in range(1, 6)
        ]
        train_paths = [p for p in train_paths if os.path.exists(p)]
        if not train_paths:
            continue
        imgs, labs = zip(*(_read_cifar_bin(p) for p in train_paths))
        images, labels = np.concatenate(imgs), np.concatenate(labs)
        holdout_images = holdout_labels = None
        test_p = os.path.join(root, "test_batch.bin")
        if os.path.exists(test_p):
            holdout_images, holdout_labels = _read_cifar_bin(test_p)
        return FileClassification(
            images=images,
            labels=labels,
            holdout_images=holdout_images,
            holdout_labels=holdout_labels,
            source=f"cifar10:{root}",
        )
    return None


def _read_cifar_bin(path: str) -> tuple[np.ndarray, np.ndarray]:
    rec = 1 + 3 * 32 * 32
    raw = np.fromfile(path, np.uint8)
    if raw.size % rec:
        raise ValueError(f"{path}: size {raw.size} not a multiple of {rec}")
    raw = raw.reshape(-1, rec)
    labels = raw[:, 0].astype(np.int32)
    # records are CHW; TPU wants NHWC
    images = (
        raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).astype(np.float32)
        / 255.0
    )
    return images, labels


def find_classification(data_dir: str) -> FileClassification | None:
    """Auto-detect MNIST or CIFAR-10 under ``data_dir``."""
    return load_mnist(data_dir) or load_cifar10(data_dir)


# ---------------------------------------------------------------------------
# memmapped token corpora
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TokenFileDataset:
    """Random ``seq_len`` windows over a memmapped flat token file.

    Duck-types :class:`SyntheticLM`: ``sample(rng, shape)`` returns int32
    ids of shape ``(*shape, seq_len)``. The highest id must be
    ``< vocab_size - 1``: the last vocab slot stays reserved as [MASK]
    (same convention as the procedural LM data).
    """

    tokens: np.ndarray  # 1-D memmap (or array) of token ids
    seq_len: int
    vocab_size: int
    val_tokens: np.ndarray | None = None
    source: str = "file"

    def __post_init__(self):
        if len(self.tokens) < self.seq_len + 1:
            raise ValueError(
                f"token file has {len(self.tokens)} tokens < seq_len+1="
                f"{self.seq_len + 1}"
            )
        # no val file on disk: carve the last 5% off the TRAIN stream now,
        # so training windows (drawn from self.tokens via worker_region)
        # can never overlap the held-out region
        if self.val_tokens is None:
            cut = max(self.seq_len + 1, len(self.tokens) // 20)
            if len(self.tokens) - cut >= self.seq_len + 1:
                self.val_tokens = self.tokens[-cut:]
                self.tokens = self.tokens[:-cut]
                self.source += ":tail-carved"
            else:  # file too small to carve — eval on train, loudly
                self.val_tokens = self.tokens
                self.source += ":eval-on-train"

    @property
    def mask_token(self) -> int:
        return self.vocab_size - 1

    def sample(self, rng: np.random.Generator, shape: tuple[int, ...]) -> np.ndarray:
        return _sample_windows(self.tokens, rng, shape, self.seq_len)

    def holdout(self) -> "TokenFileDataset":
        """Held-out windows: the val file when present, else the tail
        carved off the train stream at construction — never overlapping."""
        return TokenFileDataset(
            tokens=self.val_tokens,
            seq_len=self.seq_len,
            vocab_size=self.vocab_size,
            val_tokens=self.val_tokens,
            source=self.source + ":holdout",
        )

    def worker_region(self, rank: int, world_size: int) -> tuple[int, int]:
        """Contiguous [lo, hi) token region for one worker's windows."""
        per = len(self.tokens) // world_size
        if per < self.seq_len + 1:
            raise ValueError(
                f"token stream too small for this world: {len(self.tokens)}"
                f" train tokens / {world_size} workers = {per} per worker, "
                f"need at least seq_len+1={self.seq_len + 1} each"
            )
        lo = rank * per
        return lo, lo + per


def _sample_windows(
    tokens: np.ndarray, rng: np.random.Generator, shape: tuple[int, ...], seq_len: int
) -> np.ndarray:
    n = int(np.prod(shape))
    starts = rng.integers(0, len(tokens) - seq_len, size=n)
    out = np.empty((n, seq_len), np.int32)
    for i, s in enumerate(starts):
        out[i] = tokens[s : s + seq_len]
    return out.reshape(*shape, seq_len)


def _sniff_token_dtype(path: str, vocab_size: int):
    """Distinguish uint16 from uint32 token files.

    A uint32 file read as uint16 becomes alternating ``(id, 0)`` pairs
    (little-endian, ids < 2^16) — every id still passes the vocab check,
    so misreading is SILENT. Heuristic: probe the first 128 KiB; if the
    file is 4-byte aligned and the odd uint16 positions are ~all zero
    while even positions aren't, it is uint32. A vocab over 2^16 forces
    uint32 outright.
    """
    if vocab_size > 1 << 16:
        return np.uint32
    size = os.path.getsize(path)
    probe = np.fromfile(path, np.uint16, count=min(size // 2, 65536))
    if size % 4 == 0 and probe.size >= 8:
        odd, even = probe[1::2], probe[0::2]
        if np.count_nonzero(odd) * 100 <= odd.size and np.count_nonzero(even):
            return np.uint32
    return np.uint16


def load_tokens(
    data_dir: str,
    seq_len: int,
    vocab_size: int,
    *,
    names: tuple[str, ...] = ("tokens.bin", "train.bin"),
    dtype="auto",
) -> TokenFileDataset | None:
    """Memmap ``tokens.bin`` (+ optional ``tokens.val.bin`` / ``val.bin``).

    ``dtype="auto"`` sniffs uint16 vs uint32 (see
    :func:`_sniff_token_dtype`); pass an explicit dtype to override.
    """
    if not os.path.isdir(data_dir):
        return None
    for name in names:
        p = os.path.join(data_dir, name)
        if not os.path.exists(p):
            continue
        dt = _sniff_token_dtype(p, vocab_size) if dtype == "auto" else np.dtype(dtype)
        toks = np.memmap(p, dtype=dt, mode="r")
        stem = name.rsplit(".bin", 1)[0]
        val = None
        for vname in (f"{stem}.val.bin", "val.bin"):
            vp = os.path.join(data_dir, vname)
            if os.path.exists(vp):
                val = np.memmap(vp, dtype=dt, mode="r")
                break
        return TokenFileDataset(
            tokens=toks,
            seq_len=seq_len,
            vocab_size=vocab_size,
            val_tokens=val,
            source=f"tokens:{p}[{np.dtype(dt).name}]",
        )
    return None


find_tokens = load_tokens


# ---------------------------------------------------------------------------
# round-batch iterator for token files (classification reuses round_batches)
# ---------------------------------------------------------------------------


def token_round_batches(
    dataset: TokenFileDataset,
    world_size: int,
    h: int,
    batch: int,
    rounds: int,
    seed: int = 0,
    mlm_rate: float = 0.0,
    mask_token: int | None = None,
    start: int = 0,
) -> Iterator[dict]:
    """Stacked ``(W, H, B, S)`` batches of file-token windows.

    Worker ``r`` draws windows only from its contiguous token region, so
    workers see disjoint data (replica drift, as with every other loader).
    Keyed by (seed, absolute round, rank) for exact resume.
    """
    from consensusml_tpu.data.synthetic import mlm_corrupt

    regions = [dataset.worker_region(r, world_size) for r in range(world_size)]
    for r in range(start, start + rounds):
        per_worker = []
        for rank, (lo, hi) in enumerate(regions):
            rng = np.random.default_rng((seed, r, rank))
            per_worker.append(
                _sample_windows(
                    dataset.tokens[lo:hi], rng, (h, batch), dataset.seq_len
                )
            )
        ids = np.stack(per_worker)
        if mlm_rate <= 0:
            yield {"input_ids": jnp.asarray(ids)}
        else:
            yield mlm_corrupt(ids, dataset, seed, r, mlm_rate, mask_token)
