"""Round-batch iterators backed by the native C++ prefetch pipeline.

Reference parity: the reference's native data-loader feeding its training
loop (SURVEY.md L0/L5; mount empty). Same stacked ``(W, H, B, ...)`` batch
contract as :mod:`consensusml_tpu.data.synthetic`, but batches are
synthesized by C++ producer threads that run AHEAD of the training loop —
round r+1..r+depth-1 are being filled while the TPU executes round r, so
host data work overlaps device compute instead of serializing with it.

Semantics difference from the Python path (documented, intentional): the
native stream is an infinite procedural stream (every sample fresh from
the class-prototype/Markov generative process), whereas the Python path
draws from a finite per-worker shard. Workers still see disjoint samples
(disjoint global sample ids), so replicas drift and consensus has work to
do. The Python path remains the reference semantics used by convergence
tests; this path is the throughput path.
"""

from __future__ import annotations

import numpy as np

from consensusml_tpu.data.synthetic import (
    SyntheticClassification,
    SyntheticLM,
    mlm_corrupt,
)

__all__ = [
    "native_round_batches",
    "native_lm_round_batches",
    "native_file_round_batches",
    "native_file_token_batches",
]


def _ring_yield(loader, rounds, world_size, h, batch, image_shape):
    """Shared consume loop. The loader's wire mode decides the yielded
    image dtype (u8 wire → uint8 arrays; the training step dequants ON
    DEVICE with ``u8 / loader.qscale - loader.qoff``).

    Each round gets FRESH host arrays (loader.next() copies out of the
    ring): ``jnp.asarray`` ALIASES numpy memory on the CPU backend and
    may read it asynchronously on TPU, so a reused host buffer would
    silently rewrite batches the consumer still holds. Callers that can
    prove their batch lifetimes may manage rotation themselves via
    ``loader.next(out=...)``."""
    import jax.numpy as jnp

    for _ in range(rounds):
        data, ints = loader.next()
        yield {
            "image": jnp.asarray(
                data.reshape(world_size, h, batch, *image_shape)
            ),
            "label": jnp.asarray(ints.reshape(world_size, h, batch)),
        }


def native_round_batches(
    dataset: SyntheticClassification,
    world_size: int,
    h: int,
    batch: int,
    rounds: int,
    seed: int = 0,
    depth: int = 4,
    nthreads: int = 2,
    start: int = 0,
    wire: str = "f32",
    qscale: float = 32.0,
    qoff: float = 4.0,
):
    """Yield ``rounds`` stacked ``(W, H, B, *image_shape)`` batches.

    Deterministic in ``seed`` (independent of depth/nthreads/timing).
    ``start`` fast-forwards the stream by consuming that many slots — the
    slot sequence is the round number, so resume keeps the exact stream.
    ``wire="u8"`` ships quantized bytes (1/4 the host->device traffic;
    producer threads run the quantize pass) — consumers dequant on device
    as ``u8 / qscale - qoff``.
    """
    from consensusml_tpu.native import NativeLoader

    sample_floats = int(np.prod(dataset.image_shape))
    per_slot = world_size * h * batch
    with NativeLoader(
        kind="classification",
        samples_per_slot=per_slot,
        sample_floats=sample_floats,
        sample_ints=1,
        nclasses_or_vocab=dataset.classes,
        noise=dataset.noise,
        prototypes=dataset.prototypes.reshape(dataset.classes, sample_floats),
        depth=depth,
        nthreads=nthreads,
        seed=seed,
        start_seq=start,
        wire=wire,
        qscale=qscale,
        qoff=qoff,
    ) as loader:
        yield from _ring_yield(
            loader, rounds, world_size, h, batch, dataset.image_shape
        )


def native_lm_round_batches(
    dataset: SyntheticLM,
    world_size: int,
    h: int,
    batch: int,
    rounds: int,
    seed: int = 0,
    mlm_rate: float = 0.0,
    mask_token: int | None = None,
    depth: int = 4,
    nthreads: int = 2,
    start: int = 0,
):
    """Yield stacked ``(W, H, B, S)`` LM batches from the native pipeline.

    ``mlm_rate > 0`` applies BERT-style masking host-side (numpy), keyed by
    (seed, round) for determinism — corruption is cheap relative to chain
    sampling, which is what the C++ threads accelerate.
    """
    import jax.numpy as jnp

    from consensusml_tpu.native import NativeLoader

    per_slot = world_size * h * batch
    with NativeLoader(
        kind="lm",
        samples_per_slot=per_slot,
        sample_floats=0,
        sample_ints=dataset.seq_len,
        nclasses_or_vocab=dataset.vocab_size,
        successors=dataset.successors,
        depth=depth,
        nthreads=nthreads,
        seed=seed,
        start_seq=start,
    ) as loader:
        for r in range(start, start + rounds):
            _, ints = loader.next()
            ids = ints.reshape(world_size, h, batch, dataset.seq_len)
            if mlm_rate <= 0:
                yield {"input_ids": jnp.asarray(ids)}
            else:
                yield mlm_corrupt(ids, dataset, seed, r, mlm_rate, mask_token)


def native_file_round_batches(
    dataset,  # data.files.FileClassification
    world_size: int,
    h: int,
    batch: int,
    rounds: int,
    seed: int = 0,
    depth: int = 4,
    nthreads: int = 2,
    start: int = 0,
    wire: str = "f32",
    qscale: float = 32.0,
    qoff: float = 4.0,
):
    """File-backed classification batches through the C++ prefetch ring.

    Producer threads do the per-sample gather from the in-memory image
    table (worker shards = the same contiguous blocks worker_shard uses),
    so --data-dir training overlaps batch assembly with device compute.
    Deterministic in ``seed``; the sampled indices differ from the Python
    path's numpy draws (documented divergence, as with the procedural
    kinds).
    """
    from consensusml_tpu.native import NativeLoader

    sample_floats = int(np.prod(dataset.image_shape))
    per_slot = world_size * h * batch
    with NativeLoader(
        kind="file_classification",
        samples_per_slot=per_slot,
        sample_floats=sample_floats,
        sample_ints=1,
        world=world_size,
        images=dataset.images.reshape(dataset.n, sample_floats),
        labels=dataset.labels,
        depth=depth,
        nthreads=nthreads,
        seed=seed,
        start_seq=start,
        wire=wire,
        qscale=qscale,
        qoff=qoff,
    ) as loader:
        yield from _ring_yield(
            loader, rounds, world_size, h, batch, dataset.image_shape
        )


def native_file_token_batches(
    dataset,  # data.files.TokenFileDataset
    world_size: int,
    h: int,
    batch: int,
    rounds: int,
    seed: int = 0,
    mlm_rate: float = 0.0,
    mask_token: int | None = None,
    depth: int = 4,
    nthreads: int = 2,
    start: int = 0,
):
    """Token-window batches through the C++ prefetch ring (kind 3): each
    producer thread memcpys seq_len windows from its worker's contiguous
    token region. MLM corruption stays host-side numpy, keyed by
    (seed, round) like every other loader."""
    import jax.numpy as jnp

    from consensusml_tpu.native import NativeLoader

    per_slot = world_size * h * batch
    with NativeLoader(
        kind="file_lm",
        samples_per_slot=per_slot,
        sample_floats=0,
        sample_ints=dataset.seq_len,
        world=world_size,
        # uint16 memmaps pass through uncopied (C++ widens per window)
        tokens=dataset.tokens,
        depth=depth,
        nthreads=nthreads,
        seed=seed,
        start_seq=start,
    ) as loader:
        for r in range(start, start + rounds):
            _, ints = loader.next()
            ids = ints.reshape(world_size, h, batch, dataset.seq_len)
            if mlm_rate <= 0:
                yield {"input_ids": jnp.asarray(ids)}
            else:
                yield mlm_corrupt(ids, dataset, seed, r, mlm_rate, mask_token)
