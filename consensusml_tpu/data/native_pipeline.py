"""Round-batch iterators backed by the native C++ prefetch pipeline.

Reference parity: the reference's native data-loader feeding its training
loop (SURVEY.md L0/L5; mount empty). Same stacked ``(W, H, B, ...)`` batch
contract as :mod:`consensusml_tpu.data.synthetic`, but batches are
synthesized by C++ producer threads that run AHEAD of the training loop —
round r+1..r+depth-1 are being filled while the TPU executes round r, so
host data work overlaps device compute instead of serializing with it.

Semantics difference from the Python path (documented, intentional): the
native stream is an infinite procedural stream (every sample fresh from
the class-prototype/Markov generative process), whereas the Python path
draws from a finite per-worker shard. Workers still see disjoint samples
(disjoint global sample ids), so replicas drift and consensus has work to
do. The Python path remains the reference semantics used by convergence
tests; this path is the throughput path.
"""

from __future__ import annotations

import os

import numpy as np

from consensusml_tpu.data.synthetic import (
    SyntheticClassification,
    SyntheticLM,
    mlm_corrupt,
)

__all__ = [
    "native_round_batches",
    "native_lm_round_batches",
    "native_file_round_batches",
    "native_file_token_batches",
    "native_cls_feed",
    "plan_ring",
]


def plan_ring(
    samples_per_slot: int,
    sample_wire_bytes: int,
    prefetch: int = 2,
    cpu_count: int | None = None,
) -> tuple[int, int]:
    """Size the native ring from the round shape: ``(depth, nthreads)``.

    ``depth``: the device prefetcher holds up to ``prefetch`` staged
    batches plus one in transfer, each pinning a ring slot until its H2D
    copy completes — ``prefetch + 2`` keeps at least one slot free for
    the producers at all times (no fill/consume deadlock, no starvation).

    ``nthreads``: producer work scales with slot bytes (synthesis or
    gather + optional quantize pass over every sample), so grant roughly
    one thread per 8 MB of slot payload, within [2, cpus-2] — a 77 MB
    ImageNet-shaped round gets ~10 threads where the old fixed default
    of 2 left the ring permanently behind the consumer.
    """
    depth = max(2, int(prefetch) + 2)
    cpus = cpu_count if cpu_count is not None else (os.cpu_count() or 4)
    slot_mb = samples_per_slot * max(1, sample_wire_bytes) / 1e6
    nthreads = int(min(max(2, slot_mb // 8 + 1), max(2, cpus - 2)))
    return depth, nthreads


def _ring_yield(loader, rounds, world_size, h, batch, image_shape):
    """Shared consume loop. The loader's wire mode decides the yielded
    image dtype (u8 wire → uint8 arrays; the training step dequants ON
    DEVICE with ``u8 / loader.qscale - loader.qoff``).

    Each round gets FRESH host arrays (loader.next() copies out of the
    ring): ``jnp.asarray`` ALIASES numpy memory on the CPU backend and
    may read it asynchronously on TPU, so a reused host buffer would
    silently rewrite batches the consumer still holds. The zero-copy
    path that avoids this copy safely is :func:`_ring_view_yield` +
    ``DevicePrefetcher`` (slot release deferred to transfer completion)."""
    import jax.numpy as jnp

    for _ in range(rounds):
        data, ints = loader.next()
        yield {
            "image": jnp.asarray(
                data.reshape(world_size, h, batch, *image_shape)
            ),
            "label": jnp.asarray(ints.reshape(world_size, h, batch)),
        }


def _ring_view_yield(loader, rounds, world_size, h, batch, image_shape, depth):
    """Zero-copy consume loop: yields :class:`~consensusml_tpu.data.
    prefetch.FeedItem`\\ s whose leaves are numpy VIEWS of the ring slot
    (the slot is the H2D staging buffer) and whose ``on_done`` releases
    the slot back to the producers. ``pool=depth`` tells the prefetcher
    how many slots exist, so it caps its in-flight transfer window below
    the pool size regardless of what the caller configured.

    MUST be consumed through ``DevicePrefetcher`` (or with manual
    ``on_done`` calls): without releases the ring deadlocks once all
    slots are acquired, and a slot's bytes may be rewritten the moment
    its release fires.

    Loader lifetime: callers must NOT close the loader around this
    generator — slot memory has to stay alive until the last deferred
    release fires (an in-flight ``device_put`` reads the slot
    asynchronously on accelerator backends, so destroy-before-drain is
    a use-after-free). The release closures hold the loader; after the
    prefetcher drains them, refcounting finalizes it (``__del__`` →
    ``close``)."""
    from consensusml_tpu.data.prefetch import FeedItem

    for _ in range(rounds):
        idx, data, ints = loader.acquire_view()
        yield FeedItem(
            {
                "image": data.reshape(world_size, h, batch, *image_shape),
                "label": ints.reshape(world_size, h, batch),
            },
            lambda i=idx: loader.release_slot(i),
            pool=depth,
        )


def native_round_batches(
    dataset: SyntheticClassification,
    world_size: int,
    h: int,
    batch: int,
    rounds: int,
    seed: int = 0,
    depth: int = 4,
    nthreads: int = 2,
    start: int = 0,
    wire: str = "f32",
    qscale: float = 32.0,
    qoff: float = 4.0,
    views: bool = False,
):
    """Yield ``rounds`` stacked ``(W, H, B, *image_shape)`` batches.

    Deterministic in ``seed`` (independent of depth/nthreads/timing).
    ``start`` fast-forwards the stream by consuming that many slots — the
    slot sequence is the round number, so resume keeps the exact stream.
    ``wire="u8"`` ships quantized bytes (1/4 the host->device traffic;
    producer threads run the quantize pass) — consumers dequant on device
    as ``u8 / qscale - qoff``. ``views=True`` switches to the zero-copy
    FeedItem stream (see :func:`_ring_view_yield`; DevicePrefetcher
    consumption required).
    """
    from consensusml_tpu.native import NativeLoader

    sample_floats = int(np.prod(dataset.image_shape))
    per_slot = world_size * h * batch
    loader = NativeLoader(
        kind="classification",
        samples_per_slot=per_slot,
        sample_floats=sample_floats,
        sample_ints=1,
        nclasses_or_vocab=dataset.classes,
        noise=dataset.noise,
        prototypes=dataset.prototypes.reshape(dataset.classes, sample_floats),
        depth=depth,
        nthreads=nthreads,
        seed=seed,
        start_seq=start,
        wire=wire,
        qscale=qscale,
        qoff=qoff,
    )
    if views:
        # no eager close: slot views are read by in-flight async
        # transfers after this generator exhausts — the release closures
        # keep the loader alive until the prefetcher drains them, then
        # refcounting finalizes it (see _ring_view_yield)
        yield from _ring_view_yield(
            loader, rounds, world_size, h, batch, dataset.image_shape, depth
        )
    else:
        with loader:
            yield from _ring_yield(
                loader, rounds, world_size, h, batch, dataset.image_shape
            )


def native_lm_round_batches(
    dataset: SyntheticLM,
    world_size: int,
    h: int,
    batch: int,
    rounds: int,
    seed: int = 0,
    mlm_rate: float = 0.0,
    mask_token: int | None = None,
    depth: int = 4,
    nthreads: int = 2,
    start: int = 0,
):
    """Yield stacked ``(W, H, B, S)`` LM batches from the native pipeline.

    ``mlm_rate > 0`` applies BERT-style masking host-side (numpy), keyed by
    (seed, round) for determinism — corruption is cheap relative to chain
    sampling, which is what the C++ threads accelerate.
    """
    import jax.numpy as jnp

    from consensusml_tpu.native import NativeLoader

    per_slot = world_size * h * batch
    with NativeLoader(
        kind="lm",
        samples_per_slot=per_slot,
        sample_floats=0,
        sample_ints=dataset.seq_len,
        nclasses_or_vocab=dataset.vocab_size,
        successors=dataset.successors,
        depth=depth,
        nthreads=nthreads,
        seed=seed,
        start_seq=start,
    ) as loader:
        for r in range(start, start + rounds):
            _, ints = loader.next()
            ids = ints.reshape(world_size, h, batch, dataset.seq_len)
            if mlm_rate <= 0:
                yield {"input_ids": jnp.asarray(ids)}
            else:
                yield mlm_corrupt(ids, dataset, seed, r, mlm_rate, mask_token)


def native_file_round_batches(
    dataset,  # data.files.FileClassification
    world_size: int,
    h: int,
    batch: int,
    rounds: int,
    seed: int = 0,
    depth: int = 4,
    nthreads: int = 2,
    start: int = 0,
    wire: str = "f32",
    qscale: float = 32.0,
    qoff: float = 4.0,
    views: bool = False,
):
    """File-backed classification batches through the C++ prefetch ring.

    Producer threads do the per-sample gather from the in-memory image
    table (worker shards = the same contiguous blocks worker_shard uses),
    so --data-dir training overlaps batch assembly with device compute.
    Deterministic in ``seed``; the sampled indices differ from the Python
    path's numpy draws (documented divergence, as with the procedural
    kinds). ``views=True``: zero-copy FeedItem stream (DevicePrefetcher
    consumption required).
    """
    from consensusml_tpu.native import NativeLoader

    sample_floats = int(np.prod(dataset.image_shape))
    per_slot = world_size * h * batch
    loader = NativeLoader(
        kind="file_classification",
        samples_per_slot=per_slot,
        sample_floats=sample_floats,
        sample_ints=1,
        world=world_size,
        images=dataset.images.reshape(dataset.n, sample_floats),
        labels=dataset.labels,
        depth=depth,
        nthreads=nthreads,
        seed=seed,
        start_seq=start,
        wire=wire,
        qscale=qscale,
        qoff=qoff,
    )
    if views:
        # lifetime contract as in native_round_batches: the prefetcher's
        # deferred releases finalize the loader, never this generator
        yield from _ring_view_yield(
            loader, rounds, world_size, h, batch, dataset.image_shape, depth
        )
    else:
        with loader:
            yield from _ring_yield(
                loader, rounds, world_size, h, batch, dataset.image_shape
            )


def native_cls_feed(
    dataset,
    world_size: int,
    h: int,
    batch: int,
    rounds: int,
    *,
    seed: int = 0,
    start: int = 0,
    wire: str = "u8",
    qscale: float = 32.0,
    qoff: float = 4.0,
    prefetch: int = 2,
    depth: int | None = None,
    nthreads: int | None = None,
    placement=None,
    place: bool = True,
):
    """The overlapped host→device classification feed, end to end.

    Zero-copy ring views (the slot is the staging buffer) pushed through
    a :class:`~consensusml_tpu.data.prefetch.DevicePrefetcher`: batch
    synthesis/gather+quantize run on the C++ producer threads, the H2D
    transfer for round ``r+1`` overlaps round ``r``'s compute, and slots
    release the moment their bytes are on device. ``depth``/``nthreads``
    default to :func:`plan_ring` sized from the round shape.

    ``prefetch <= 0`` disables overlap and falls back to the plain
    copying iterator — same byte stream (determinism is a function of
    ``(seed, round)`` only), so the two paths are A/B-comparable.

    Works for both classification sources (``SyntheticClassification``
    and ``data.files.FileClassification`` — anything with ``images``/
    ``labels`` tables routes to the file kind, mirroring
    configs._native_cls_batches).
    """
    wire_bytes = 1 if wire == "u8" else 4
    sample_floats = int(np.prod(dataset.image_shape))
    plan_depth, plan_threads = plan_ring(
        world_size * h * batch, sample_floats * wire_bytes, max(prefetch, 1)
    )
    depth = plan_depth if depth is None else depth
    nthreads = plan_threads if nthreads is None else nthreads
    from consensusml_tpu.data.files import FileClassification

    fn = (
        native_file_round_batches
        if isinstance(dataset, FileClassification)
        else native_round_batches
    )
    source = fn(
        dataset, world_size, h, batch, rounds, seed=seed, depth=depth,
        nthreads=nthreads, start=start, wire=wire, qscale=qscale, qoff=qoff,
        views=prefetch > 0,
    )
    # the view stream's FeedItem.pool caps the prefetcher's in-flight
    # window below the ring depth (a shallow explicit depth shrinks the
    # window instead of deadlocking the ring)
    from consensusml_tpu.data.prefetch import prefetch_to_device

    return prefetch_to_device(
        source, prefetch, placement=placement, place=place
    )


def native_file_token_batches(
    dataset,  # data.files.TokenFileDataset
    world_size: int,
    h: int,
    batch: int,
    rounds: int,
    seed: int = 0,
    mlm_rate: float = 0.0,
    mask_token: int | None = None,
    depth: int = 4,
    nthreads: int = 2,
    start: int = 0,
):
    """Token-window batches through the C++ prefetch ring (kind 3): each
    producer thread memcpys seq_len windows from its worker's contiguous
    token region. MLM corruption stays host-side numpy, keyed by
    (seed, round) like every other loader."""
    import jax.numpy as jnp

    from consensusml_tpu.native import NativeLoader

    per_slot = world_size * h * batch
    with NativeLoader(
        kind="file_lm",
        samples_per_slot=per_slot,
        sample_floats=0,
        sample_ints=dataset.seq_len,
        world=world_size,
        # uint16 memmaps pass through uncopied (C++ widens per window)
        tokens=dataset.tokens,
        depth=depth,
        nthreads=nthreads,
        seed=seed,
        start_seq=start,
    ) as loader:
        for r in range(start, start + rounds):
            _, ints = loader.next()
            ids = ints.reshape(world_size, h, batch, dataset.seq_len)
            if mlm_rate <= 0:
                yield {"input_ids": jnp.asarray(ids)}
            else:
                yield mlm_corrupt(ids, dataset, seed, r, mlm_rate, mask_token)
