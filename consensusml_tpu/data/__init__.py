"""Data pipelines: per-worker sharded batch streams.

Reference parity: the per-workload dataloaders (SURVEY.md L5; mount
empty). This environment has no network access, so image/text datasets are
procedurally generated with the same shapes and a learnable structure —
the decentralized-training math (gossip, consensus, local SGD) is dataset-
agnostic. Loaders yield STACKED round batches of shape ``(W, H, B, ...)``:
one microbatch per inner step per worker, each worker sampling from its own
disjoint shard (the reference's data-parallel partitioning).
"""

from consensusml_tpu.data.synthetic import (  # noqa: F401
    SyntheticClassification,
    SyntheticLM,
    lm_round_batches,
    round_batches,
)
from consensusml_tpu.data.native_pipeline import (  # noqa: F401
    native_cls_feed,
    native_file_round_batches,
    native_file_token_batches,
    native_lm_round_batches,
    native_round_batches,
    plan_ring,
)
from consensusml_tpu.data.prefetch import (  # noqa: F401
    DevicePrefetcher,
    FeedItem,
    prefetch_to_device,
)
from consensusml_tpu.data.files import (  # noqa: F401
    FileClassification,
    TokenFileDataset,
    load_cifar10,
    load_mnist,
    load_tokens,
    read_idx,
    token_round_batches,
)
