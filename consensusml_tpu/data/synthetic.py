"""Procedural datasets with MNIST/CIFAR shapes.

Class-prototype + noise classification: class k's images cluster around a
fixed random prototype, so a small model reaches high accuracy quickly —
ideal for convergence smoke tests (the reference's MNIST role, SURVEY.md
§4) while requiring zero network access.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["SyntheticClassification", "round_batches", "SyntheticLM", "lm_round_batches"]


@dataclasses.dataclass
class SyntheticClassification:
    """Deterministic synthetic classification dataset, sharded by worker."""

    # the u8-wire quant affine for this data family: prototypes+noise are
    # ~N(0,1)-scale, so u8 = clip((x + 4) * 32) covers [-4, 4). The ONE
    # source of truth for every u8 consumer of synthetic images (configs'
    # native closures, bench's u8 feeds, the perf sweep's dequant step).
    U8_QSCALE = 32.0
    U8_QOFF = 4.0

    n: int = 8192
    image_shape: tuple[int, ...] = (28, 28, 1)
    classes: int = 10
    noise: float = 0.35
    seed: int = 0
    # None => samples come from the prototype rng stream (training split).
    # An int selects an independent sample stream over the SAME prototypes
    # — a held-out split of the same task (see holdout()).
    sample_seed: int | None = None

    def __post_init__(self):
        rng = np.random.default_rng(self.seed)
        self.prototypes = rng.normal(size=(self.classes, *self.image_shape)).astype(
            np.float32
        )
        if self.sample_seed is not None:
            rng = np.random.default_rng((self.seed, self.sample_seed))
        self.labels = rng.integers(0, self.classes, size=self.n).astype(np.int32)
        self.images = (
            self.prototypes[self.labels]
            + self.noise * rng.normal(size=(self.n, *self.image_shape))
        ).astype(np.float32)

    def holdout(self, n: int | None = None) -> "SyntheticClassification":
        """Held-out split: same class prototypes, disjoint sample stream."""
        return dataclasses.replace(
            self, n=n or self.n, sample_seed=(self.sample_seed or 0) + 1
        )

    def worker_shard(self, rank: int, world_size: int) -> tuple[np.ndarray, np.ndarray]:
        """Disjoint contiguous shard for one worker (reference-style DP
        partitioning)."""
        per = self.n // world_size
        lo = rank * per
        return self.images[lo : lo + per], self.labels[lo : lo + per]

    def eval_batch(self, size: int = 1024) -> dict[str, jnp.ndarray]:
        return {
            "image": jnp.asarray(self.images[:size]),
            "label": jnp.asarray(self.labels[:size]),
        }


@dataclasses.dataclass
class SyntheticLM:
    """Procedural token streams with learnable structure.

    Sequences follow a fixed random Markov chain over the vocab (worker-
    sharded by seeding), so causal/masked LMs can demonstrably reduce loss
    without any downloaded corpus.
    """

    vocab_size: int = 256
    seq_len: int = 128
    seed: int = 0

    def __post_init__(self):
        rng = np.random.default_rng(self.seed)
        # sparse-ish transition table: each token has 4 likely successors.
        # The last vocab id is RESERVED (never emitted by the chain) so it
        # can serve as an unambiguous [MASK] token for MLM corruption.
        succ = rng.integers(0, self.vocab_size - 1, size=(self.vocab_size, 4))
        self.successors = succ.astype(np.int32)

    @property
    def mask_token(self) -> int:
        """Reserved id never produced by the chain."""
        return self.vocab_size - 1

    def sample(self, rng: np.random.Generator, shape: tuple[int, ...]) -> np.ndarray:
        """Sample token id sequences of shape (*shape, seq_len)."""
        n = int(np.prod(shape))
        out = np.empty((n, self.seq_len), np.int32)
        state = rng.integers(0, self.vocab_size - 1, size=n)
        for t in range(self.seq_len):
            out[:, t] = state
            choice = rng.integers(0, 4, size=n)
            state = self.successors[state, choice]
        return out.reshape(*shape, self.seq_len)


def mlm_corrupt(
    ids: np.ndarray,
    dataset: SyntheticLM,
    seed: int,
    r: int,
    mlm_rate: float,
    mask_token: int | None = None,
) -> dict:
    """BERT-style corruption of a round's token block, keyed (seed, round).

    Shared by the Python and native loader paths so the two streams stay
    bit-identical for the same (seed, round)."""
    rng = np.random.default_rng((seed, r, 10**6))
    mask = rng.random(ids.shape) < mlm_rate
    mtok = dataset.mask_token if mask_token is None else mask_token
    corrupted = np.where(mask, mtok, ids)
    return {
        "input_ids": jnp.asarray(corrupted, jnp.int32),
        "labels": jnp.asarray(ids, jnp.int32),
        "mlm_mask": jnp.asarray(mask, jnp.float32),
    }


def lm_round_batches(
    dataset: SyntheticLM,
    world_size: int,
    h: int,
    batch: int,
    rounds: int,
    seed: int = 0,
    mlm_rate: float = 0.0,
    mask_token: int | None = None,
    start: int = 0,
):
    """Stacked (W, H, B, S) LM round batches; ``mlm_rate > 0`` yields
    BERT-style corrupted inputs + labels + mlm_mask.

    Batches are keyed by (seed, absolute round, rank), so resuming with
    ``start=N`` continues the EXACT stream a fresh run would have produced
    at round N (checkpoint/resume correctness)."""
    for r in range(start, start + rounds):
        per_worker = []
        for rank in range(world_size):
            rng = np.random.default_rng((seed, r, rank))
            per_worker.append(dataset.sample(rng, (h, batch)))
        ids = np.stack(per_worker)  # (W, H, B, S)
        if mlm_rate <= 0:
            yield {"input_ids": jnp.asarray(ids)}
        else:
            yield mlm_corrupt(ids, dataset, seed, r, mlm_rate, mask_token)


def round_batches(
    dataset: SyntheticClassification,
    world_size: int,
    h: int,
    batch: int,
    rounds: int,
    seed: int = 0,
    start: int = 0,
) -> Iterator[dict[str, jnp.ndarray]]:
    """Yield ``rounds`` stacked round-batches of shape ``(W, H, B, ...)``.

    Every worker samples uniformly (with replacement) from its OWN shard —
    workers see disjoint data, which is what makes their replicas drift and
    gives the consensus step something to do. Batches are keyed by
    (seed, absolute round), so ``start=N`` resumes the exact stream.
    """
    shards = [dataset.worker_shard(r, world_size) for r in range(world_size)]
    for rnd in range(start, start + rounds):
        rng = np.random.default_rng((seed, rnd))
        imgs = np.empty(
            (world_size, h, batch, *dataset.image_shape), np.float32
        )
        labs = np.empty((world_size, h, batch), np.int32)
        for r, (x, y) in enumerate(shards):
            idx = rng.integers(0, len(x), size=(h, batch))
            imgs[r] = x[idx]
            labs[r] = y[idx]
        yield {"image": jnp.asarray(imgs), "label": jnp.asarray(labs)}
