"""Procedural datasets with MNIST/CIFAR shapes.

Class-prototype + noise classification: class k's images cluster around a
fixed random prototype, so a small model reaches high accuracy quickly —
ideal for convergence smoke tests (the reference's MNIST role, SURVEY.md
§4) while requiring zero network access.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["SyntheticClassification", "round_batches"]


@dataclasses.dataclass
class SyntheticClassification:
    """Deterministic synthetic classification dataset, sharded by worker."""

    n: int = 8192
    image_shape: tuple[int, ...] = (28, 28, 1)
    classes: int = 10
    noise: float = 0.35
    seed: int = 0

    def __post_init__(self):
        rng = np.random.default_rng(self.seed)
        self.prototypes = rng.normal(size=(self.classes, *self.image_shape)).astype(
            np.float32
        )
        self.labels = rng.integers(0, self.classes, size=self.n).astype(np.int32)
        self.images = (
            self.prototypes[self.labels]
            + self.noise * rng.normal(size=(self.n, *self.image_shape))
        ).astype(np.float32)

    def worker_shard(self, rank: int, world_size: int) -> tuple[np.ndarray, np.ndarray]:
        """Disjoint contiguous shard for one worker (reference-style DP
        partitioning)."""
        per = self.n // world_size
        lo = rank * per
        return self.images[lo : lo + per], self.labels[lo : lo + per]

    def eval_batch(self, size: int = 1024) -> dict[str, jnp.ndarray]:
        return {
            "image": jnp.asarray(self.images[:size]),
            "label": jnp.asarray(self.labels[:size]),
        }


def round_batches(
    dataset: SyntheticClassification,
    world_size: int,
    h: int,
    batch: int,
    rounds: int,
    seed: int = 0,
) -> Iterator[dict[str, jnp.ndarray]]:
    """Yield ``rounds`` stacked round-batches of shape ``(W, H, B, ...)``.

    Every worker samples uniformly (with replacement) from its OWN shard —
    workers see disjoint data, which is what makes their replicas drift and
    gives the consensus step something to do.
    """
    shards = [dataset.worker_shard(r, world_size) for r in range(world_size)]
    rng = np.random.default_rng(seed)
    for _ in range(rounds):
        imgs = np.empty(
            (world_size, h, batch, *dataset.image_shape), np.float32
        )
        labs = np.empty((world_size, h, batch), np.int32)
        for r, (x, y) in enumerate(shards):
            idx = rng.integers(0, len(x), size=(h, batch))
            imgs[r] = x[idx]
            labs[r] = y[idx]
        yield {"image": jnp.asarray(imgs), "label": jnp.asarray(labs)}
