"""Regex-rule model sharding for hybrid gossip-DP x model-parallel runs.

The reference's parallelism story is pure data-parallel gossip (SURVEY.md
§2: one worker = one GPU; its largest model, Llama-2-7B, fits one worker
via LoRA). On TPU we generalize the worker: a gossip worker is a SUBMESH —
the device mesh is ``(*topology.mesh_shape, *model_axes)``, gossip
collectives are manual (``shard_map`` over the worker axes), and the model
axes stay in XLA's *auto* sharding mode, so tensor-parallel collectives
inside a worker are inserted by the compiler from these sharding
annotations (the scaling-book recipe: annotate, don't hand-schedule).

Rules are ``(regex, spec)`` pairs matched against the ``/``-joined
parameter path; ``spec`` names mesh axes for the TRAILING dims of the
leaf. The first matching rule wins; unmatched leaves are replicated over
the model axes. The same rules shard params, optimizer state, and gossip
state, because optax/CHOCO trees embed the param tree (path suffixes
still match).
"""

from __future__ import annotations

import re
from typing import Any, Sequence

import jax
from jax.sharding import NamedSharding, PartitionSpec

__all__ = [
    "ShardingRules",
    "spec_for_path",
    "llama_tp_rules",
    "gpt2_tp_rules",
    "moe_ep_rules",
]

# (path regex, partition spec) pairs — axis names must exist on the
# WorkerMesh's model axes. A spec applies to the TRAILING dims; an
# optional third element "lead" applies it to the LEADING (post-stack)
# dims instead — the natural form for layer-stacked pipeline params,
# whose stage dim is dim 0 at EVERY leaf rank.
ShardingRules = Sequence[tuple]


def spec_for_path(
    path: str, ndim: int, rules: ShardingRules | None
) -> tuple[str | None, ...]:
    """Per-dim spec for one leaf: first matching rule, else replicated.

    A rule's spec applies to the LAST ``len(spec)`` dims ("lead" rules:
    the FIRST); a spec longer than the leaf's rank is an error (catches
    rules written for the wrong tensor).
    """
    if rules:
        for rule in rules:
            pattern, spec = rule[0], rule[1]
            if len(rule) > 2 and rule[2] != "lead":
                # a typo'd marker silently becoming a trailing rule would
                # shard the wrong dim — e.g. a bias's feature dim over pp
                raise ValueError(
                    f"rule {pattern!r}: third element must be 'lead', "
                    f"got {rule[2]!r}"
                )
            lead = len(rule) > 2
            if re.search(pattern, path):
                if len(spec) > ndim:
                    raise ValueError(
                        f"sharding rule {pattern!r} wants {len(spec)} dims but "
                        f"leaf {path!r} has only {ndim}"
                    )
                pad = (None,) * (ndim - len(spec))
                return tuple(spec) + pad if lead else pad + tuple(spec)
    return (None,) * ndim


def tree_paths(tree: Any) -> Any:
    """Same-structure tree of '/'-joined string paths."""
    return jax.tree.map_with_path(
        lambda p, _: jax.tree_util.keystr(p, simple=True, separator="/"), tree
    )


def stacked_shardings(
    tree: Any, mesh, flat_axes: tuple[str, ...], rules: ShardingRules | None
) -> Any:
    """NamedSharding tree for FLAT-stacked leaves ``(W, ...)``.

    The leading axis is split over all ``flat_axes`` (the worker axes,
    row-major); trailing dims follow ``rules`` over the model axes.
    """

    def one(path, leaf):
        ndim = getattr(leaf, "ndim", len(getattr(leaf, "shape", ())))
        pathstr = jax.tree_util.keystr(path, simple=True, separator="/")
        spec = spec_for_path(pathstr, ndim - 1, rules)
        return NamedSharding(mesh, PartitionSpec(flat_axes, *spec))

    return jax.tree.map_with_path(one, tree)


# ---------------------------------------------------------------------------
# stock rule sets (Megatron-style 1-D tensor parallelism)
# ---------------------------------------------------------------------------


def pipeline_pp_rules(
    axis: str = "pp", pattern: str = r"stages/"
) -> ShardingRules:
    """Stage-stacked pipeline params: every leaf under ``pattern`` carries
    its layer/stage dim FIRST (after the worker stack axis), whatever its
    rank — one "lead" rule covers kernels and biases alike. Used with
    ``WorkerMesh(model_axes=(("pp", P),), manual_model_axes=("pp",))`` and
    a loss_fn built on :func:`~consensusml_tpu.parallel.pipeline_apply`."""
    return [(pattern, (axis,), "lead")]


def llama_tp_rules(axis: str = "tp") -> ShardingRules:
    """Column-parallel q/k/v/gate/up, row-parallel o/down — one psum per
    attention block and one per MLP block, inserted by XLA from these
    annotations. LoRA adapters follow their base projection's split so the
    adapter matmul needs no extra collective."""
    return [
        (r"(q|k|v)_proj/(base/kernel|lora_b)", (None, axis)),
        (r"o_proj/base/kernel", (axis, None)),
        (r"o_proj/lora_a", (axis, None)),
        (r"(gate|up)_proj/kernel", (None, axis)),
        (r"down_proj/kernel", (axis, None)),
        (r"lm_head/kernel", (None, axis)),
        (r"tok_emb/embedding", (None, axis)),
    ]


def moe_ep_rules(axis: str = "ep") -> ShardingRules:
    """Expert parallelism for :class:`~consensusml_tpu.models.moe.MoELM`:
    the stacked expert weights ``wi (E, d, f)`` / ``wo (E, f, d)`` split
    their leading expert dim over ``axis``; the router and the dense
    (attention/shared-MLP) weights stay replicated. XLA's auto mode derives
    the dispatch/combine all-to-alls from these annotations."""
    return [
        (r"moe/w[io]", (axis, None, None)),
    ]


def gpt2_tp_rules(axis: str = "tp") -> ShardingRules:
    """Head-parallel attention + column/row-split MLP for the GPT-2 layout
    (qkv kernel ``(hidden, heads, 3*head_dim)``, out kernel
    ``(heads, head_dim, hidden)`` — shard the heads dim)."""
    return [
        (r"qkv/kernel", (None, axis, None)),
        (r"qkv/bias", (axis, None)),
        (r"/out/kernel", (axis, None, None)),  # '/' so mlp_out doesn't match
        (r"mlp_in/kernel", (None, axis)),
        (r"mlp_in/bias", (axis,)),
        (r"mlp_out/kernel", (axis, None)),
        (r"(wte|wpe)/embedding", (None, axis)),
    ]
