"""Parallelism beyond gossip-DP: sequence/context parallelism.

The reference's parallelism story is decentralized data-parallel gossip
(SURVEY.md §2: no TP/PP/SP evidence in BASELINE.json). This package is
where the TPU build goes further: long-context training via ring
attention over a sequence-parallel mesh axis (ppermute'd KV blocks with
online-softmax accumulation), composable with the gossip worker axis on
the same device mesh.
"""

from consensusml_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ring_flash_attention,
)
from consensusml_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_apply,
    pipeline_last_stage_mean,
)
from consensusml_tpu.parallel.ulysses import (  # noqa: F401
    ulysses_attention,
)
from consensusml_tpu.parallel.sharding import (  # noqa: F401
    ShardingRules,
    gpt2_tp_rules,
    llama_tp_rules,
    moe_ep_rules,
    pipeline_pp_rules,
    spec_for_path,
)
