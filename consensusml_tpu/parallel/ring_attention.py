"""Ring attention: exact long-context attention over a sequence-sharded mesh axis.

Liu et al. 2023 ("Ring Attention with Blockwise Transformers") pattern,
TPU-native: the sequence is sharded across devices along a named mesh
axis; each device holds a Q/K/V block. K/V blocks rotate around the ring
with ``jax.lax.ppermute`` (ICI neighbor traffic — the same primitive as
the gossip step) while every device accumulates its Q-block's attention
with a numerically-stable online softmax (flash-attention style running
max/sum in f32). After P steps each Q block has attended to the FULL
sequence with only (1/P)-sized KV resident per device — sequence length
scales linearly with the ring size.

Call inside ``shard_map`` with the sequence axis sharded over
``axis_name``; shapes are per-device blocks.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = ["ring_attention", "ring_flash_attention"]

_NEG_INF = -1e30


def _pvary(x: jax.Array, axis_name: str) -> jax.Array:
    """Mark ``x`` as device-varying along ``axis_name`` (VMA annotation)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_name, to="varying")
    return jax.lax.pvary(x, axis_name)


def ring_attention(
    q: jax.Array,  # (B, S_blk, H, D) — this device's query block
    k: jax.Array,  # (B, S_blk, H, D)
    v: jax.Array,  # (B, S_blk, H, D)
    axis_name: str,
    *,
    causal: bool = False,
) -> jax.Array:
    """Exact attention across the full (sharded) sequence.

    Returns this device's output block ``(B, S_blk, H, D)`` in ``q.dtype``.
    Causal masking uses absolute positions derived from the device's ring
    index, so the result matches single-device causal attention on the
    gathered sequence (tested against it).
    """
    p = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, s_blk, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    pos_q = my * s_blk + jnp.arange(s_blk)  # absolute positions of our queries

    perm = [(i, (i + 1) % p) for i in range(p)]

    def step(t, carry):
        out, row_max, row_sum, kv = carry
        k_t, v_t = kv
        # the block we hold after t rotations originated at rank (my - t) % p
        src = (my - t) % p
        pos_k = src * s_blk + jnp.arange(s_blk)
        logits = (
            jnp.einsum("bshd,bthd->bhst", q, k_t, preferred_element_type=jnp.float32)
            * scale
        )
        if causal:
            mask = pos_q[:, None] >= pos_k[None, :]
            logits = jnp.where(mask[None, None], logits, _NEG_INF)
        blk_max = jnp.max(logits, axis=-1)  # (B,H,S)
        new_max = jnp.maximum(row_max, blk_max)
        correction = jnp.exp(row_max - new_max)
        probs = jnp.exp(logits - new_max[..., None])  # (B,H,S,T)
        new_sum = row_sum * correction + jnp.sum(probs, axis=-1)
        blk_out = jnp.einsum(
            "bhst,bthd->bshd", probs, jnp.asarray(v_t, jnp.float32),
            preferred_element_type=jnp.float32,
        )
        new_out = out * correction.transpose(0, 2, 1)[..., None] + blk_out
        # rotate KV to the next device (the final rotation restores the
        # original block; unconditional so no collective sits under a cond)
        kv = jax.tree.map(lambda x: jax.lax.ppermute(x, axis_name, perm), (k_t, v_t))
        return new_out, new_max, new_sum, kv

    # initial accumulators must carry the device-varying axis annotation
    # (VMA) or the fori_loop carry types mismatch after the first ppermute
    out0 = _pvary(jnp.zeros((b, s_blk, h, d), jnp.float32), axis_name)
    max0 = _pvary(jnp.full((b, h, s_blk), _NEG_INF, jnp.float32), axis_name)
    sum0 = _pvary(jnp.zeros((b, h, s_blk), jnp.float32), axis_name)
    out, _, row_sum, _ = jax.lax.fori_loop(0, p, step, (out0, max0, sum0, (k, v)))
    denom = jnp.maximum(row_sum, 1e-30).transpose(0, 2, 1)[..., None]
    return (out / denom).astype(q.dtype)


# ---------------------------------------------------------------------------
# ring flash attention: the same rotation schedule, but each device's
# (q-block x visiting-kv-block) tile runs the Pallas flash kernels
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, axis_name, causal, interpret):
    out, _ = _ring_flash_fwd(q, k, v, axis_name, causal, interpret)
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, interpret):
    from consensusml_tpu.models import flash_attention as fa

    p = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, s_blk, h, d = q.shape
    scale = 1.0 / float(d) ** 0.5
    block = math.lcm(fa._BQ, fa._BK)
    q3 = fa.fold_pad(q, block)
    k3 = fa.fold_pad(k, block)
    v3 = fa.fold_pad(v, block)
    bh, sq_pad, _ = q3.shape
    perm = [(i, (i + 1) % p) for i in range(p)]

    def step(t, carry):
        acc, m, l, kv = carry
        k_t, v_t = kv
        src = (my - t) % p
        o_t, lse_t = fa._fwd(
            q3, k_t, v_t, causal, s_blk, scale, interpret,
            q_offset=my * s_blk, k_offset=src * s_blk, vma=(axis_name,),
        )
        lse_col = lse_t[..., :1]  # (BH, sq_pad, 1) — lanes are replicas
        m_new = jnp.maximum(m, lse_col)
        c_old = jnp.exp(m - m_new)
        c_new = jnp.exp(lse_col - m_new)
        acc = acc * c_old + o_t.astype(jnp.float32) * c_new
        l = l * c_old + c_new
        kv = jax.tree.map(lambda x: jax.lax.ppermute(x, axis_name, perm), (k_t, v_t))
        return acc, m_new, l, kv

    acc0 = _pvary(jnp.zeros((bh, sq_pad, d), jnp.float32), axis_name)
    m0 = _pvary(jnp.full((bh, sq_pad, 1), _NEG_INF, jnp.float32), axis_name)
    l0 = _pvary(jnp.zeros((bh, sq_pad, 1), jnp.float32), axis_name)
    acc, m, l, _ = jax.lax.fori_loop(0, p, step, (acc0, m0, l0, (k3, v3)))
    l_safe = jnp.maximum(l, 1e-30)
    out3 = (acc / l_safe).astype(q.dtype)
    lse_total = jnp.broadcast_to(
        m + jnp.log(l_safe), (bh, sq_pad, fa._LANE)
    )  # lane-replicated, the layout the backward kernels read
    out = jnp.moveaxis(out3[:, :s_blk].reshape(b, h, s_blk, d), 1, 2)
    return out, (q3, k3, v3, out3, lse_total)


def _ring_flash_bwd(axis_name, causal, interpret, res, dout):
    from consensusml_tpu.models import flash_attention as fa

    q3, k3, v3, out3, lse = res
    p = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    bh, sq_pad, d = q3.shape
    b = dout.shape[0]
    h = bh // b
    s_blk = dout.shape[1]
    scale = 1.0 / float(d) ** 0.5

    # fold dout and zero-pad its rows out to the residuals' padded length
    do3 = fa.fold_pad(dout, sq_pad).astype(jnp.float32)
    delta = jnp.sum(do3 * out3.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (bh, sq_pad, fa._LANE))
    perm = [(i, (i + 1) % p) for i in range(p)]

    def step(t, carry):
        dq, blk = carry
        k_t, v_t, dk_t, dv_t = blk
        src = (my - t) % p
        dq = dq + fa._bwd_dq(
            q3, k_t, v_t, do3, lse, delta, causal, s_blk, scale, interpret,
            q_offset=my * s_blk, k_offset=src * s_blk, vma=(axis_name,),
        ).astype(jnp.float32)
        dk_c, dv_c = fa._bwd_dkv(
            q3, k_t, v_t, do3, lse, delta, causal, s_blk, scale, interpret,
            q_offset=my * s_blk, k_offset=src * s_blk, vma=(axis_name,),
        )
        # the kv block's gradient travels WITH the block: after the full
        # rotation both land back on the block's home device
        blk = jax.tree.map(
            lambda x: jax.lax.ppermute(x, axis_name, perm),
            (k_t, v_t, dk_t + dk_c.astype(jnp.float32), dv_t + dv_c.astype(jnp.float32)),
        )
        return dq, blk

    dq0 = _pvary(jnp.zeros((bh, sq_pad, d), jnp.float32), axis_name)
    dk0 = _pvary(jnp.zeros((bh, sq_pad, d), jnp.float32), axis_name)
    dv0 = _pvary(jnp.zeros((bh, sq_pad, d), jnp.float32), axis_name)
    dq, (_, _, dk, dv) = jax.lax.fori_loop(
        0, p, step, (dq0, (k3, v3, dk0, dv0))
    )

    def unfold(g3, like):
        g = g3[:, :s_blk].reshape(b, h, s_blk, d)
        return jnp.moveaxis(g, 1, 2).astype(like.dtype)

    # reconstruct (B, S, H, D) reference dtypes from the folded residuals
    return (
        unfold(dq, q3),
        unfold(dk, k3),
        unfold(dv, v3),
    )


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(
    q: jax.Array,  # (B, S_blk, H, D) — this device's blocks
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """Ring attention whose per-step tiles run the Pallas flash kernels.

    Same contract and rotation schedule as :func:`ring_attention` (call
    inside ``shard_map`` with the sequence sharded over ``axis_name``),
    but each device's (local-q x visiting-kv) computation is the fused
    flash kernel with dynamic position offsets; per-step partial outputs
    merge by logsumexp (the flash-decoding combine), and the backward is
    a second ring pass where each kv block's (dk, dv) travels with it
    back to its home device. ``interpret=True`` runs the kernels in the
    Pallas interpreter (CPU tests).
    """
    if q.shape != k.shape or q.shape != v.shape:
        raise ValueError(
            f"ring_flash_attention needs equal block shapes: q{q.shape} k{k.shape}"
        )
    return _ring_flash(q, k, v, axis_name, causal, interpret)
