"""Ring attention: exact long-context attention over a sequence-sharded mesh axis.

Liu et al. 2023 ("Ring Attention with Blockwise Transformers") pattern,
TPU-native: the sequence is sharded across devices along a named mesh
axis; each device holds a Q/K/V block. K/V blocks rotate around the ring
with ``jax.lax.ppermute`` (ICI neighbor traffic — the same primitive as
the gossip step) while every device accumulates its Q-block's attention
with a numerically-stable online softmax (flash-attention style running
max/sum in f32). After P steps each Q block has attended to the FULL
sequence with only (1/P)-sized KV resident per device — sequence length
scales linearly with the ring size.

Call inside ``shard_map`` with the sequence axis sharded over
``axis_name``; shapes are per-device blocks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["ring_attention"]

_NEG_INF = -1e30


def _pvary(x: jax.Array, axis_name: str) -> jax.Array:
    """Mark ``x`` as device-varying along ``axis_name`` (VMA annotation)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_name, to="varying")
    return jax.lax.pvary(x, axis_name)


def ring_attention(
    q: jax.Array,  # (B, S_blk, H, D) — this device's query block
    k: jax.Array,  # (B, S_blk, H, D)
    v: jax.Array,  # (B, S_blk, H, D)
    axis_name: str,
    *,
    causal: bool = False,
) -> jax.Array:
    """Exact attention across the full (sharded) sequence.

    Returns this device's output block ``(B, S_blk, H, D)`` in ``q.dtype``.
    Causal masking uses absolute positions derived from the device's ring
    index, so the result matches single-device causal attention on the
    gathered sequence (tested against it).
    """
    p = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, s_blk, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    pos_q = my * s_blk + jnp.arange(s_blk)  # absolute positions of our queries

    perm = [(i, (i + 1) % p) for i in range(p)]

    def step(t, carry):
        out, row_max, row_sum, kv = carry
        k_t, v_t = kv
        # the block we hold after t rotations originated at rank (my - t) % p
        src = (my - t) % p
        pos_k = src * s_blk + jnp.arange(s_blk)
        logits = (
            jnp.einsum("bshd,bthd->bhst", q, k_t, preferred_element_type=jnp.float32)
            * scale
        )
        if causal:
            mask = pos_q[:, None] >= pos_k[None, :]
            logits = jnp.where(mask[None, None], logits, _NEG_INF)
        blk_max = jnp.max(logits, axis=-1)  # (B,H,S)
        new_max = jnp.maximum(row_max, blk_max)
        correction = jnp.exp(row_max - new_max)
        probs = jnp.exp(logits - new_max[..., None])  # (B,H,S,T)
        new_sum = row_sum * correction + jnp.sum(probs, axis=-1)
        blk_out = jnp.einsum(
            "bhst,bthd->bshd", probs, jnp.asarray(v_t, jnp.float32),
            preferred_element_type=jnp.float32,
        )
        new_out = out * correction.transpose(0, 2, 1)[..., None] + blk_out
        # rotate KV to the next device (the final rotation restores the
        # original block; unconditional so no collective sits under a cond)
        kv = jax.tree.map(lambda x: jax.lax.ppermute(x, axis_name, perm), (k_t, v_t))
        return new_out, new_max, new_sum, kv

    # initial accumulators must carry the device-varying axis annotation
    # (VMA) or the fori_loop carry types mismatch after the first ppermute
    out0 = _pvary(jnp.zeros((b, s_blk, h, d), jnp.float32), axis_name)
    max0 = _pvary(jnp.full((b, h, s_blk), _NEG_INF, jnp.float32), axis_name)
    sum0 = _pvary(jnp.zeros((b, h, s_blk), jnp.float32), axis_name)
    out, _, row_sum, _ = jax.lax.fori_loop(0, p, step, (out0, max0, sum0, (k, v)))
    denom = jnp.maximum(row_sum, 1e-30).transpose(0, 2, 1)[..., None]
    return (out / denom).astype(q.dtype)
