"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

SURVEY.md §2 records no TP/PP evidence in the reference; like ring/Ulysses
attention this is TPU-build-native capability. The design is the
collective-pipelining pattern (shard_map + ppermute), not a scheduler
process: layers are stacked on a leading axis and sharded over the ``pp``
mesh axis (each device holds ``L/P`` contiguous layers); microbatches
flow through stages with one ``ppermute`` hop per tick inside a
``lax.scan``. The whole schedule — bubbles included — is ONE traced XLA
program, so:

- the backward pass needs no hand-written schedule: ``jax.grad``
  differentiates through scan+ppermute and the transposed ppermute IS the
  reverse-direction pipeline;
- XLA's latency-hiding scheduler overlaps each tick's ppermute with the
  next tick's stage compute (the classic async-send/recv of a CUDA
  pipeline runtime, for free);
- it composes with the gossip worker axis and tensor-parallel axes on the
  same mesh, because it is just another named-axis collective.

Schedule: tick ``t`` has stage ``s`` processing microbatch ``m = t - s``
(valid when ``0 <= m < M``); ``T = M + P - 1`` ticks total. Bubble
fraction ``(P-1)/T`` — use ``M >> P``.

Call :func:`pipeline_apply` inside ``shard_map`` with the layer-stacked
params sharded ``P(axis_name)`` on their leading axis.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

__all__ = ["pipeline_apply", "pipeline_last_stage_mean"]


def _vma(x) -> frozenset:
    return frozenset(getattr(jax.typeof(x), "vma", ()))


def _varying(x: jax.Array, axes) -> jax.Array:
    """Mark ``x`` device-varying along ``axes`` it isn't already (VMA)."""
    missing = tuple(sorted(frozenset(axes) - _vma(x)))
    if not missing:
        return x
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, missing, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, missing)
    return x


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,  # (M, B, ...) — same on every stage (replicated)
    axis_name: str,
) -> jax.Array:
    """Run microbatches through the stage pipeline.

    ``stage_fn(stage_params, x) -> y`` applies THIS device's slice of the
    layer stack (params leaves carry a leading local-layers axis); ``x``
    and ``y`` must have identical shape/dtype (the activation that flows
    between stages).

    Returns ``(M, B, ...)`` outputs that are VALID ON THE LAST STAGE ONLY
    (other stages hold garbage from bubble ticks) — compute the loss
    there and reduce a scalar, e.g. with :func:`pipeline_last_stage_mean`.
    """
    p = jax.lax.axis_size(axis_name)
    s = jax.lax.axis_index(axis_name)
    m = microbatches.shape[0]
    ticks = m + p - 1
    perm = [(i, (i + 1) % p) for i in range(p)]

    def tick(carry, t):
        outs, act_in = carry
        # stage 0 ingests microbatch t; later stages take the ppermuted
        # activation (their microbatch t - s arrives exactly now)
        x_t = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, m - 1), keepdims=False
        )
        x_in = jnp.where(s == 0, x_t.astype(act_in.dtype), act_in)
        y = stage_fn(stage_params, x_in)
        idx = t - s  # my microbatch index this tick (negative/past-end = bubble)
        cidx = jnp.clip(idx, 0, m - 1)
        old = jax.lax.dynamic_index_in_dim(outs, cidx, keepdims=False)
        valid = jnp.logical_and(idx >= 0, idx < m)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(valid, y, old), cidx, axis=0
        )
        act_out = jax.lax.ppermute(y, axis_name, perm)
        return (outs, act_out), None

    # the scan carries must enter with the SAME varying-manual-axes set the
    # tick body produces: {axis_name} for the ppermute, plus whatever the
    # params/microbatches are already varying over (the gossip worker axes
    # when pipelining runs inside the composed gossip-DP shard_map)
    varying_axes = {axis_name} | _vma(microbatches)
    for leaf in jax.tree.leaves(stage_params):
        varying_axes |= _vma(leaf)
    x0 = _varying(microbatches[0], varying_axes)
    y_shape = jax.eval_shape(stage_fn, stage_params, x0)
    if y_shape.shape != x0.shape:
        raise ValueError(
            f"stage_fn must preserve the activation shape (got {y_shape.shape} "
            f"from {x0.shape}) — stages chain into each other"
        )
    outs0 = jnp.zeros((m,) + x0.shape, y_shape.dtype)
    act0 = jnp.zeros(x0.shape, y_shape.dtype)
    # carries must already be device-varying before the first ppermute
    outs0 = _varying(outs0, varying_axes)
    act0 = _varying(act0, varying_axes)
    (outs, _), _ = jax.lax.scan(tick, (outs0, act0), jnp.arange(ticks))
    return outs


def pipeline_last_stage_mean(value: jax.Array, axis_name: str) -> jax.Array:
    """Reduce a per-stage scalar to the LAST stage's value, replicated.

    The pipeline's outputs (and hence any loss computed from them) are
    valid only on stage ``P-1``; this masks the other stages' garbage and
    broadcasts the real value everywhere with one ``psum``.
    """
    p = jax.lax.axis_size(axis_name)
    s = jax.lax.axis_index(axis_name)
    masked = jnp.where(s == p - 1, value, jnp.zeros_like(value))
    return jax.lax.psum(masked, axis_name)
