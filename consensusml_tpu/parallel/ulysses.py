"""Ulysses attention: all-to-all sequence/context parallelism.

The second long-context strategy alongside ring attention (the task's
"ring attention OR all-to-all sequence parallelism" — we ship both;
SURVEY.md §5 notes the reference shows no long-context evidence, so this
is TPU-build-native capability, not reference parity). Pattern from
DeepSpeed-Ulysses (Jacobs et al. 2023), expressed with XLA collectives:

  1. Input arrives sequence-sharded: each of P devices holds a
     ``(B, S/P, H, D)`` block of Q/K/V.
  2. One ``jax.lax.all_to_all`` per tensor reshards sequence->heads:
     every device ends up with the FULL sequence for ``H/P`` heads,
     ``(B, S, H/P, D)``.
  3. Plain (unmodified, exact) attention runs locally per head group —
     a single large MXU-friendly batched matmul, no online-softmax
     bookkeeping and no P-step dependency chain.
  4. A second all-to-all reshards heads->sequence, restoring
     ``(B, S/P, H, D)``.

Trade-off vs ring attention: Ulysses moves Q+K+V+O once each
(4 all-to-alls totalling O(B*S*H*D/P) bytes per device) in two
latency-critical phases, while ring overlaps P ppermute hops of K/V with
compute but serializes P attention blocks. Ulysses needs ``H % P == 0``
(parallelism bounded by head count); ring scales to any P. Both are
exact and cross-checked against the dense reference in
tests/test_ulysses.py.

Call inside ``shard_map`` with the sequence axis sharded over
``axis_name``; shapes are per-device blocks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from consensusml_tpu.models.attention import dot_product_attention

__all__ = ["ulysses_attention"]


def ulysses_attention(
    q: jax.Array,  # (B, S_blk, H, D) — this device's sequence block
    k: jax.Array,  # (B, S_blk, H, D)
    v: jax.Array,  # (B, S_blk, H, D)
    axis_name: str,
    *,
    causal: bool = False,
) -> jax.Array:
    """Exact attention across the full (sharded) sequence via all-to-all.

    Returns this device's output block ``(B, S_blk, H, D)`` in ``q.dtype``.
    Requires the head count to be divisible by the axis size.
    """
    p = jax.lax.axis_size(axis_name)
    h = q.shape[2]
    if h % p:
        raise ValueError(
            f"ulysses_attention needs heads ({h}) divisible by the "
            f"'{axis_name}' axis size ({p}); use ring_attention for "
            "head-count-exceeding parallelism"
        )

    # sequence-sharded -> head-sharded: (B, S/P, H, D) -> (B, S, H/P, D)
    seq_to_heads = lambda x: jax.lax.all_to_all(
        x, axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)

    # full-sequence attention on our H/P heads: one big MXU matmul pair
    out = dot_product_attention(qg, kg, vg, causal=causal, dtype=q.dtype)

    # head-sharded -> sequence-sharded: (B, S, H/P, D) -> (B, S/P, H, D)
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)
