"""The five reference workload configs, runnable at two scales.

Reference parity: BASELINE.json ``configs`` — each entry below reproduces
one of them (SURVEY.md L6 config system; mount empty, so hyperparameters
are standard-literature defaults, flagged as approximations):

  mnist_mlp      — "2-layer MLP on MNIST, 4 simulated workers, dense gossip"
  cifar_resnet50 — "ResNet-50 on CIFAR-10, 8-worker ring consensus all-reduce"
  bert_mlm       — "BERT-base MLM, 32-worker local-SGD (H=8) + periodic averaging"
  llama_lora     — "Llama-2-7B LoRA fine-tune, torus gossip over 4x4 mesh"
  gpt2_topk      — "GPT-2-medium pretrain, top-k sparsified + 8-bit quantized gossip"

``scale="smoke"`` shrinks model/worker count for CPU runs and CI;
``scale="full"`` is the reference-sized workload for TPU pods. Data is
procedurally generated (no network in this environment — see
consensusml_tpu.data).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import optax

from consensusml_tpu.compress import topk_int8_compressor
from consensusml_tpu.consensus import GossipConfig
from consensusml_tpu.data import (
    SyntheticClassification,
    SyntheticLM,
    lm_round_batches,
    round_batches,
)
from consensusml_tpu.topology import topology_from_name
from consensusml_tpu.train import (
    LocalSGDConfig,
    causal_lm_eval_fn,
    classification_eval_fn,
    mlm_eval_fn,
)

__all__ = ["RunBundle", "CONFIGS", "build", "names"]


@dataclasses.dataclass
class RunBundle:
    """Everything the CLI needs to run one workload."""

    name: str
    world_size: int
    cfg: LocalSGDConfig
    loss_fn: Callable
    init_params: Callable[[jax.Array], Any]
    batches: Callable[..., Iterator[dict]]  # (rounds, seed, start=0) -> iterator
    description: str
    # held-out evaluation (train.evaluate): sums-returning metric fn +
    # UNSTACKED held-out batch iterator (n_batches, seed) -> iterator
    eval_fn: Callable | None = None
    eval_batches: Callable[[int, int], Iterator[dict]] | None = None


# keeps held-out LM sample streams disjoint from every training round key
_EVAL_SEED_OFFSET = 999_983


def _lm_eval_batches(data, batch: int, *, mlm_rate: float = 0.0):
    """Held-out LM batches: same Markov chain, disjoint rng keys."""
    from consensusml_tpu.data.synthetic import mlm_corrupt
    import numpy as np

    def gen(n_batches: int, seed: int):
        for r in range(n_batches):
            rng = np.random.default_rng((seed + _EVAL_SEED_OFFSET, r))
            ids = data.sample(rng, (batch,))
            if mlm_rate > 0:
                yield mlm_corrupt(ids, data, seed + _EVAL_SEED_OFFSET, r, mlm_rate)
            else:
                yield {"input_ids": jnp.asarray(ids)}

    return gen


def _cls_eval_batches(data, batch: int):
    """Held-out classification batches from the dataset's holdout split.

    The holdout split materializes lazily on first use, so eval-less runs
    (and ``--list``) never pay for a second dataset copy."""
    import numpy as np

    def gen(n_batches: int, seed: int):
        held = data.holdout()
        for r in range(n_batches):
            rng = np.random.default_rng((seed + _EVAL_SEED_OFFSET, r))
            idx = rng.integers(0, held.n, size=batch)
            yield {
                "image": jnp.asarray(held.images[idx]),
                "label": jnp.asarray(held.labels[idx]),
            }

    return gen


def _mnist_mlp(scale: str) -> RunBundle:
    from consensusml_tpu.models import MLP, mlp_loss_fn

    world = 4
    topo = topology_from_name("dense", world)
    model = MLP(hidden=256 if scale == "full" else 64)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo), optimizer=optax.adam(1e-3), h=1
    )
    data = SyntheticClassification(
        n=8192 if scale == "full" else 2048, image_shape=(28, 28, 1)
    )
    batch = 64
    return RunBundle(
        name="mnist_mlp",
        world_size=world,
        cfg=cfg,
        loss_fn=mlp_loss_fn(model),
        init_params=lambda r: model.init(r, jnp.zeros((1, 28, 28, 1)))["params"],
        batches=lambda rounds, seed, start=0: round_batches(data, world, cfg.h, batch, rounds, seed, start=start),
        description="2-layer MLP, 4 workers, dense gossip (CPU reference config)",
        eval_fn=classification_eval_fn(model),
        eval_batches=_cls_eval_batches(data, batch),
    )


def _cifar_resnet50(scale: str) -> RunBundle:
    from consensusml_tpu.models import resnet50, resnet_init, resnet_loss_fn
    from consensusml_tpu.models.resnet import BottleneckBlock, ResNet

    world = 8
    topo = topology_from_name("ring", world)
    if scale == "full":
        model = resnet50(num_classes=10, stem="cifar")
        batch, image = 128, 32
    else:
        model = ResNet(
            stage_sizes=[1, 1], block=BottleneckBlock, num_classes=10, width=8,
            stem="cifar", dtype=jnp.float32,
        )
        batch, image = 8, 16
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo),
        optimizer=optax.sgd(0.1 if scale == "full" else 0.05, momentum=0.9),
        h=1,
    )
    data = SyntheticClassification(
        n=4096 if scale == "full" else 512, image_shape=(image, image, 3), noise=0.25
    )
    return RunBundle(
        name="cifar_resnet50",
        world_size=world,
        cfg=cfg,
        loss_fn=resnet_loss_fn(model),
        init_params=resnet_init(model, (1, image, image, 3)),
        batches=lambda rounds, seed, start=0: round_batches(data, world, cfg.h, batch, rounds, seed, start=start),
        description="ResNet-50 (CIFAR stem), 8-worker ring consensus",
        eval_fn=classification_eval_fn(model, train_kwarg=True),
        eval_batches=_cls_eval_batches(data, batch),
    )


def _bert_mlm(scale: str) -> RunBundle:
    from consensusml_tpu.models.bert import BertConfig, BertMLM, bert_mlm_loss_fn

    if scale == "full":
        world, model, batch, seq = 32, BertMLM(config=BertConfig()), 32, 128
        vocab = 30522
    else:
        world = 4
        vocab = 64
        model = BertMLM(
            config=BertConfig(
                vocab_size=vocab, hidden=32, layers=2, heads=2, mlp_dim=64,
                max_len=32, dropout=0.0,
            )
        )
        batch, seq = 8, 16
    topo = topology_from_name("ring", world)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo), optimizer=optax.adam(1e-4 if scale == "full" else 1e-2), h=8
    )
    data = SyntheticLM(vocab_size=vocab, seq_len=seq)
    return RunBundle(
        name="bert_mlm",
        world_size=world,
        cfg=cfg,
        loss_fn=bert_mlm_loss_fn(model),
        init_params=lambda r: model.init(r, jnp.zeros((1, seq), jnp.int32))["params"],
        batches=lambda rounds, seed, start=0: lm_round_batches(
            data, world, cfg.h, batch, rounds, seed, mlm_rate=0.15, start=start
        ),
        description="BERT MLM, local-SGD H=8 + periodic ring averaging",
        eval_fn=mlm_eval_fn(model),
        eval_batches=_lm_eval_batches(data, batch, mlm_rate=0.15),
    )


def _llama_lora(scale: str) -> RunBundle:
    from consensusml_tpu.models.llama import llama2_7b, llama_tiny, llama_loss_fn
    from consensusml_tpu.models.lora import lora_gossip_filter, lora_mask, lora_optimizer

    if scale == "full":
        world, rows, cols = 16, 4, 4
        model = llama2_7b(lora_rank=16)
        batch, seq, vocab = 8, 2048, 32000
    else:
        world, rows, cols = 4, 2, 2
        model = llama_tiny(lora_rank=4)
        batch, seq, vocab = 8, 16, 256
    topo = topology_from_name("torus", world, rows=rows, cols=cols)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo, path_filter=lora_gossip_filter),
        optimizer=lora_optimizer(optax.adam(1e-3 if scale == "full" else 1e-2)),
        h=1,
    )
    data = SyntheticLM(vocab_size=vocab, seq_len=seq)

    def init(rng):
        # shared "pretrained" base across workers (fixed key, not per-worker)
        base_rng = jax.random.key(42)
        params = model.init(base_rng, jnp.zeros((1, seq), jnp.int32))["params"]
        mask = lora_mask(params)
        leaves = jax.tree.leaves(params)
        keys = jax.random.split(rng, len(leaves))
        return jax.tree.unflatten(
            jax.tree.structure(params),
            [
                jax.random.normal(k, p.shape, p.dtype) * 0.02 if m else p
                for p, m, k in zip(leaves, jax.tree.leaves(mask), keys)
            ],
        )

    return RunBundle(
        name="llama_lora",
        world_size=world,
        cfg=cfg,
        loss_fn=llama_loss_fn(model),
        init_params=init,
        batches=lambda rounds, seed, start=0: lm_round_batches(data, world, cfg.h, batch, rounds, seed, start=start),
        description=f"Llama LoRA fine-tune, {rows}x{cols} torus gossip (adapters-only wire)",
        eval_fn=causal_lm_eval_fn(model, deterministic_kwarg=False),
        eval_batches=_lm_eval_batches(data, batch),
    )


def _gpt2_topk(scale: str) -> RunBundle:
    from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM, gpt2_loss_fn

    if scale == "full":
        world = 8
        model = GPT2LM(config=GPT2Config())  # gpt2-medium dims
        batch, seq, vocab = 8, 1024, 50257
    else:
        world = 4
        vocab = 64
        model = GPT2LM(
            config=GPT2Config(
                vocab_size=vocab, hidden=32, layers=2, heads=2, max_len=32, dropout=0.0
            )
        )
        batch, seq = 8, 16
    topo = topology_from_name("ring", world)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(
            topology=topo,
            compressor=topk_int8_compressor(ratio=0.01 if scale == "full" else 0.1, chunk=128),
            gamma=0.5,
        ),
        optimizer=optax.adam(1e-4 if scale == "full" else 3e-3),
        h=2,
    )
    data = SyntheticLM(vocab_size=vocab, seq_len=seq)
    return RunBundle(
        name="gpt2_topk",
        world_size=world,
        cfg=cfg,
        loss_fn=gpt2_loss_fn(model),
        init_params=lambda r: model.init(r, jnp.zeros((1, seq), jnp.int32))["params"],
        batches=lambda rounds, seed, start=0: lm_round_batches(data, world, cfg.h, batch, rounds, seed, start=start),
        description="GPT-2 pretrain with top-k + int8 compressed gossip (CHOCO)",
        eval_fn=causal_lm_eval_fn(model),
        eval_batches=_lm_eval_batches(data, batch),
    )


CONFIGS = {
    "mnist_mlp": _mnist_mlp,
    "cifar_resnet50": _cifar_resnet50,
    "bert_mlm": _bert_mlm,
    "llama_lora": _llama_lora,
    "gpt2_topk": _gpt2_topk,
}


def names() -> list[str]:
    return sorted(CONFIGS)


def build(name: str, scale: str = "smoke") -> RunBundle:
    if name not in CONFIGS:
        raise ValueError(f"unknown config {name!r}; available: {names()}")
    if scale not in ("smoke", "full"):
        raise ValueError(f"scale must be smoke|full, got {scale!r}")
    return CONFIGS[name](scale)
