"""Runtime-side annotations the static passes key on.

Kept dependency-free (stdlib only): the instrumented modules
(``obs.metrics``, ``data.prefetch``, the native loader, the watchdog)
import :func:`guarded_by` at module load, so this file must never pull
in jax or anything from the package's runtime layers.
"""

from __future__ import annotations

__all__ = ["guarded_by"]


def guarded_by(lock_attr: str, *attrs: str):
    """Declare that ``self.<attr>`` (for each attr) may only be read or
    written while holding ``self.<lock_attr>``::

        @guarded_by("_lock", "_value", "_count")
        class Counter:
            ...

    At runtime this only records the contract on the class
    (``__guarded_by__``: attr -> lock attr, merged across decorators and
    base classes); enforcement is static — the cml-check lock-discipline
    pass (:mod:`consensusml_tpu.analysis.locks`) flags any access to an
    annotated attribute outside a lexical ``with self.<lock_attr>:``
    block. ``__init__`` is exempt (the object is not shared before
    construction completes). Intentional exceptions go in
    ``.cml-check-baseline`` with a comment, not around the convention.
    """
    if not isinstance(lock_attr, str) or not lock_attr:
        raise ValueError("guarded_by needs the lock attribute name first")
    if not attrs:
        raise ValueError("guarded_by needs at least one guarded attribute")

    def deco(cls):
        merged = dict(getattr(cls, "__guarded_by__", {}) or {})
        for a in attrs:
            merged[a] = lock_attr
        cls.__guarded_by__ = merged
        return cls

    return deco
