"""Bounded explicit-state model checking for host-side control protocols.

cml-check pass 8 (``--model``) proves safety invariants of the serving
control plane — BlockPool/PrefixIndex refcounts, the request lifecycle
composed with hot-swap generation flips, and membership epoch
pin/advance — on *every* interleaving of a small number of abstract
actors, up to a depth bound.  The runtime tools (``BlockPool.check()``,
the lockdep fuzzer) only observe the schedules that happened to run;
this pass enumerates all of them.

A protocol model is any object with four methods::

    initial() -> state                  # hashable (tuples/frozensets)
    labels(state) -> iterable[label]    # candidate actions (tuples)
    apply(state, label) -> state        # raises IllegalAction when the
                                        # label's guard fails in `state`
    invariant(state) -> str | None      # violation message, or None

``check_model`` runs an exhaustive depth-first search with state
hashing: every distinct reachable state is visited once (re-visited
only when reached again at a shallower depth, so the bound is honoured
exactly).  When an invariant breaks, the counterexample is re-derived
by breadth-first search so the reported trace is *minimal* — the
shortest action sequence from the initial state to any violating
state.

``replay`` drives the same ``apply``/``invariant`` code over a recorded
trace from the real implementation (see ``analysis/conformance.py``) —
conformance is "recorded traces are valid paths of the model", proven
by replay rather than asserted.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Iterable, Optional, Protocol, Sequence, Tuple

Label = Tuple[Any, ...]


class IllegalAction(Exception):
    """Raised by ``apply`` when a label's guard fails in this state."""


class ConformanceError(Exception):
    """A recorded trace is not a valid path of the abstract model."""


class ProtocolModel(Protocol):
    name: str
    subject: str  # repo-relative source file this model abstracts

    def initial(self) -> Any: ...

    def labels(self, state: Any) -> Iterable[Label]: ...

    def apply(self, state: Any, label: Label) -> Any: ...

    def invariant(self, state: Any) -> Optional[str]: ...


@dataclasses.dataclass(frozen=True)
class CheckResult:
    """Outcome of one bounded search."""

    ok: bool
    states: int  # distinct states visited
    transitions: int  # enabled transitions taken
    max_depth: Optional[int]  # the bound the search ran with (None = full)
    hit_bound: bool  # some path was truncated at max_depth
    violation: Optional[str] = None
    trace: Tuple[Label, ...] = ()  # minimal counterexample when not ok

    def format_trace(self) -> str:
        return " ; ".join(_format_label(l) for l in self.trace)


def _format_label(label: Label) -> str:
    head = str(label[0])
    if len(label) == 1:
        return head
    return head + "(" + ", ".join(repr(a) for a in label[1:]) + ")"


def successors(model: ProtocolModel, state: Any) -> Iterable[Tuple[Label, Any]]:
    """Enabled transitions of `state`: labels whose guards hold."""
    for label in model.labels(state):
        try:
            yield label, model.apply(state, label)
        except IllegalAction:
            continue


def check_model(
    model: ProtocolModel,
    max_depth: Optional[int] = 12,
    max_states: int = 200_000,
) -> CheckResult:
    """Exhaustive bounded DFS with state hashing.

    With an integer ``max_depth``, visits every state reachable within
    ``max_depth`` actions (a state is re-expanded when reached again at
    a shallower depth, so no state within the bound is missed).  With
    ``max_depth=None`` the search is pure reachability — every state of
    a FINITE protocol is visited exactly once and the result covers the
    whole reachable space (``hit_bound`` is then always False).  Stops
    at the first invariant violation and reports a BFS-minimal
    counterexample trace.
    """
    init = model.initial()
    msg = model.invariant(init)
    if msg is not None:
        return CheckResult(
            ok=False, states=1, transitions=0, max_depth=max_depth,
            hit_bound=False, violation=msg, trace=(),
        )

    bounded = max_depth is not None
    # best_depth[state] = shallowest depth at which `state` was expanded
    # (pinned to 0 in the unbounded search: first visit is the only one).
    best_depth = {init: 0}
    stack = [(init, 0)]
    transitions = 0
    hit_bound = False
    while stack:
        state, depth = stack.pop()
        if bounded and depth >= max_depth:
            # Truncated: note it so callers know the bound was active.
            for _ in successors(model, state):
                hit_bound = True
                break
            continue
        for label, nxt in successors(model, state):
            transitions += 1
            msg = model.invariant(nxt)
            if msg is not None:
                trace, msg = _minimal_counterexample(model, max_depth, msg)
                return CheckResult(
                    ok=False, states=len(best_depth), transitions=transitions,
                    max_depth=max_depth, hit_bound=hit_bound,
                    violation=msg, trace=trace,
                )
            nd = depth + 1 if bounded else 0
            seen = best_depth.get(nxt)
            if seen is not None and seen <= nd:
                continue
            best_depth[nxt] = nd
            if len(best_depth) > max_states:
                raise RuntimeError(
                    f"model {model.name!r}: state space exceeds "
                    f"max_states={max_states}"
                )
            stack.append((nxt, nd))
    return CheckResult(
        ok=True, states=len(best_depth), transitions=transitions,
        max_depth=max_depth, hit_bound=hit_bound,
    )


def _minimal_counterexample(
    model: ProtocolModel, max_depth: Optional[int], fallback_msg: str
) -> Tuple[Tuple[Label, ...], str]:
    """BFS from the initial state to the nearest invariant violation.

    DFS finds *a* violation fast; this re-search guarantees the
    reported trace is the shortest one, which is what makes
    counterexamples readable. Returns ``(trace, violation_message)``
    with the message recomputed at the minimal trace's end state, so
    the two always describe the same path.
    """
    init = model.initial()
    seen = {init}
    frontier: deque = deque([(init, ())])
    while frontier:
        state, trace = frontier.popleft()
        if max_depth is not None and len(trace) >= max_depth:
            continue
        for label, nxt in successors(model, state):
            path = trace + (label,)
            msg = model.invariant(nxt)
            if msg is not None:
                return path, msg
            if nxt in seen:
                continue
            seen.add(nxt)
            frontier.append((nxt, path))
    # unreachable when the caller just saw a violation
    return (), fallback_msg


def replay(
    model: ProtocolModel,
    trace: Sequence[Label],
    state: Any = None,
) -> Any:
    """Replay a recorded trace as a path of the model (conformance).

    Every label must be a legal action of the model in sequence, and
    the invariant must hold after every step.  Raises
    ``ConformanceError`` naming the failing step otherwise.  Returns
    the final state.
    """
    if state is None:
        state = model.initial()
    msg = model.invariant(state)
    if msg is not None:
        raise ConformanceError(
            f"model {model.name!r}: initial state violates invariant: {msg}"
        )
    for i, label in enumerate(trace):
        try:
            state = model.apply(state, label)
        except IllegalAction as e:
            raise ConformanceError(
                f"model {model.name!r}: step {i} {_format_label(label)}: "
                f"illegal in recorded context: {e}"
            ) from e
        msg = model.invariant(state)
        if msg is not None:
            raise ConformanceError(
                f"model {model.name!r}: step {i} {_format_label(label)}: "
                f"invariant violated after replay step: {msg}"
            )
    return state
