"""Runtime lock-order sanitizer: observe what the static graph predicts.

:mod:`~consensusml_tpu.analysis.lockorder` PROVES ordering from the
source; this module WATCHES it at runtime. Opt-in (tests only — the
wrappers put a Python frame on every acquire, which production code
must not pay): while a :class:`LockOrderSanitizer` is installed, every
lock constructed through ``threading.Lock()`` / ``threading.RLock()``
is wrapped, and each acquisition records

- the per-thread acquisition stack (which locks were already held),
- one directed edge ``held -> acquired`` per held lock,
- a resolved NAME for package locks: the wrapper is found by identity
  in the acquiring frame's ``self.__dict__``, yielding the same
  ``ClassName._attr`` node ids the static model uses.

:meth:`LockOrderSanitizer.check` then asserts two things:

- the observed graph is **acyclic** — a cycle means some interleaving
  of the exercised paths deadlocks (the runtime twin of the static
  ``lock-cycle`` finding, catching orders composed through code the
  AST passes cannot resolve: dynamic dispatch, callbacks, C code);
- the observed graph is a **subgraph of the static model** for edges
  between package locks — an observed edge static analysis never
  predicted means the model or the code drifted, and the lint's proof
  no longer covers reality.

The **schedule-fuzz harness** (:func:`fuzz_schedule`) drives worker
callables concurrently under randomized ``sys.setswitchinterval``
values with a barrier-aligned start, and the sanitizer can inject
seeded sub-millisecond sleeps before acquisitions (``fuzz=``) to widen
race windows — together they make one test run explore many
interleavings deterministically-seeded. Tier-1 uses this to drive the
paged engine's submit/drain/hot-swap/scrape/preempt paths concurrently
(``tests/test_lockdep.py``).
"""

from __future__ import annotations

import random
import sys
import threading
import time
from typing import Any, Callable, Iterable

__all__ = ["LockOrderSanitizer", "fuzz_schedule"]

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock


def _creation_site() -> str:
    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename.endswith(
        ("threading.py", "lockdep.py")
    ):
        f = f.f_back
    if f is None:  # pragma: no cover - interpreter internals
        return "anon"
    return f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}"


class _SanitizedLock:
    """Duck-typed stand-in for a ``threading.Lock``/``RLock``: the
    public protocol plus the private ``_is_owned``/``_release_save``/
    ``_acquire_restore`` trio ``threading.Condition`` binds when
    present — so wrapped locks work inside ``queue.Queue``,
    ``threading.Event`` and ``Condition(RLock())`` alike, with the
    sanitizer's held stack kept honest across ``Condition.wait``'s
    full release/re-acquire."""

    def __init__(self, san: "LockOrderSanitizer", inner: Any, kind: str):
        self._ld_san = san
        self._ld_inner = inner
        self._ld_kind = kind  # "Lock" | "RLock"
        self._ld_site = _creation_site()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        self._ld_san._pre_acquire(self)
        ok = self._ld_inner.acquire(blocking, timeout)
        if ok:
            self._ld_san._on_acquired(self)
        return ok

    def release(self):
        self._ld_inner.release()
        self._ld_san._on_released(self)

    def locked(self):
        try:
            return self._ld_inner.locked()
        except AttributeError:  # RLock pre-3.12 has no locked()
            if self._ld_inner.acquire(False):
                self._ld_inner.release()
                return False
            return True

    # -- threading.Condition private protocol ------------------------------
    # Condition binds these when present; without them its acquire(False)
    # fallback _is_owned() SUCCEEDS re-entrantly on a held wrapped RLock
    # and wait() dies with "cannot wait on un-acquired lock". Implemented
    # for both inner kinds, with the sanitizer's held stack kept honest
    # across the full-release/re-acquire that wait() performs.

    def _is_owned(self):
        inner = self._ld_inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):  # plain Lock: stdlib fallback semantics
            inner.release()
            return False
        return True

    def _release_save(self):
        held = self._ld_san._held()
        count = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                del held[i]
                count += 1
        inner = self._ld_inner
        if hasattr(inner, "_release_save"):
            state = inner._release_save()
        else:
            inner.release()
            state = None
        return (state, max(count, 1))

    def _acquire_restore(self, saved):
        state, count = saved
        inner = self._ld_inner
        if state is not None and hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        for _ in range(count):  # re-acquisition after wait(): real edges
            self._ld_san._on_acquired(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):  # pragma: no cover - debug aid
        return f"<sanitized {self._ld_kind} @{self._ld_site}>"


class LockOrderSanitizer:
    """Records lock-acquisition order while installed; see module doc.

    Use as a context manager around the code that CONSTRUCTS the
    objects under test (wrapping happens at lock construction):

        with LockOrderSanitizer(fuzz=0.05, seed=3) as san:
            engine = Engine(...)
            ... drive it from many threads ...
        san.assert_clean(static=lockorder.static_model(REPO))
    """

    def __init__(self, fuzz: float = 0.0, seed: int = 0):
        self.fuzz = float(fuzz)
        self._rng = random.Random(seed)
        self._rng_lock = _REAL_LOCK()
        self._state = _REAL_LOCK()  # guards the maps below
        # (holder name, acquired name) -> witness "thread / site"
        self.edges: dict[tuple[str, str], str] = {}
        self.reentries: dict[str, int] = {}
        # explicit-name override hook (tests plant entries); resolved
        # names are cached on each wrapper itself, never keyed by id
        self._names: dict[int, tuple[str, bool]] = {}
        self._tls = threading.local()
        self._installed = False
        self.acquisitions = 0

    # -- installation ------------------------------------------------------
    def install(self) -> "LockOrderSanitizer":
        if self._installed:
            return self
        san = self

        def make_lock():
            return _SanitizedLock(san, _REAL_LOCK(), "Lock")

        def make_rlock():
            return _SanitizedLock(san, _REAL_RLOCK(), "RLock")

        threading.Lock = make_lock  # type: ignore[assignment]
        threading.RLock = make_rlock  # type: ignore[assignment]
        self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            threading.Lock = _REAL_LOCK  # type: ignore[assignment]
            threading.RLock = _REAL_RLOCK  # type: ignore[assignment]
            self._installed = False

    def __enter__(self) -> "LockOrderSanitizer":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- acquisition hooks -------------------------------------------------
    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _pre_acquire(self, wrapper: _SanitizedLock) -> None:
        if self.fuzz <= 0.0:
            return
        with self._rng_lock:
            hit = self._rng.random() < self.fuzz
            dt = self._rng.random() * 1e-4
        if hit:
            time.sleep(dt)  # widen the race window, seeded

    def _on_acquired(self, wrapper: _SanitizedLock) -> None:
        held = self._held()
        name, is_pkg = self._name_of(wrapper)
        with self._state:  # concurrent read-modify-write, not GIL-atomic
            self.acquisitions += 1
        for w, _n in held:
            if w is wrapper:  # RLock re-entry: exempt self-loop
                with self._state:
                    self.reentries[name] = self.reentries.get(name, 0) + 1
                held.append((wrapper, name))
                return
        if held:
            t = threading.current_thread().name
            with self._state:
                for _w, h in held:
                    if h != name:
                        self.edges.setdefault(
                            (h, name), f"thread {t}: {h} -> {name}"
                        )
        held.append((wrapper, name))

    def _on_released(self, wrapper: _SanitizedLock) -> None:
        held = getattr(self._tls, "held", None)
        if not held:
            return
        # releases may be non-LIFO (Condition.wait drops the mutex from
        # the middle of the stack): remove the NEWEST entry for this lock
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is wrapper:
                del held[i]
                return

    def _name_of(self, wrapper: _SanitizedLock) -> tuple[str, bool]:
        got = self._names.get(id(wrapper))  # test/override hook
        if got is not None:
            return got
        # cache ON the wrapper, not in an id-keyed map: a GC'd wrapper's
        # id can be reused by a new lock, which would inherit the dead
        # lock's name and fabricate edges
        cached = getattr(wrapper, "_ld_name", None)
        if cached is not None:
            return cached
        name, is_pkg = self._resolve_name(wrapper)
        wrapper._ld_name = (name, is_pkg)
        return name, is_pkg

    def _resolve_name(self, wrapper: _SanitizedLock) -> tuple[str, bool]:
        """``ClassName._attr`` via identity search in the acquiring
        frames' ``self`` objects — package classes preferred, so the
        names line up with the static model's nodes."""
        try:
            f = sys._getframe(3)
        except ValueError:  # pragma: no cover
            f = None
        fallback: tuple[str, bool] | None = None
        depth = 0
        while f is not None and depth < 20:
            locs = f.f_locals
            candidates = []
            if "self" in locs:
                candidates.append(locs["self"])
            candidates.extend(
                v for k, v in locs.items() if k != "self"
            )
            for obj in candidates:
                if obj is None or isinstance(obj, _SanitizedLock):
                    continue
                d = getattr(obj, "__dict__", None)
                if not d:
                    continue
                for attr, val in list(d.items()):
                    if val is wrapper:
                        name = f"{type(obj).__name__}.{attr}"
                        pkg = type(obj).__module__.startswith(
                            "consensusml_tpu"
                        )
                        if pkg:
                            return name, True
                        if fallback is None:
                            fallback = (name, False)
                        break
            f = f.f_back
            depth += 1
        if fallback is not None:
            return fallback
        return f"anon@{wrapper._ld_site}", False

    # -- verification ------------------------------------------------------
    def observed_edges(self) -> dict[tuple[str, str], str]:
        with self._state:
            return dict(self.edges)

    def check(self, static=None) -> list[str]:
        """Violations: observed-order cycles, plus observed edges between
        package locks the static model (a
        :class:`~consensusml_tpu.analysis.lockorder.LockModel`) does not
        contain."""
        edges = self.observed_edges()
        problems: list[str] = []
        graph: dict[str, set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        # cycle detection: iterative DFS with colors
        WHITE, GREY, BLACK = 0, 1, 2
        color = {v: WHITE for v in graph}
        for root in sorted(graph):
            if color[root] != WHITE:
                continue
            stack = [(root, iter(sorted(graph[root])))]
            color[root] = GREY
            path = [root]
            while stack:
                node, it = stack[-1]
                for nxt in it:
                    if color[nxt] == GREY:
                        cyc = path[path.index(nxt):] + [nxt]
                        problems.append(
                            "observed lock-order cycle: "
                            + " -> ".join(cyc)
                            + " (witness: "
                            + edges.get((cyc[0], cyc[1]), "?")
                            + ")"
                        )
                    elif color[nxt] == WHITE:
                        color[nxt] = GREY
                        stack.append((nxt, iter(sorted(graph[nxt]))))
                        path.append(nxt)
                        break
                else:
                    color[node] = BLACK
                    stack.pop()
                    path.pop()
        if static is not None:
            known = set(static.kinds)
            for (a, b), wit in sorted(edges.items()):
                if a in known and b in known and not static.has_edge(a, b):
                    problems.append(
                        f"observed edge {a} -> {b} is NOT in the static "
                        f"lock model ({wit}) — the lockorder pass can no "
                        "longer see this nesting; make the call path "
                        "statically resolvable or re-examine the code"
                    )
        return problems

    def assert_clean(self, static=None) -> None:
        problems = self.check(static)
        if problems:
            raise AssertionError(
                "lockdep: "
                + "; ".join(problems)
                + f" [{self.acquisitions} acquisitions observed]"
            )


def fuzz_schedule(
    workers: Iterable[Callable[[], Any]],
    *,
    seed: int = 0,
    repeat: int = 1,
    switch_intervals: tuple = (1e-6, 1e-5, 1e-4, 5e-3),
    timeout_s: float = 60.0,
) -> None:
    """Run ``workers`` concurrently ``repeat`` times under randomized
    thread-switch intervals, each round barrier-aligned so every worker
    starts inside the same scheduling window. Worker exceptions re-raise
    on the caller; the previous switch interval is always restored."""
    workers = list(workers)
    prev = sys.getswitchinterval()
    rng = random.Random(seed)
    try:
        for _round in range(repeat):
            sys.setswitchinterval(rng.choice(switch_intervals))
            barrier = threading.Barrier(len(workers))
            errors: list[BaseException] = []

            def run(fn: Callable[[], Any]) -> None:
                try:
                    barrier.wait(timeout=timeout_s)
                    fn()
                except BaseException as e:  # re-raised below
                    errors.append(e)

            threads = [
                threading.Thread(
                    target=run, args=(w,), name=f"lockdep-fuzz-{i}",
                    daemon=True,
                )
                for i, w in enumerate(workers)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=timeout_s)
                if t.is_alive():
                    raise TimeoutError(
                        f"lockdep fuzz worker {t.name} still running after "
                        f"{timeout_s}s — possible deadlock"
                    )
            if errors:
                raise errors[0]
    finally:
        sys.setswitchinterval(prev)
