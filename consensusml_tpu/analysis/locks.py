"""Lock-discipline race lint for ``@guarded_by``-annotated classes.

The threaded host side of the stack (device prefetcher, native ring
loader, metrics registry, watchdog) shares mutable state between a
training thread, producer threads, signal handlers and teardown hooks.
An unguarded mutation there does not crash — it corrupts counters or
races a handle free. This pass makes the locking contract *checkable*:

    @guarded_by("_lock", "_value", "_count")
    class Counter:
        def inc(self):
            with self._lock:
                self._value += 1      # ok
        def peek(self):
            return self._value        # finding: unguarded-read

Rules:

- ``unguarded-read`` / ``unguarded-write`` — ``self.<attr>`` access for
  an annotated attr outside a lexical ``with self.<lock>:`` block.
- ``__init__`` is exempt: the object is not shared before construction
  completes. (``__del__`` is NOT exempt — finalizers run concurrently
  with everything.)
- A ``with self.<lock>:`` anywhere up the lexical statement chain
  satisfies the contract; multi-item ``with`` statements count each
  item.
- ``bare-acquire`` — ``self.<lock>.acquire()`` / ``.release()`` on any
  attribute the class assigns a ``threading.Lock``/``RLock`` to (or
  names as a ``@guarded_by`` lock). A bare pair never satisfies the
  guard (the pass cannot pair the release), leaks the lock on an
  exception between the calls, and hides the critical section from the
  lock-order graph (:mod:`~consensusml_tpu.analysis.lockorder`) — use
  ``with``. Applies to every class, annotated or not.
- ``guarded-escape`` — ``return``/``yield`` of a guarded MUTABLE
  attribute (list/dict/set/deque/... per its ``__init__`` assignment)
  as a bare reference while holding the lock: the caller now mutates or
  iterates the shared object outside any lock. Return a copy
  (``list(self._x)``) instead.
- ``guarded-alias-escape`` — the two-step form of the same leak: a
  local aliased to a guarded mutable under the lock
  (``x = self._items``) and later returned/yielded. The
  ownership-TRANSFER pattern (``x, self._items = self._items, None`` —
  the shared slot is re-bound in the same ``with`` block) is exempt:
  after the transfer the object is no longer shared.
- Functions nested inside a method are analyzed with an EMPTY lock set:
  a closure may escape the lock scope it was created in (handed to a
  thread/callback), so holding the lock at definition time proves
  nothing about call time. Baseline the finding if the closure provably
  never escapes.
- ``unlocked-read`` — a read of a guarded attribute from OUTSIDE the
  owning class: ``watcher._generation`` in engine code reaches into
  ``GenerationWatcher``'s ``@guarded_by`` state with no lock at all.
  Receivers are typed from construction sites (``self._w =
  GenerationWatcher(...)`` / ``w = GenerationWatcher(...)``), so a
  same-named private attr on an unrelated class never false-positives.
  The package-wide guarded-class map is built in :func:`lint_paths`'s
  first phase; intentional cross-class reads (tests' white-box pokes
  live outside the scanned roots; in-package ones are reviewed) get
  baselined, real ones get a lock or an accessor.

The decorator itself lives in
:mod:`consensusml_tpu.analysis.annotations` and is a pure metadata
no-op at runtime.
"""

from __future__ import annotations

import ast
import os

from consensusml_tpu.analysis.findings import Finding

__all__ = ["lint_source", "lint_file", "lint_paths"]

PASS = "locks"


def _guard_map_from_class(cls: ast.ClassDef) -> dict[str, str]:
    """attr -> lock attr, from ``@guarded_by("lock", "a", "b")``
    decorators (string literals only — the annotation is a static
    contract, computed lock names defeat the point)."""
    gm: dict[str, str] = {}
    for deco in cls.decorator_list:
        if not isinstance(deco, ast.Call):
            continue
        name = (
            deco.func.attr
            if isinstance(deco.func, ast.Attribute)
            else getattr(deco.func, "id", None)
        )
        if name != "guarded_by" or not deco.args:
            continue
        vals = [
            a.value
            for a in deco.args
            if isinstance(a, ast.Constant) and isinstance(a.value, str)
        ]
        if len(vals) >= 2:
            lock, *attrs = vals
            for a in attrs:
                gm[a] = lock
    return gm


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


# constructors whose result is shared-mutable for the escape rules; a
# frozen dataclass handed out of a lock is a snapshot, these are not
_MUTABLE_CTORS = {
    "list", "dict", "set", "deque", "OrderedDict", "defaultdict",
    "bytearray", "Counter",
}


def _is_mutable_expr(node: ast.AST | None) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        seg = node.func.attr if isinstance(node.func, ast.Attribute) else (
            node.func.id if isinstance(node.func, ast.Name) else None
        )
        return seg in _MUTABLE_CTORS
    return False


def _lock_attrs_of_class(
    cls: ast.ClassDef, guard: dict[str, str]
) -> set[str]:
    """Attributes holding a lock: ``@guarded_by`` lock names plus every
    ``self.<attr> = threading.Lock()/RLock()`` assignment in the class."""
    out = set(guard.values())
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        seg = (
            value.func.attr
            if isinstance(value.func, ast.Attribute)
            else getattr(value.func, "id", None)
        )
        if seg not in ("Lock", "RLock"):
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            attr = _self_attr(t)
            if attr is not None:
                out.add(attr)
    return out


def _mutable_attrs_of_class(cls: ast.ClassDef) -> set[str]:
    """Attributes ``__init__`` binds to a mutable container — the ones
    whose bare reference must not leak out of the lock."""
    out: set[str] = set()
    for item in cls.body:
        if (
            isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
            and item.name == "__init__"
        ):
            for node in ast.walk(item):
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    if not _is_mutable_expr(node.value):
                        continue
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        attr = _self_attr(t)
                        if attr is not None:
                            out.add(attr)
    return out


class _MethodScan:
    def __init__(
        self,
        guard: dict[str, str],
        cls_name: str,
        path: str,
        mutable: frozenset[str] = frozenset(),
    ):
        self.guard = guard
        self.cls_name = cls_name
        self.path = path
        self.mutable = mutable
        self.findings: list[Finding] = []
        # alias-escape state, reset per method: local name -> (attr, line)
        self._aliases: dict[str, tuple[str, int]] = {}

    def scan(self, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> None:
        if fn.name == "__init__":
            return
        self._aliases = {}
        qual = f"{self.cls_name}.{fn.name}"
        self._walk_stmts(fn.body, frozenset(), qual)
        if self._aliases:
            self._check_alias_returns(fn, qual)

    def _walk_stmts(self, stmts, held: frozenset[str], qual: str) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # closures escape lock scope: analyze with no locks held
                self._walk_stmts(
                    st.body, frozenset(), f"{qual}.<locals>.{st.name}"
                )
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                now = set(held)
                for item in st.items:
                    attr = _self_attr(item.context_expr)
                    if attr is not None:
                        now.add(attr)
                    self._scan_expr(item.context_expr, held, qual)
                    if item.optional_vars is not None:
                        self._scan_expr(item.optional_vars, held, qual)
                self._collect_aliases(st, frozenset(now), qual)
                self._walk_stmts(st.body, frozenset(now), qual)
                continue
            if isinstance(st, ast.Return) and st.value is not None:
                self._check_escape(st.value, held, qual)
            # compound statements: scan their own expressions with the
            # current lock set, then their bodies
            for field in ("test", "iter", "value", "exc", "cause", "msg"):
                sub = getattr(st, field, None)
                if isinstance(sub, ast.AST):
                    self._scan_expr(sub, held, qual)
            if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                # the read side of `self.x += 1` is covered by the write
                # finding; st.value was scanned via the field loop above
                targets = (
                    st.targets
                    if isinstance(st, ast.Assign)
                    else [st.target]
                )
                for t in targets:
                    self._scan_target(t, held, qual)
            for body_field in ("body", "orelse", "finalbody"):
                sub = getattr(st, body_field, None)
                if isinstance(sub, list):
                    self._walk_stmts(sub, held, qual)
            for h in getattr(st, "handlers", []) or []:
                self._walk_stmts(h.body, held, qual)

    def _flag(self, attr: str, lock: str, write: bool, line: int, qual: str):
        rule = "unguarded-write" if write else "unguarded-read"
        verb = "write to" if write else "read of"
        self.findings.append(
            Finding(
                PASS, rule, self.path, qual, attr,
                f"{verb} self.{attr} outside `with self.{lock}:` "
                f"(declared guarded_by({lock!r}) on {self.cls_name})",
                line,
            )
        )

    # -- escape analysis ----------------------------------------------------

    def _guarded_mutable(self, node: ast.AST, held: frozenset[str]):
        """``(attr, lock)`` when ``node`` is a bare reference to a
        guarded mutable attribute whose lock is currently held."""
        attr = _self_attr(node)
        if attr is None or attr not in self.mutable:
            return None
        lock = self.guard.get(attr)
        if lock is None or lock not in held:
            return None
        return attr, lock

    def _check_escape(self, value: ast.AST, held: frozenset[str], qual: str):
        hit = self._guarded_mutable(value, held)
        if hit is None:
            return
        attr, lock = hit
        self.findings.append(
            Finding(
                PASS, "guarded-escape", self.path, qual, attr,
                f"bare reference to mutable self.{attr} escapes the "
                f"`with self.{lock}:` block via return/yield — the "
                "caller mutates/iterates it with no lock; hand out a "
                f"copy (e.g. list(self.{attr}))",
                value.lineno,
            )
        )

    def _collect_aliases(
        self, with_node: ast.AST, held: frozenset[str], qual: str
    ) -> None:
        """Record ``x = self.<guarded mutable>`` bindings made under the
        lock — unless the same ``with`` body re-binds the attribute
        (ownership transfer)."""
        def restricted(node):
            # this with's own straight-line body: recurse through
            # compound statements but NOT nested withs (their own call
            # collects them) or nested functions (closure rule)
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.With, ast.AsyncWith, ast.FunctionDef,
                     ast.AsyncFunctionDef, ast.Lambda),
                ):
                    continue
                yield child
                yield from restricted(child)

        stores: set[str] = set()
        assigns: list[tuple[str, str, int]] = []  # (local, attr, line)
        for node in restricted(with_node):
            if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, (ast.Store, ast.Del)
            ):
                attr = _self_attr(node)
                if attr is not None:
                    stores.add(attr)
            if isinstance(node, ast.Assign):
                pairs: list[tuple[ast.AST, ast.AST]] = []
                if (
                    len(node.targets) == 1
                    and isinstance(node.targets[0], (ast.Tuple, ast.List))
                    and isinstance(node.value, (ast.Tuple, ast.List))
                    and len(node.targets[0].elts) == len(node.value.elts)
                ):
                    pairs = list(zip(node.targets[0].elts, node.value.elts))
                else:
                    pairs = [(t, node.value) for t in node.targets]
                for tgt, val in pairs:
                    if not isinstance(tgt, ast.Name):
                        continue
                    hit = self._guarded_mutable(val, held)
                    if hit is not None:
                        assigns.append((tgt.id, hit[0], tgt.lineno))
        for local, attr, line in assigns:
            if attr not in stores:  # re-bound in-block == transfer, exempt
                self._aliases[local] = (attr, line)

    def _check_alias_returns(self, fn: ast.AST, qual: str) -> None:
        def walk(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    continue
                yield child
                yield from walk(child)

        # a later re-binding breaks the alias — `x = list(x)` (the very
        # copy the escape rule recommends) must not be flagged
        rebinds: dict[str, set[int]] = {}
        for node in walk(fn):
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)
            ):
                rebinds.setdefault(node.id, set()).add(node.lineno)
        for name in list(self._aliases):
            _attr, bind_line = self._aliases[name]
            if rebinds.get(name, set()) - {bind_line}:
                del self._aliases[name]

        for node in walk(fn):
            value = None
            if isinstance(node, ast.Return):
                value = node.value
            elif isinstance(node, (ast.Yield, ast.YieldFrom)):
                value = node.value
            if not isinstance(value, ast.Name):
                continue
            hit = self._aliases.get(value.id)
            if hit is None:
                continue
            attr, bind_line = hit
            lock = self.guard.get(attr, "?")
            self.findings.append(
                Finding(
                    PASS, "guarded-alias-escape", self.path, qual, attr,
                    f"local {value.id!r} aliases mutable self.{attr} "
                    f"under `with self.{lock}:` (line {bind_line}) and "
                    "escapes via return/yield — the shared object leaks "
                    "out of the lock; copy it, or re-bind the attribute "
                    "in the same block (ownership transfer)",
                    value.lineno,
                )
            )

    def _scan_target(self, node: ast.AST, held: frozenset[str], qual: str):
        attr = _self_attr(node)
        if attr is not None:
            lock = self.guard.get(attr)
            if lock is not None and lock not in held:
                self._flag(attr, lock, True, node.lineno, qual)
            return
        for child in ast.iter_child_nodes(node):
            self._scan_target(child, held, qual)

    def _scan_expr(self, node: ast.AST, held: frozenset[str], qual: str):
        # a lambda is a closure like a nested def: it may escape the
        # lock scope it was written under (thread target, callback), so
        # its body is analyzed with NO locks held — and must be pruned
        # from the enclosing walk, not just skipped as a node
        if isinstance(node, ast.Lambda):
            self._scan_expr(
                node.body, frozenset(), f"{qual}.<locals>.<lambda>"
            )
            return
        if (
            isinstance(node, (ast.Yield, ast.YieldFrom))
            and node.value is not None
        ):
            self._check_escape(node.value, held, qual)
        attr = _self_attr(node)
        if attr is not None:
            lock = self.guard.get(attr)
            if lock is not None and lock not in held:
                write = isinstance(node.ctx, (ast.Store, ast.Del))
                self._flag(attr, lock, write, node.lineno, qual)
        for child in ast.iter_child_nodes(node):
            self._scan_expr(child, held, qual)


def _scan_bare_acquire(
    cls: ast.ClassDef, lock_attrs: set[str], path: str
) -> list[Finding]:
    """``self.<lock>.acquire()``/``.release()`` anywhere in the class —
    the ``with``-less form the guard rules cannot see through."""
    findings: list[Finding] = []
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        qual = f"{cls.name}.{item.name}"
        for node in ast.walk(item):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in ("acquire", "release")
            ):
                attr = _self_attr(fn.value)
                if attr in lock_attrs:
                    findings.append(
                        Finding(
                            PASS, "bare-acquire", path, qual, attr,
                            f"bare self.{attr}.{fn.attr}() — the lint "
                            "cannot pair it with its release, an "
                            "exception between the pair leaks the lock, "
                            "and the lock-order graph cannot see the "
                            "critical section; use `with "
                            f"self.{attr}:` (guard a try-acquire with a "
                            "flag under a plain `with` instead)",
                            node.lineno,
                        )
                    )
    return findings


# -- cross-class unlocked reads ---------------------------------------------


def _guarded_classes_in_tree(tree: ast.AST) -> dict[str, dict[str, str]]:
    """class name -> (guarded attr -> lock) for every annotated class."""
    out: dict[str, dict[str, str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            gm = _guard_map_from_class(node)
            if gm:
                out[node.name] = gm
    return out


def _ctor_class(value: ast.AST, guarded: dict[str, dict[str, str]]):
    """The guarded class a ``X(...)`` construction instantiates, else
    None. Both ``GenerationWatcher(...)`` and ``mod.GenerationWatcher
    (...)`` resolve on the final name segment."""
    if not isinstance(value, ast.Call):
        return None
    fn = value.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None
    )
    return name if name in guarded else None


class _ExternalReadScan:
    """Flag loads of another class's guarded attributes.

    Receiver typing is construction-site based, the same idiom the
    lock-order pass uses: ``self._w = GenerationWatcher(...)`` types
    ``self._w`` for the whole enclosing class; ``w = Watcher(...)``
    types local ``w`` for the enclosing function. An untypeable
    receiver is never flagged — this rule must not guess.
    """

    def __init__(
        self, path: str, guarded: dict[str, dict[str, str]],
        findings: list[Finding],
    ):
        self.path = path
        self.guarded = guarded
        self.findings = findings

    def scan_tree(self, tree: ast.AST) -> None:
        self._scan_scope(tree, cls_name=None, attr_types={}, qual="")

    def _scan_scope(self, node, cls_name, attr_types, qual) -> None:
        for item in ast.iter_child_nodes(node):
            if isinstance(item, ast.ClassDef):
                # type self.<x> from every construction site in the class
                types: dict[str, str] = {}
                for n in ast.walk(item):
                    if isinstance(n, (ast.Assign, ast.AnnAssign)):
                        targets = (
                            n.targets if isinstance(n, ast.Assign)
                            else [n.target]
                        )
                        cls = _ctor_class(n.value, self.guarded)
                        if cls is None:
                            continue
                        for t in targets:
                            attr = _self_attr(t)
                            if attr is not None:
                                types[attr] = cls
                self._scan_scope(
                    item, item.name, types,
                    f"{qual}.{item.name}" if qual else item.name,
                )
            elif isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_fn(
                    item, cls_name, attr_types,
                    f"{qual}.{item.name}" if qual else item.name,
                )
            else:
                self._scan_scope(item, cls_name, attr_types, qual)

    def _scan_fn(self, fn, cls_name, attr_types, qual) -> None:
        local_types: dict[str, str] = {}
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and (
                isinstance(n.targets[0], ast.Name)
            ):
                cls = _ctor_class(n.value, self.guarded)
                if cls is not None:
                    local_types[n.targets[0].id] = cls

        def recv_type(node) -> str | None:
            if isinstance(node, ast.Name):
                return local_types.get(node.id)
            attr = _self_attr(node)
            if attr is not None:
                return attr_types.get(attr)
            return None

        def held_locks(n, held):
            # `with other._lock:` legitimises reads of other's state
            out = set(held)
            for item in n.items:
                if isinstance(item.context_expr, ast.Attribute):
                    out.add(ast.dump(item.context_expr))
            return out

        def walk(node, held):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    nh = held_locks(child, held)
                    for item in child.items:
                        walk(item.context_expr, held)
                    for st in child.body:
                        walk(st, nh)
                    continue
                if isinstance(child, ast.Attribute) and isinstance(
                    child.ctx, ast.Load
                ):
                    self._check_attr(child, recv_type, cls_name, qual, held)
                walk(child, held)

        walk(fn, frozenset())

    def _check_attr(self, node, recv_type, cls_name, qual, held) -> None:
        owner = recv_type(node.value)
        if owner is None or owner == cls_name:
            return  # untypeable, or the class's own state (self-rules)
        lock = self.guarded.get(owner, {}).get(node.attr)
        if lock is None:
            return
        recv = ast.dump(
            ast.Attribute(value=node.value, attr=lock, ctx=ast.Load())
        )
        if recv in held:
            return  # read under `with <recv>.<lock>:`
        recv_txt = (
            node.value.id if isinstance(node.value, ast.Name)
            else f"self.{_self_attr(node.value)}"
        )
        self.findings.append(
            Finding(
                PASS, "unlocked-read", self.path, qual, node.attr,
                f"read of {recv_txt}.{node.attr} from outside {owner} "
                f"(declared guarded_by({lock!r})) with no lock held — "
                "use the owning class's locked accessor or take "
                f"{recv_txt}.{lock}",
                node.lineno,
            )
        )


def lint_source(
    src: str,
    path: str,
    guarded_classes: dict[str, dict[str, str]] | None = None,
) -> list[Finding]:
    """Per-file rules; when ``guarded_classes`` (the package-wide map
    from :func:`lint_paths`'s first phase) is given, the cross-class
    unlocked-read rule runs too."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                PASS, "syntax-error", path, "", "parse",
                f"file does not parse: {e}", e.lineno or 0,
            )
        ]
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        guard = _guard_map_from_class(node)
        lock_attrs = _lock_attrs_of_class(node, guard)
        if lock_attrs:
            findings.extend(_scan_bare_acquire(node, lock_attrs, path))
        if not guard:
            continue
        scan = _MethodScan(
            guard, node.name, path,
            mutable=frozenset(_mutable_attrs_of_class(node)),
        )
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan.scan(item)
        findings.extend(scan.findings)
    if guarded_classes:
        _ExternalReadScan(path, guarded_classes, findings).scan_tree(tree)
    return findings


def _iter_py(paths: list[str]):
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [
                d for d in dirnames if d not in ("__pycache__", ".git")
            ]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def lint_file(
    path: str,
    repo_root: str,
    guarded_classes: dict[str, dict[str, str]] | None = None,
) -> list[Finding]:
    rel = os.path.relpath(path, repo_root)
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), rel, guarded_classes)


def lint_paths(paths: list[str], repo_root: str) -> list[Finding]:
    # phase 1: the package-wide guarded-class map (cheap: one parse per
    # file, reused nowhere else — the rule must see classes defined in
    # files OUTSIDE the restricted roots too, so a `--paths serve/` run
    # still types `GenerationWatcher` correctly)
    guarded: dict[str, dict[str, str]] = {}
    files = list(_iter_py(paths))
    for fpath in files:
        try:
            with open(fpath, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=fpath)
        except SyntaxError:
            continue  # reported by phase 2
        for name, gm in _guarded_classes_in_tree(tree).items():
            guarded.setdefault(name, {}).update(gm)
    # phase 2: per-file rules + cross-class reads
    findings: list[Finding] = []
    for fpath in files:
        findings.extend(lint_file(fpath, repo_root, guarded))
    return findings
