"""Lock-discipline race lint for ``@guarded_by``-annotated classes.

The threaded host side of the stack (device prefetcher, native ring
loader, metrics registry, watchdog) shares mutable state between a
training thread, producer threads, signal handlers and teardown hooks.
An unguarded mutation there does not crash — it corrupts counters or
races a handle free. This pass makes the locking contract *checkable*:

    @guarded_by("_lock", "_value", "_count")
    class Counter:
        def inc(self):
            with self._lock:
                self._value += 1      # ok
        def peek(self):
            return self._value        # finding: unguarded-read

Rules:

- ``unguarded-read`` / ``unguarded-write`` — ``self.<attr>`` access for
  an annotated attr outside a lexical ``with self.<lock>:`` block.
- ``__init__`` is exempt: the object is not shared before construction
  completes. (``__del__`` is NOT exempt — finalizers run concurrently
  with everything.)
- A ``with self.<lock>:`` anywhere up the lexical statement chain
  satisfies the contract; multi-item ``with`` statements count each
  item. ``self.<lock>.acquire()`` does NOT count — the pass cannot see
  the matching release, and the codebase convention is ``with``.
- Functions nested inside a method are analyzed with an EMPTY lock set:
  a closure may escape the lock scope it was created in (handed to a
  thread/callback), so holding the lock at definition time proves
  nothing about call time. Baseline the finding if the closure provably
  never escapes.

The decorator itself lives in
:mod:`consensusml_tpu.analysis.annotations` and is a pure metadata
no-op at runtime.
"""

from __future__ import annotations

import ast
import os

from consensusml_tpu.analysis.findings import Finding

__all__ = ["lint_source", "lint_file", "lint_paths"]

PASS = "locks"


def _guard_map_from_class(cls: ast.ClassDef) -> dict[str, str]:
    """attr -> lock attr, from ``@guarded_by("lock", "a", "b")``
    decorators (string literals only — the annotation is a static
    contract, computed lock names defeat the point)."""
    gm: dict[str, str] = {}
    for deco in cls.decorator_list:
        if not isinstance(deco, ast.Call):
            continue
        name = (
            deco.func.attr
            if isinstance(deco.func, ast.Attribute)
            else getattr(deco.func, "id", None)
        )
        if name != "guarded_by" or not deco.args:
            continue
        vals = [
            a.value
            for a in deco.args
            if isinstance(a, ast.Constant) and isinstance(a.value, str)
        ]
        if len(vals) >= 2:
            lock, *attrs = vals
            for a in attrs:
                gm[a] = lock
    return gm


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _MethodScan:
    def __init__(self, guard: dict[str, str], cls_name: str, path: str):
        self.guard = guard
        self.cls_name = cls_name
        self.path = path
        self.findings: list[Finding] = []

    def scan(self, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> None:
        if fn.name == "__init__":
            return
        self._walk_stmts(fn.body, frozenset(), f"{self.cls_name}.{fn.name}")

    def _walk_stmts(self, stmts, held: frozenset[str], qual: str) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # closures escape lock scope: analyze with no locks held
                self._walk_stmts(
                    st.body, frozenset(), f"{qual}.<locals>.{st.name}"
                )
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                now = set(held)
                for item in st.items:
                    attr = _self_attr(item.context_expr)
                    if attr is not None:
                        now.add(attr)
                    self._scan_expr(item.context_expr, held, qual)
                    if item.optional_vars is not None:
                        self._scan_expr(item.optional_vars, held, qual)
                self._walk_stmts(st.body, frozenset(now), qual)
                continue
            # compound statements: scan their own expressions with the
            # current lock set, then their bodies
            for field in ("test", "iter", "value", "exc", "cause", "msg"):
                sub = getattr(st, field, None)
                if isinstance(sub, ast.AST):
                    self._scan_expr(sub, held, qual)
            if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                # the read side of `self.x += 1` is covered by the write
                # finding; st.value was scanned via the field loop above
                targets = (
                    st.targets
                    if isinstance(st, ast.Assign)
                    else [st.target]
                )
                for t in targets:
                    self._scan_target(t, held, qual)
            for body_field in ("body", "orelse", "finalbody"):
                sub = getattr(st, body_field, None)
                if isinstance(sub, list):
                    self._walk_stmts(sub, held, qual)
            for h in getattr(st, "handlers", []) or []:
                self._walk_stmts(h.body, held, qual)

    def _flag(self, attr: str, lock: str, write: bool, line: int, qual: str):
        rule = "unguarded-write" if write else "unguarded-read"
        verb = "write to" if write else "read of"
        self.findings.append(
            Finding(
                PASS, rule, self.path, qual, attr,
                f"{verb} self.{attr} outside `with self.{lock}:` "
                f"(declared guarded_by({lock!r}) on {self.cls_name})",
                line,
            )
        )

    def _scan_target(self, node: ast.AST, held: frozenset[str], qual: str):
        attr = _self_attr(node)
        if attr is not None:
            lock = self.guard.get(attr)
            if lock is not None and lock not in held:
                self._flag(attr, lock, True, node.lineno, qual)
            return
        for child in ast.iter_child_nodes(node):
            self._scan_target(child, held, qual)

    def _scan_expr(self, node: ast.AST, held: frozenset[str], qual: str):
        # a lambda is a closure like a nested def: it may escape the
        # lock scope it was written under (thread target, callback), so
        # its body is analyzed with NO locks held — and must be pruned
        # from the enclosing walk, not just skipped as a node
        if isinstance(node, ast.Lambda):
            self._scan_expr(
                node.body, frozenset(), f"{qual}.<locals>.<lambda>"
            )
            return
        attr = _self_attr(node)
        if attr is not None:
            lock = self.guard.get(attr)
            if lock is not None and lock not in held:
                write = isinstance(node.ctx, (ast.Store, ast.Del))
                self._flag(attr, lock, write, node.lineno, qual)
        for child in ast.iter_child_nodes(node):
            self._scan_expr(child, held, qual)


def lint_source(src: str, path: str) -> list[Finding]:
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                PASS, "syntax-error", path, "", "parse",
                f"file does not parse: {e}", e.lineno or 0,
            )
        ]
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        guard = _guard_map_from_class(node)
        if not guard:
            continue
        scan = _MethodScan(guard, node.name, path)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan.scan(item)
        findings.extend(scan.findings)
    return findings


def lint_file(path: str, repo_root: str) -> list[Finding]:
    rel = os.path.relpath(path, repo_root)
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), rel)


def lint_paths(paths: list[str], repo_root: str) -> list[Finding]:
    findings: list[Finding] = []
    for p in paths:
        if os.path.isfile(p):
            findings.extend(lint_file(p, repo_root))
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [
                d for d in dirnames if d not in ("__pycache__", ".git")
            ]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    findings.extend(
                        lint_file(os.path.join(dirpath, fn), repo_root)
                    )
    return findings
