"""Recording shims: prove the abstract models faithful to the code.

A model checker over a hand-written abstraction proves nothing about
the implementation unless the abstraction is tied back to it. The tie
here is *trace conformance*: thin recording subclasses wrap the real
classes, tests drive the REAL workloads through them (the PR 17
randomized pool churn, the lockdep preempt/hot-swap engine e2e), and
the recorded action sequences must replay as valid paths of the
abstract models via :func:`consensusml_tpu.analysis.model.replay` —
the same ``apply``/``invariant`` code the exhaustive search runs.

Replay is strictly harder than "the run didn't crash": every recorded
action must be *enabled* in the model at that point (a recorded
``extend`` must pop the block ids the model's LIFO free stack predicts,
a recorded ``admission`` must carry the continuation flag the model's
preempt/readmit accounting implies), and every intermediate state must
satisfy the invariants. A drift between model and code — a reordered
free list, a lost readmission — fails replay with the exact step.

Shims:

- :class:`RecordingPool` — :class:`~consensusml_tpu.serve.pool.blocks.
  BlockPool` subclass recording begin/extend/adopt/pin/unpin/shrink/
  release with concrete block ids (``alloc`` records via its begin +
  extend legs). :func:`pool_model_for` builds the matching
  :class:`~.protocol_models.PoolModel` at the pool's REAL geometry —
  replay needs no bounded state space, so real sizes are fine.
- :func:`request_trace_labels` — adapts the engine's own
  :class:`~consensusml_tpu.obs.requests.RequestTraceRegistry` event
  stream (submit / admission.defer / admission / prefill / decode /
  preempt / hotswap / complete) into request-model labels, merged
  across requests in timestamp order. No engine changes needed: the
  wide-event instrumentation IS the recording.
- :class:`RecordingMembership` — pin/advance/release over
  :class:`~consensusml_tpu.swarm.membership.MembershipController`,
  with pins mapped onto model round actors.
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional, Tuple

from .protocol_models import MembershipModel, PoolModel, RequestModel

__all__ = [
    "RecordingPool",
    "pool_model_for",
    "replay_pool_trace",
    "request_trace_labels",
    "request_model_for",
    "replay_request_registry",
    "RecordingMembership",
    "membership_model_for",
    "replay_membership_trace",
]


# ---------------------------------------------------------------------------
# BlockPool
# ---------------------------------------------------------------------------


def _make_recording_pool():
    """Build the RecordingPool class lazily: ``serve.pool`` imports
    numpy-adjacent machinery the analysis package must not pull at
    import time (cml-check runs in bare CI environments)."""
    from consensusml_tpu.serve.pool.blocks import BlockPool

    class RecordingPool(BlockPool):
        """BlockPool that appends one model label per mutation.

        ``alloc`` is begin + extend in the real pool and dispatches
        through the overridden legs, so the recording decomposes it the
        same way the model does."""

        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self.trace: List[Tuple[Any, ...]] = []

        def begin(self, slot: int) -> None:
            super().begin(slot)
            self.trace.append(("begin", slot))

        def extend(self, slot: int, n_blocks: int = 1):
            got = super().extend(slot, n_blocks)
            self.trace.append(("extend", slot, tuple(got)))
            return got

        def adopt(self, slot: int, blocks):
            got = super().adopt(slot, blocks)
            self.trace.append(("adopt", slot, tuple(got)))
            return got

        def pin(self, block: int) -> None:
            super().pin(block)
            self.trace.append(("pin", int(block)))

        def unpin(self, block: int) -> None:
            super().unpin(block)
            self.trace.append(("unpin", int(block)))

        def shrink(self, slot: int, keep_blocks: int):
            dropped = super().shrink(slot, keep_blocks)
            self.trace.append(("shrink", slot, int(keep_blocks)))
            return dropped

        def release(self, slot: int):
            owned = super().release(slot)
            self.trace.append(("release", slot))
            return owned

    return RecordingPool


class _LazyRecordingPool:
    """Constructor proxy: ``RecordingPool(...)`` builds the subclass on
    first use without importing serve.pool at module import."""

    _cls = None

    def __call__(self, *a, **kw):
        if _LazyRecordingPool._cls is None:
            _LazyRecordingPool._cls = _make_recording_pool()
        return _LazyRecordingPool._cls(*a, **kw)


RecordingPool = _LazyRecordingPool()


def pool_model_for(pool) -> PoolModel:
    """The abstract pool at the REAL pool's geometry (replay only —
    too many blocks for exhaustive search, which is fine: replay
    walks one path)."""
    return PoolModel(
        num_slots=pool.num_slots,
        usable_blocks=pool.num_blocks - 1,
        blocks_per_slot=pool.blocks_per_slot,
    )


def replay_pool_trace(pool) -> Any:
    """Replay a RecordingPool's trace through the abstract model;
    raises ``ConformanceError`` on the first divergent step. Returns
    the model's final state so tests can cross-check it against the
    real pool's."""
    from .model import replay

    return replay(pool_model_for(pool), pool.trace)


# ---------------------------------------------------------------------------
# request lifecycle (adapter over the engine's own trace registry)
# ---------------------------------------------------------------------------

_TERMINAL_OK = ("complete", "eos", "max_tokens", "length", "stop")


def request_trace_labels(
    traces: Iterable, n_slots: int
) -> Tuple[List[Tuple[Any, ...]], int]:
    """Convert RequestTrace event streams into one merged label
    sequence. Returns ``(labels, n_requests)``.

    Ordering: global sort by event timestamp, stable so each request's
    own event order is preserved; at equal timestamps, events that
    FREE resources (complete / preempt) sort before events that claim
    them, which resolves the only ambiguity a microsecond clock tie
    can introduce (slot hand-off).
    """
    traces = list(traces)
    rows: List[Tuple[float, int, int, Tuple[Any, ...]]] = []
    for i, tr in enumerate(traces):
        for k, ev in enumerate(tr.events):
            name = ev.get("name")
            ts = float(ev.get("ts_us", 0.0))
            if name == "submit":
                lab: Optional[Tuple[Any, ...]] = ("submit", i)
            elif name == "admission.defer":
                lab = ("defer", i)
            elif name == "admission":
                lab = (
                    "admit", i, int(ev["slot"]),
                    bool(ev.get("continuation", False)),
                )
            elif name == "prefill":
                lab = ("prefill", i)
            elif name == "decode":
                lab = ("tick", i)
            elif name == "preempt":
                lab = ("preempt", i)
            elif name == "hotswap":
                lab = ("observe_swap", i, int(ev.get("generation", 0)))
            elif name == "complete":
                reason = tr.finish_reason or "complete"
                lab = (
                    ("complete", i) if reason in _TERMINAL_OK
                    else ("cancel", i)
                )
            else:
                lab = None  # spec/accounting events carry no transition
            if lab is not None:
                frees = lab[0] in ("complete", "cancel", "preempt")
                rows.append((ts, 0 if frees else 1, k, lab))
    rows.sort(key=lambda r: (r[0], r[1]))
    return [r[3] for r in rows], len(traces)


def request_model_for(n_requests: int, n_slots: int) -> RequestModel:
    """Replay-mode request model: per-request targets and bounds are
    unknown in a recording, so guards relax to the structural protocol
    (``strict=False``) while generation monotonicity, slot aliasing,
    continuation accounting and lost-stream ghosts stay enforced."""
    return RequestModel(
        n_requests=n_requests, n_slots=n_slots, strict=False
    )


def replay_request_registry(registry, n_slots: int) -> Any:
    """Replay every completed request in an engine's trace registry
    through the abstract lifecycle model. Returns the final model
    state."""
    from .model import replay

    traces = [
        t for t in registry.completed()
        if t.finish_reason not in ("superseded", "truncated", "rejected")
    ]
    labels, n = request_trace_labels(traces, n_slots)
    return replay(request_model_for(n, n_slots), labels)


# ---------------------------------------------------------------------------
# membership epochs
# ---------------------------------------------------------------------------


def _make_recording_membership():
    from consensusml_tpu.swarm.membership import MembershipController

    class RecordingMembership(MembershipController):
        """MembershipController recording pin/advance/release as model
        labels. Pins map onto model round actors (lowest free index);
        ``advance`` records its internal gauge feed too — the real
        controller feeds metrics inside ``advance`` under the
        ``_fed_epoch`` claim, which is exactly the model's
        advance-then-feed pair."""

        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self.trace: List[Tuple[Any, ...]] = []
            self._actors: dict[int, int] = {}  # round actor -> pinned epoch
            self.max_rounds = 0
            self.n_advances = 0

        def pin(self):
            view = super().pin()
            a = 0
            while a in self._actors:
                a += 1
            self._actors[a] = view.epoch
            self.max_rounds = max(self.max_rounds, a + 1)
            self.trace.append(("pin", a))
            return view

        def release(self, view) -> None:
            super().release(view)
            # two pins of one epoch are interchangeable in the model;
            # any holder of view.epoch is a consistent attribution
            for a, e in self._actors.items():
                if e == view.epoch:
                    del self._actors[a]
                    self.trace.append(("complete", a))
                    break

        def advance(self):
            view = super().advance()
            self.n_advances += 1
            self.trace.append(("advance", 0))
            self.trace.append(("feed", 0))
            return view

    return RecordingMembership


class _LazyRecordingMembership:
    _cls = None

    def __call__(self, *a, **kw):
        if _LazyRecordingMembership._cls is None:
            _LazyRecordingMembership._cls = _make_recording_membership()
        return _LazyRecordingMembership._cls(*a, **kw)


RecordingMembership = _LazyRecordingMembership()


def membership_model_for(mc) -> MembershipModel:
    return MembershipModel(
        n_rounds=max(1, mc.max_rounds),
        n_advancers=1,
        max_epoch=max(1, mc.n_advances),
    )


def replay_membership_trace(mc) -> Any:
    from .model import replay

    return replay(membership_model_for(mc), mc.trace)
