"""Static lock-ordering graph: ABBA deadlocks found before they hang.

The ``locks`` pass proves each annotated class guards its own state; it
says nothing about ORDER. Two threads that take the same two locks in
opposite orders deadlock — the hot-swap watcher's ``_lock`` against a
metric's lock, a registry export against an updater — and that failure
is unreachable by tests (it needs a precise interleaving) but fully
decidable from the source: every acquisition in this codebase is a
lexical ``with self.<lock>:`` (the ``bare-acquire`` rule makes that an
invariant, not a convention).

This pass builds the package-wide **lock graph**:

- **nodes** — ``ClassName._lockattr`` (plus ``<module>.<name>`` for
  module-level locks), with the lock KIND (``Lock`` / ``RLock``) read
  off its construction site;
- **edges** — ``A -> B`` when code can acquire B while holding A:
  directly (a ``with self.B:`` nested inside ``with self.A:``), through
  the same-class/same-module call graph (holding A, calling a method
  that acquires B — composed to a fixpoint, the same machinery shape as
  ``host_sync``'s traced-ness propagation), and across classes through
  attribute construction sites (``self._w = GenerationWatcher(...)`` in
  ``__init__`` types ``self._w``, so ``self._w.take()`` under a lock
  contributes the watcher's acquisitions) plus the metric-registry
  factory idiom (``reg.counter(...)`` returns a ``Counter``, etc.).

Findings:

- ``lock-cycle`` — a cycle in the graph: some interleaving of the
  participating code paths can deadlock. The finding's detail is the
  canonical cycle string, so the id is stable; the message carries one
  witness code path per edge.
- ``self-deadlock`` — a self-edge on a NON-reentrant ``threading.Lock``:
  the thread wedges against itself on the first execution of that path,
  no interleaving needed. Re-entry on an ``RLock`` is modeled as an
  exempt self-loop (e.g. ``RequestTraceRegistry._finish_locked`` re-
  entering under the registry's signal-dump RLock).

The analysis under-approximates like every AST pass here (cross-module
calls resolve only through typed attributes and known factories;
dynamic dispatch is invisible) — it flags what it can prove. The graph
it builds is also the STATIC MODEL the runtime sanitizer
(:mod:`~consensusml_tpu.analysis.lockdep`) checks observed acquisition
orders against: an observed edge between package locks that static
analysis never predicted means the model (or the code) needs a look.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable

from consensusml_tpu.analysis.findings import Finding
from consensusml_tpu.analysis.locks import _self_attr

__all__ = [
    "LockModel",
    "analyze_sources",
    "analyze_paths",
    "static_model",
    "check_repo",
    "PASS",
]

PASS = "lockorder"

# factory METHODS whose return type we know (the metrics-registry idiom:
# self._m = reg.counter(...) hands back a Counter with its own lock)
_FACTORY_METHODS = {
    "counter": "Counter",
    "gauge": "Gauge",
    "histogram": "Histogram",
}
# module-level factory FUNCTIONS with known return types
_FACTORY_FUNCS = {
    "get_registry": "MetricsRegistry",
    "get_request_registry": "RequestTraceRegistry",
    "get_tracer": "SpanTracer",
    "get_cost_ledger": "CostLedger",
    "GenerationWatcher": "GenerationWatcher",
}


def _lock_ctor_kind(value: ast.AST) -> str | None:
    """'Lock'/'RLock' when ``value`` constructs one, else None."""
    if not isinstance(value, ast.Call):
        return None
    seg = (
        value.func.attr
        if isinstance(value.func, ast.Attribute)
        else getattr(value.func, "id", None)
    )
    return seg if seg in ("Lock", "RLock") else None


def _value_type(value: ast.AST | None) -> str | None:
    """Bare class name an assigned value constructs, when decidable:
    direct constructor calls, the known registry factories, and the
    ``x if x is not None else get_registry()`` default idiom (either
    branch resolving wins — both branches yield the same type in every
    in-tree use of the pattern)."""
    if isinstance(value, ast.IfExp):
        return _value_type(value.body) or _value_type(value.orelse)
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    seg = (
        f.attr if isinstance(f, ast.Attribute) else getattr(f, "id", None)
    )
    if seg in _FACTORY_METHODS:
        return _FACTORY_METHODS[seg]
    if seg in _FACTORY_FUNCS:
        return _FACTORY_FUNCS[seg]
    if seg and seg[:1].isupper():
        # looks like a constructor: resolved against the package-wide
        # class index at model-build time
        return seg
    return None


class _Func:
    """One function/method body's lock-relevant events."""

    __slots__ = ("qual", "line", "events")

    def __init__(self, qual: str, line: int):
        self.qual = qual
        self.line = line
        # (held tokens tuple, kind, payload, line):
        #   kind "acquire": payload = token  (("self", attr)|("mod", name))
        #   kind "call":    payload = callref
        #     ("self", meth) | ("attr", attr, meth) | ("mod", name)
        self.events: list[tuple[tuple, str, tuple, int]] = []


class _Class:
    __slots__ = (
        "name", "path", "line", "lock_kinds", "attr_types", "methods"
    )

    def __init__(self, name: str, path: str, line: int):
        self.name = name
        self.path = path
        self.line = line
        self.lock_kinds: dict[str, str] = {}  # lock attr -> Lock|RLock
        self.attr_types: dict[str, str] = {}  # attr -> class bare name
        self.methods: dict[str, _Func] = {}


class _Module:
    __slots__ = ("path", "lock_kinds", "functions", "classes")

    def __init__(self, path: str):
        self.path = path
        self.lock_kinds: dict[str, str] = {}  # module-level lock name -> kind
        self.functions: dict[str, _Func] = {}
        self.classes: list[_Class] = []


def _scan_body(
    fn: ast.AST,
    qual: str,
    module_locks: dict[str, str],
    out: _Func,
) -> None:
    """Collect acquire/call events with the lexically-held lock set.
    Nested functions/lambdas are skipped entirely: a closure's run-time
    lock context is unknown (same reasoning as the locks pass)."""

    def scan_expr(node: ast.AST, held: tuple):
        if isinstance(
            node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            return
        if isinstance(node, ast.Call):
            ref = _callref(node.func)
            if ref is not None:
                out.events.append((held, "call", ref, node.lineno))
        for child in ast.iter_child_nodes(node):
            scan_expr(child, held)

    def walk(stmts, held: tuple):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                now = list(held)
                for item in st.items:
                    scan_expr(item.context_expr, tuple(now))
                    tok = None
                    attr = _self_attr(item.context_expr)
                    if attr is not None:
                        tok = ("self", attr)
                    elif (
                        isinstance(item.context_expr, ast.Name)
                        and item.context_expr.id in module_locks
                    ):
                        tok = ("mod", item.context_expr.id)
                    if tok is not None:
                        out.events.append(
                            (tuple(now), "acquire", tok, st.lineno)
                        )
                        now.append(tok)
                walk(st.body, tuple(now))
                continue
            # this statement's own expressions (calls live here); bodies
            # of compound statements recurse below with the same held set
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    scan_expr(child, held)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(st, field, None)
                if isinstance(sub, list):
                    walk(sub, held)
            for h in getattr(st, "handlers", []) or []:
                walk(h.body, held)

    body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
    walk(body, ())


def _callref(func: ast.AST):
    if isinstance(func, ast.Attribute):
        base = func.value
        if isinstance(base, ast.Name):
            if base.id == "self":
                return ("self", func.attr)
            return ("mod", f"{base.id}.{func.attr}")  # unresolved dotted
        attr = _self_attr(base)
        if attr is not None:
            return ("attr", attr, func.attr)
        return None
    if isinstance(func, ast.Name):
        return ("mod", func.id)
    return None


def _scan_module(src: str, rel: str) -> _Module | None:
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError:
        return None
    mod = _Module(rel)
    # module-level locks first (withs in functions reference them)
    for st in tree.body:
        if isinstance(st, ast.Assign):
            kind = _lock_ctor_kind(st.value)
            if kind:
                for t in st.targets:
                    if isinstance(t, ast.Name):
                        mod.lock_kinds[t.id] = kind

    def scan_class(cls: ast.ClassDef):
        ci = _Class(cls.name, rel, cls.lineno)
        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                kind = _lock_ctor_kind(node.value)
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if kind:
                        ci.lock_kinds[attr] = kind
                    else:
                        tname = _value_type(node.value)
                        if tname is not None:
                            ci.attr_types[attr] = tname
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                f = _Func(f"{cls.name}.{item.name}", item.lineno)
                _scan_body(item, f.qual, mod.lock_kinds, f)
                ci.methods[item.name] = f
        mod.classes.append(ci)

    for st in tree.body:
        if isinstance(st, ast.ClassDef):
            scan_class(st)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            f = _Func(st.name, st.lineno)
            _scan_body(st, st.name, mod.lock_kinds, f)
            mod.functions[st.name] = f
    return mod


class LockModel:
    """The package lock graph + the finding computation over it."""

    def __init__(self):
        self.kinds: dict[str, str] = {}  # node -> Lock|RLock|"?"
        # (a, b) -> list of (path, line, witness description)
        self.edges: dict[tuple[str, str], list[tuple[str, int, str]]] = {}
        # exempt RLock re-entries, kept for reporting/debug
        self.reentries: dict[str, list[tuple[str, int, str]]] = {}

    def add_edge(self, a: str, b: str, path: str, line: int, why: str):
        self.edges.setdefault((a, b), []).append((path, line, why))

    def edge_set(self) -> set[tuple[str, str]]:
        return set(self.edges)

    def has_edge(self, a: str, b: str) -> bool:
        return (a, b) in self.edges

    # -- findings ---------------------------------------------------------
    def findings(self) -> list[Finding]:
        out: list[Finding] = []
        for (a, b), wit in sorted(self.edges.items()):
            if a == b and self.kinds.get(a) == "Lock":
                path, line, why = wit[0]
                out.append(
                    Finding(
                        PASS, "self-deadlock", path, why.split(" ")[0], a,
                        f"non-reentrant lock {a} is re-acquired while "
                        f"already held ({why}) — this thread deadlocks "
                        "against itself; use an RLock or restructure",
                        line,
                    )
                )
        for cyc in self._cycles():
            wit = self.edges[(cyc[0], cyc[1])][0]
            detail = "->".join(cyc)
            paths = "; ".join(
                f"{a}->{b} via {self.edges[(a, b)][0][2]} "
                f"({self.edges[(a, b)][0][0]}:{self.edges[(a, b)][0][1]})"
                for a, b in zip(cyc, cyc[1:])
            )
            out.append(
                Finding(
                    PASS, "lock-cycle", wit[0], "<graph>", detail,
                    f"lock-order cycle {detail}: two threads taking "
                    "these locks in opposite orders deadlock. Witness "
                    f"paths: {paths}. Fix the ordering or split the "
                    "critical sections",
                    wit[1],
                )
            )
        return out

    def _cycles(self) -> list[list[str]]:
        """Each multi-node SCC reduced to one canonical witness cycle
        (stable detail strings; self-loops handled separately)."""
        graph: dict[str, set[str]] = {}
        for a, b in self.edges:
            if a != b:
                graph.setdefault(a, set()).add(b)
                graph.setdefault(b, set())
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on: set[str] = set()
        stack: list[str] = []
        sccs: list[list[str]] = []
        counter = [0]

        def strongconnect(v: str):
            # iterative Tarjan (deep graphs must not blow recursion)
            work = [(v, iter(sorted(graph.get(v, ()))))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append((w, iter(sorted(graph.get(w, ())))))
                        advanced = True
                        break
                    elif w in on:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    low[work[-1][0]] = min(low[work[-1][0]], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        sccs.append(scc)

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)

        cycles: list[list[str]] = []
        for scc in sccs:
            # one witness cycle: BFS from the lexicographically-smallest
            # node back to itself WITHIN the SCC (a greedy min-successor
            # walk can dead-end on branchy SCCs; BFS cannot — strong
            # connectivity guarantees a path back)
            start = min(scc)
            scc_set = set(scc)
            parent: dict[str, str] = {}
            seen = {start}
            frontier = [start]
            end = None
            while frontier and end is None:
                nxt_frontier: list[str] = []
                for node in frontier:
                    for w in sorted(graph[node]):
                        if w == start:
                            end = node
                            break
                        if w in scc_set and w not in seen:
                            seen.add(w)
                            parent[w] = node
                            nxt_frontier.append(w)
                    if end is not None:
                        break
                frontier = nxt_frontier
            if end is None:  # pragma: no cover - SCC guarantees a cycle
                continue
            back = [end]
            while back[-1] != start:
                back.append(parent[back[-1]])
            cycles.append(list(reversed(back)) + [start])
        return cycles


def _build_model(modules: list[_Module]) -> LockModel:
    model = LockModel()
    # bare-name class index for typed-attribute resolution. A name
    # defined in TWO modules is ambiguous — drop it entirely rather
    # than resolve calls against whichever definition was scanned
    # first (a wrong-class resolution could both fabricate and MISS
    # edges; conservative under-approximation is the pass's contract).
    class_index: dict[str, _Class] = {}
    ambiguous: set[str] = set()
    for m in modules:
        for ci in m.classes:
            if ci.name in class_index and class_index[ci.name].path != ci.path:
                ambiguous.add(ci.name)
            class_index.setdefault(ci.name, ci)
        base = os.path.splitext(os.path.basename(m.path))[0]
        for name, kind in m.lock_kinds.items():
            model.kinds[f"{base}.{name}"] = kind
    for name in ambiguous:
        class_index.pop(name, None)

    def node_of(tok: tuple, ci: _Class | None, mod: _Module) -> str | None:
        if tok[0] == "self":
            if ci is None:
                return None
            name = f"{ci.name}.{tok[1]}"
            model.kinds.setdefault(name, ci.lock_kinds.get(tok[1], "?"))
            return name
        base = os.path.splitext(os.path.basename(mod.path))[0]
        return f"{base}.{tok[1]}"

    # -- fixpoint: may-acquire set per (class-or-module, func) ------------
    # key: (id(ci) or module path, method name)
    may: dict[tuple, set[str]] = {}

    def key_of(ci, mod, name):
        # (path, class) so same-named classes in different modules keep
        # separate may-acquire sets (node names still collide by class
        # name, but ambiguous names are dropped from resolution above)
        return ((ci.path, ci.name) if ci is not None else mod.path, name)

    def resolve_call(ref, ci: _Class | None, mod: _Module):
        """-> (callee _Func, callee ci, callee mod) or None."""
        if ref[0] == "self" and ci is not None:
            f = ci.methods.get(ref[1])
            if f is not None:
                return f, ci, mod
            return None
        if ref[0] == "attr" and ci is not None:
            tname = ci.attr_types.get(ref[1])
            if tname is None:
                return None
            target = class_index.get(tname)
            if target is None:
                return None
            f = target.methods.get(ref[2])
            if f is None:
                return None
            tmod = next(
                (mm for mm in modules if mm.path == target.path), mod
            )
            return f, target, tmod
        if ref[0] == "mod":
            f = mod.functions.get(ref[1])
            if f is not None:
                return f, None, mod
        return None

    all_funcs: list[tuple[_Func, _Class | None, _Module]] = []
    for m in modules:
        for f in m.functions.values():
            all_funcs.append((f, None, m))
        for ci in m.classes:
            for f in ci.methods.values():
                all_funcs.append((f, ci, m))

    for f, ci, m in all_funcs:
        k = key_of(ci, m, f.qual.split(".")[-1])
        may[k] = set()
        for _held, kind, payload, _line in f.events:
            if kind == "acquire":
                n = node_of(payload, ci, m)
                if n is not None:
                    may[k].add(n)

    changed = True
    while changed:
        changed = False
        for f, ci, m in all_funcs:
            k = key_of(ci, m, f.qual.split(".")[-1])
            for _held, kind, payload, _line in f.events:
                if kind != "call":
                    continue
                r = resolve_call(payload, ci, m)
                if r is None:
                    continue
                cf, cci, cm = r
                ck = key_of(cci, cm, cf.qual.split(".")[-1])
                extra = may.get(ck, set()) - may[k]
                if extra:
                    may[k] |= extra
                    changed = True

    # -- edges ------------------------------------------------------------
    for f, ci, m in all_funcs:
        for held, kind, payload, line in f.events:
            held_nodes = [
                n for n in (node_of(t, ci, m) for t in held) if n is not None
            ]
            if not held_nodes:
                continue
            if kind == "acquire":
                n = node_of(payload, ci, m)
                if n is None:
                    continue
                for h in held_nodes:
                    if h == n and model.kinds.get(n) == "RLock":
                        model.reentries.setdefault(n, []).append(
                            (m.path, line, f.qual)
                        )
                        continue
                    model.add_edge(
                        h, n, m.path, line,
                        f"{f.qual} holds {h} and acquires {n}",
                    )
            else:
                r = resolve_call(payload, ci, m)
                if r is None:
                    continue
                cf, cci, cm = r
                ck = key_of(cci, cm, cf.qual.split(".")[-1])
                for n in sorted(may.get(ck, ())):
                    for h in held_nodes:
                        if h == n and model.kinds.get(n) == "RLock":
                            model.reentries.setdefault(n, []).append(
                                (m.path, line, f"{f.qual} -> {cf.qual}")
                            )
                            continue
                        model.add_edge(
                            h, n, m.path, line,
                            f"{f.qual} holds {h}, calls {cf.qual} "
                            f"which acquires {n}",
                        )
    return model


def analyze_sources(sources: Iterable[tuple[str, str]]) -> LockModel:
    """Build a model from ``(repo-relative path, source)`` pairs —
    the test-fixture entry point."""
    modules = []
    for rel, src in sources:
        m = _scan_module(src, rel)
        if m is not None:
            modules.append(m)
    return _build_model(modules)


def analyze_paths(paths: list[str], repo_root: str) -> LockModel:
    sources: list[tuple[str, str]] = []
    for p in paths:
        files = []
        if os.path.isfile(p):
            files = [p]
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [
                    d for d in dirnames if d not in ("__pycache__", ".git")
                ]
                files.extend(
                    os.path.join(dirpath, fn)
                    for fn in sorted(filenames)
                    if fn.endswith(".py")
                )
        for path in files:
            rel = os.path.relpath(os.path.abspath(path), repo_root)
            try:
                with open(path, encoding="utf-8") as f:
                    sources.append((rel, f.read()))
            except OSError:
                continue
    return analyze_sources(sources)


def static_model(repo_root: str) -> LockModel:
    """The package-wide graph — also the reference model
    :mod:`~consensusml_tpu.analysis.lockdep` validates runtime
    acquisition orders against."""
    pkg = os.path.join(repo_root, "consensusml_tpu")
    return analyze_paths([pkg], repo_root)


def check_paths(paths: list[str], repo_root: str) -> list[Finding]:
    return analyze_paths(paths, repo_root).findings()


def check_repo(repo_root: str) -> list[Finding]:
    """CLI entry (tools/cml_check.py --lockorder)."""
    return static_model(repo_root).findings()
