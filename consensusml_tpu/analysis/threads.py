"""Thread-and-handler inventory pass: the concurrency surface is checked in.

The host side of the stack is threaded on purpose — engine loop,
hot-swap watcher, socket front-end, metrics HTTP server, prefetcher,
async checkpoint writer, watchdog — and hooked into process-global
machinery (``signal.signal``, ``threading.excepthook``/``sys.excepthook``
chains). Every one of those is a concurrency obligation: someone must
know it exists, what state it touches, whether it is a daemon, and who
joins it on shutdown. Nothing enforced that before this pass; a new
thread or signal handler landed silently.

``docs/threads.md`` is the checked-in inventory (same contract shape as
``docs/observability.md`` for metric families). This pass AST-collects
every

- ``threading.Thread(...)`` construction site,
- ``signal.signal(...)`` registration site,
- ``threading.excepthook`` / ``sys.excepthook`` assignment site,

across the package + CLI entry points and cross-checks them against the
inventory:

- ``undocumented-thread`` / ``undocumented-handler`` — a site the
  inventory does not list (a new thread/handler must be documented to
  land);
- ``stale-thread-doc`` — an inventory row no code site backs any more;
- ``daemon-mismatch`` — the site's literal ``daemon=`` disagrees with
  the inventory's daemon column (a daemon thread silently dying at
  interpreter exit vs a non-daemon thread blocking it is a shutdown
  contract, not a detail);
- ``unannotated-thread-state`` — a class that spawns a thread AND owns
  a ``threading.Lock``/``RLock`` attribute but carries no
  ``@guarded_by`` annotation: the lock exists, so the class KNOWS its
  state is shared, but the contract is invisible to the lock lint
  (:mod:`~consensusml_tpu.analysis.locks`).

An inventory row's key is exactly the tail of the site's finding id —
``path:symbol:detail`` — so the doc and the findings never drift in
format. Baseline mechanics are shared with every other pass
(``.cml-check-baseline``, stale entries reported).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable

from consensusml_tpu.analysis.findings import Finding
from consensusml_tpu.analysis.locks import (
    _guard_map_from_class,
    _lock_attrs_of_class,
)

__all__ = [
    "collect_sites",
    "documented_sites",
    "run",
    "check_repo",
    "Site",
]

PASS = "threads"
DOC_RELPATH = os.path.join("docs", "threads.md")

# a doc key is `path.py:Sym.bol:detail` in backticks; details may hold
# dots/underscores/dashes and call parens (thread names,
# `self._serve_conn` targets, `functools.partial(...)`-style call
# tokens, SIGTERM, threading.excepthook)
_KEY_RE = re.compile(r"`([\w/.\-]+\.py:[\w.<>-]+:[\w.<>()\- ]+)`")


class Site:
    """One collected concurrency site."""

    __slots__ = ("kind", "path", "symbol", "detail", "line", "daemon")

    def __init__(self, kind, path, symbol, detail, line, daemon=None):
        self.kind = kind  # "thread" | "signal" | "excepthook"
        self.path = path
        self.symbol = symbol
        self.detail = detail
        self.line = line
        self.daemon = daemon  # True/False when a literal, else None

    @property
    def key(self) -> str:
        return f"{self.path}:{self.symbol or '<module>'}:{self.detail}"


def _last_segment(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _token(node: ast.AST) -> str:
    """A short stable token for a target expression: ``self._run`` /
    ``self._httpd.serve_forever`` / ``write``."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_token(node.value)}.{node.attr}"
    if isinstance(node, ast.Lambda):
        return "<lambda>"
    if isinstance(node, ast.Call):
        return f"{_token(node.func)}(...)"
    return "<expr>"


def _collect_file(path: str, rel: str) -> tuple[list[Site], list[Finding]]:
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return [], []

    sites: list[Site] = []
    findings: list[Finding] = []
    stack: list[str] = []

    def symbol() -> str:
        return ".".join(stack)

    def visit(node: ast.AST) -> None:
        scoped = isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        )
        if scoped:
            stack.append(node.name)
        if isinstance(node, ast.ClassDef):
            _scan_class_thread_state(node, rel, symbol(), findings)
        if isinstance(node, ast.Call):
            seg = _last_segment(node.func)
            if seg == "Thread":
                name = daemon = None
                target = "<unnamed>"
                for kw in node.keywords:
                    if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                        name = kw.value.value
                    elif kw.arg == "daemon" and isinstance(
                        kw.value, ast.Constant
                    ):
                        daemon = bool(kw.value.value)
                    elif kw.arg == "target":
                        target = _token(kw.value)
                detail = str(name) if name is not None else target
                sites.append(
                    Site("thread", rel, symbol(), detail, node.lineno, daemon)
                )
            elif (
                seg == "signal"
                and isinstance(node.func, ast.Attribute)
                and _last_segment(node.func.value) == "signal"
                and node.args
            ):
                sig = _last_segment(node.args[0]) or "dynamic"
                sites.append(
                    Site("signal", rel, symbol(), sig, node.lineno)
                )
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr == "excepthook"
                    and _last_segment(t.value) in ("threading", "sys")
                ):
                    sites.append(
                        Site(
                            "excepthook", rel, symbol(),
                            f"{_last_segment(t.value)}.excepthook",
                            node.lineno,
                        )
                    )
        for child in ast.iter_child_nodes(node):
            visit(child)
        if scoped:
            stack.pop()

    visit(tree)
    return sites, findings


def _scan_class_thread_state(
    cls: ast.ClassDef, rel: str, qual: str, findings: list[Finding]
) -> None:
    """A thread-spawning class with locks but no @guarded_by: the
    sharing is real (the lock proves it) but the contract is undeclared,
    so the lock lint guards nothing."""
    spawns = any(
        isinstance(n, ast.Call) and _last_segment(n.func) == "Thread"
        for n in ast.walk(cls)
    )
    if not spawns:
        return
    guard = _guard_map_from_class(cls)
    if guard:
        return  # annotated: the lock lint owns it from here
    lock_attrs = _lock_attrs_of_class(cls, guard)
    if not lock_attrs:
        return  # stateless spawner (events/queues only): nothing to guard
    findings.append(
        Finding(
            PASS, "unannotated-thread-state", rel, qual,
            ",".join(sorted(lock_attrs)),
            f"class {cls.name} spawns a thread and owns lock(s) "
            f"{sorted(lock_attrs)} but has no @guarded_by annotation — "
            "declare the lock contract so the locks pass can enforce it",
            cls.lineno,
        )
    )


def documented_sites(doc_path: str) -> dict[str, dict]:
    """Inventory rows keyed by ``path:symbol:detail``. The daemon column
    (2nd cell) is honored when it is ``yes``/``no``; anything else
    (including ``-`` for handlers) skips the daemon check."""
    try:
        with open(doc_path, encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        return {}
    out: dict[str, dict] = {}
    for line in lines:
        if not line.lstrip().startswith("|"):
            continue
        m = _KEY_RE.search(line)
        if not m:
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        daemon = None
        if len(cells) >= 2 and cells[1].lower() in ("yes", "no"):
            daemon = cells[1].lower() == "yes"
        out[m.group(1)] = {"daemon": daemon}
    return out


def default_sources(repo_root: str) -> list[str]:
    from consensusml_tpu.analysis import docs_drift

    return docs_drift.default_sources(repo_root)


def collect_sites(
    py_files: Iterable[str], repo_root: str
) -> tuple[list[Site], list[Finding]]:
    sites: list[Site] = []
    findings: list[Finding] = []
    for path in sorted(py_files):
        rel = os.path.relpath(os.path.abspath(path), repo_root)
        s, f = _collect_file(path, rel)
        sites.extend(s)
        findings.extend(f)
    return sites, findings


def run(
    repo_root: str,
    py_files: Iterable[str] | None = None,
    doc_path: str | None = None,
    report_stale: bool | None = None,
) -> list[Finding]:
    """``report_stale`` defaults to True only when the FULL default
    source set is scanned — a ``--paths``-restricted run cannot tell a
    stale row from a row whose site it simply did not scan."""
    files = (
        list(py_files) if py_files is not None else default_sources(repo_root)
    )
    if report_stale is None:
        report_stale = py_files is None
    doc = (
        doc_path if doc_path is not None
        else os.path.join(repo_root, DOC_RELPATH)
    )
    doc_rel = os.path.relpath(os.path.abspath(doc), repo_root)
    sites, findings = collect_sites(files, repo_root)
    documented = documented_sites(doc)

    seen_keys: set[str] = set()
    for s in sites:
        seen_keys.add(s.key)
        row = documented.get(s.key)
        if row is None:
            rule = (
                "undocumented-thread"
                if s.kind == "thread"
                else "undocumented-handler"
            )
            what = {
                "thread": "thread spawn",
                "signal": "signal handler registration",
                "excepthook": "excepthook chain",
            }[s.kind]
            findings.append(
                Finding(
                    PASS, rule, s.path, s.symbol, s.detail,
                    f"{what} ({s.detail}) is not in the {doc_rel} "
                    "inventory — document it (daemon/join discipline + "
                    "purpose) or remove it",
                    s.line,
                )
            )
            continue
        if (
            s.kind == "thread"
            and row["daemon"] is not None
            and s.daemon is not None
            and s.daemon != row["daemon"]
        ):
            findings.append(
                Finding(
                    PASS, "daemon-mismatch", s.path, s.symbol, s.detail,
                    f"thread {s.detail!r} is daemon={s.daemon} in code "
                    f"but the {doc_rel} inventory says "
                    f"daemon={row['daemon']} — shutdown discipline "
                    "drifted; fix whichever side is wrong",
                    s.line,
                )
            )
    for key in sorted(set(documented) - seen_keys) if report_stale else []:
        findings.append(
            Finding(
                PASS, "stale-thread-doc", doc_rel, "<doc>", key,
                f"{doc_rel} lists {key!r} but no code site matches — "
                "prune the row or restore the thread/handler",
                0,
            )
        )
    return findings


def check_repo(repo_root: str) -> list[Finding]:
    """CLI entry (tools/cml_check.py --threads)."""
    return run(repo_root)
