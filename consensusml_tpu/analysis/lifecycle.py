"""Resource-lifecycle escape lint (cml-check pass 9).

The static complement to ``BlockPool.check()``: ``check()`` proves the
partition invariant on the states a run happened to visit; this pass
proves, per call site, that every resource ACQUISITION is covered by a
matching release on all forward paths *including exception edges* — the
path class runtime checks see last (an exception between acquire and
release leaks silently until pool pressure turns it into mysterious
``NoFreeBlocks`` deferrals).

Three resource families:

- **pool** — ``alloc``/``extend``/``begin``/``adopt``/``pin`` on any
  receiver whose name ends in a pool (``self._pool``, ``pool``), paired
  with ``release``/``shrink`` (``unpin`` for ``pin``).
- **slot** — ``occupy`` on slot tables, paired with ``release``/``free``.
- **handle** — ``open(...)`` / ``socket.socket(...)`` bound to a local,
  paired with ``.close()`` (a ``with`` block or ownership transfer —
  returning the handle, storing it on ``self``, passing it on — also
  discharges the obligation).

An acquisition is COVERED when one of these holds on the forward
continuation (the statements after it, walking out through enclosing
blocks to the function end; loop back-edges ignored):

1. it is lexically inside a ``with`` whose context manager is the
   resource itself;
2. it is inside a ``try`` whose handler or ``finally`` performs a
   matching release on the same receiver (the engine's
   ``except BaseException: pool.release(idx); raise`` admission guard);
3. the continuation reaches a matching release — or a protecting
   ``try`` as in (2) — before any statement that can raise (any call,
   ``raise``, ``assert``); plain data moves between acquire and release
   are fine;
4. the continuation reaches the function end with no risky statement
   at all (the acquire escapes as owned state — e.g. ``extend`` as the
   last action of a grow step, ownership parked in the pool's own
   accounting).

Like every detector pass (PR 15 pattern), the pass first lints a
seeded leak-on-exception fixture and declares ITSELF broken if the
fixture does not produce a finding.
"""

from __future__ import annotations

import ast
import os

from .findings import Finding

__all__ = ["lint_source", "lint_file", "lint_paths", "run_pass", "PASS"]

PASS = "lifecycle"

# acquire method -> releases that discharge it, per receiver family
_POOL_ACQ = {
    "alloc": ("release", "shrink"),
    "begin": ("release", "shrink"),
    "extend": ("release", "shrink"),
    "adopt": ("release", "shrink"),
    "pin": ("unpin",),
}
_SLOT_ACQ = {"occupy": ("release", "free")}
_POOL_SUFFIXES = ("pool",)
_SLOT_SUFFIXES = ("table", "slots")
_HANDLE_CALLS = {
    "open",
    "socket.socket",
    "socket.create_connection",
    "socket.create_server",
}


def _expr_text(node) -> str | None:
    """Dotted text of a Name/Attribute chain; None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _recv_family(recv: str) -> str | None:
    last = recv.rsplit(".", 1)[-1].lower()
    if any(last.endswith(s) or s in last for s in _POOL_SUFFIXES):
        return "pool"
    if any(last.endswith(s) for s in _SLOT_SUFFIXES):
        return "slot"
    return None


def _acquires_in(node):
    """Yield ``(call, family, method, receiver_text, releases)`` for
    every pool/slot acquire call in ``node``'s expression subtree."""
    for call in ast.walk(node):
        if not isinstance(call, ast.Call):
            continue
        fn = call.func
        if not isinstance(fn, ast.Attribute):
            continue
        recv = _expr_text(fn.value)
        if recv is None:
            continue
        fam = _recv_family(recv)
        if fam == "pool" and fn.attr in _POOL_ACQ:
            yield call, fam, fn.attr, recv, _POOL_ACQ[fn.attr]
        elif fam == "slot" and fn.attr in _SLOT_ACQ:
            yield call, fam, fn.attr, recv, _SLOT_ACQ[fn.attr]


def _is_release(node, recv: str, releases) -> bool:
    """Does ``node``'s subtree contain ``<recv>.<release>(...)``? A
    conditional release in the continuation counts — the lint flags
    exception-edge leaks, not control-flow conservatism."""
    for call in ast.walk(node):
        if (
            isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr in releases
            and _expr_text(call.func.value) == recv
        ):
            return True
    return False


def _protective_try(st, recv: str, releases) -> bool:
    """A ``try`` whose handlers or ``finally`` release the resource."""
    if not isinstance(st, ast.Try):
        return False
    for h in st.handlers:
        for s in h.body:
            if _is_release(s, recv, releases):
                return True
    for s in st.finalbody:
        if _is_release(s, recv, releases):
            return True
    return False


def _risky(st) -> bool:
    """Can this statement raise between acquire and release? Any call,
    explicit raise, or assert. Plain data moves (constant/name binds,
    ``pass``, bare ``return``) cannot."""
    for n in ast.walk(st):
        if isinstance(n, (ast.Call, ast.Raise, ast.Assert)):
            return True
    return False


class _FuncScan:
    """Scan one function: every acquire must be covered per the module
    docstring's rules 1–4."""

    def __init__(self, path: str, symbol: str, findings: list):
        self.path = path
        self.symbol = symbol
        self.findings = findings

    # continuation = list of statement-lists: the statements after the
    # current one in its block, then after the enclosing statement in
    # ITS block, ... out to the function body's tail.
    def scan(self, fn) -> None:
        self._block(fn.body, conts=[], prots=[])

    def _block(self, stmts, conts, prots) -> None:
        for i, st in enumerate(stmts):
            rest = [stmts[i + 1 :]] + conts
            self._check_stmt(st, rest, prots)
            self._recurse(st, rest, prots)

    def _check_stmt(self, st, conts, prots) -> None:
        # only this statement's own expressions; child blocks are
        # visited by _recurse with their own continuations
        headers = self._header_nodes(st)
        for h in headers:
            for call, fam, meth, recv, rels in _acquires_in(h):
                if self._covered(st, recv, rels, conts, prots):
                    continue
                self.findings.append(
                    Finding(
                        PASS,
                        "leak-on-exception",
                        self.path,
                        self.symbol,
                        f"{fam}.{meth}",
                        f"`{recv}.{meth}(...)` has no matching "
                        f"{'/'.join(rels)} on the exception path: an "
                        "error raised before the release leaks the "
                        f"{fam} resource (wrap in try/finally or "
                        "release in an except handler and re-raise)",
                        call.lineno,
                    )
                )
        self._check_handles(st, conts, prots)

    def _header_nodes(self, st):
        """The statement's own expression nodes, excluding child
        statement blocks (which recurse with their own context)."""
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return []
        if isinstance(st, (ast.If, ast.While)):
            return [st.test]
        if isinstance(st, (ast.For, ast.AsyncFor)):
            return [st.iter, st.target]
        if isinstance(st, (ast.With, ast.AsyncWith)):
            return [item.context_expr for item in st.items]
        if isinstance(st, ast.Try):
            return []
        return [st]

    def _covered(self, st, recv, rels, conts, prots) -> bool:
        # rule 1/2: an enclosing protector already covers this receiver
        for prot_recv, prot_rels in prots:
            if prot_recv == recv and set(rels) & set(prot_rels):
                return True
        # the acquiring statement may release inline (rare but legal:
        # `pool.release(pool.begin(s))`-style wrappers)
        # rules 3/4: scan the forward continuation
        for block in conts:
            for nxt in block:
                if _is_release(nxt, recv, rels):
                    return True
                if _protective_try(nxt, recv, rels):
                    return True
                if _risky(nxt):
                    return False
        return True  # clean run-off: ownership parked, nothing can raise

    # -- handle family ------------------------------------------------------

    def _check_handles(self, st, conts, prots) -> None:
        if not isinstance(st, ast.Assign) or len(st.targets) != 1:
            return
        tgt = st.targets[0]
        if not isinstance(tgt, ast.Name):
            return  # self.x = open(...) is ownership transfer by itself
        call = st.value
        if not isinstance(call, ast.Call):
            return
        cname = (
            call.func.id if isinstance(call.func, ast.Name)
            else _expr_text(call.func)
        )
        if cname not in _HANDLE_CALLS:
            return
        recv = tgt.id
        rels = ("close",)
        if self._escapes(recv, conts):
            return
        if self._covered(st, recv, rels, conts, prots):
            return
        self.findings.append(
            Finding(
                PASS,
                "handle-leak",
                self.path,
                self.symbol,
                f"handle.{cname}",
                f"`{recv} = {cname}(...)` is never closed on the "
                "exception path and does not escape this function: "
                "use `with`, or close in a finally",
                st.lineno,
            )
        )

    def _escapes(self, name: str, conts) -> bool:
        """Ownership transfer: the handle is returned/yielded, stored
        on an object, or passed to another call — someone else closes."""
        for block in conts:
            for nxt in block:
                for n in ast.walk(nxt):
                    if isinstance(n, (ast.Return, ast.Yield)) and n.value:
                        if any(
                            isinstance(x, ast.Name) and x.id == name
                            for x in ast.walk(n.value)
                        ):
                            return True
                    if isinstance(n, ast.Assign) and isinstance(
                        n.value, ast.Name
                    ) and n.value.id == name:
                        if any(
                            not isinstance(t, ast.Name) for t in n.targets
                        ):
                            return True
                    if isinstance(n, ast.Call):
                        fn_recv = (
                            _expr_text(n.func.value)
                            if isinstance(n.func, ast.Attribute) else None
                        )
                        for arg in list(n.args) + [
                            kw.value for kw in n.keywords
                        ]:
                            if any(
                                isinstance(x, ast.Name) and x.id == name
                                for x in ast.walk(arg)
                            ) and fn_recv != name:
                                return True
        return False

    def _recurse(self, st, conts, prots) -> None:
        if isinstance(st, ast.Try):
            inner = list(prots)
            for _call, _fam, _m, recv, rels in self._try_protects(st):
                inner.append((recv, rels))
            self._block(st.body, conts, inner)
            for h in st.handlers:
                self._block(h.body, conts, prots)
            self._block(st.orelse, conts, prots)
            self._block(st.finalbody, conts, prots)
        elif isinstance(st, (ast.If,)):
            self._block(st.body, conts, prots)
            self._block(st.orelse, conts, prots)
        elif isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
            # back-edges ignored: per-iteration acquires must be covered
            # within the iteration or by the post-loop continuation
            self._block(st.body, conts, prots)
            self._block(st.orelse, conts, prots)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            inner = list(prots)
            for item in st.items:
                ce = item.context_expr
                recv = None
                if isinstance(ce, ast.Call) and isinstance(
                    ce.func, ast.Attribute
                ):
                    recv = _expr_text(ce.func.value)
                elif isinstance(ce, (ast.Name, ast.Attribute)):
                    recv = _expr_text(ce)
                if recv:
                    # `with pool.guard(s):`-style scopes release on exit
                    inner.append(
                        (recv, ("release", "shrink", "unpin", "close", "free"))
                    )
                if item.optional_vars is not None and isinstance(
                    item.optional_vars, ast.Name
                ):
                    inner.append(
                        (item.optional_vars.id, ("close",))
                    )
            self._block(st.body, conts, inner)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested function: fresh scope, its own continuations
            sub = _FuncScan(
                self.path,
                f"{self.symbol}.<locals>.{st.name}" if self.symbol else st.name,
                self.findings,
            )
            sub.scan(st)

    def _try_protects(self, st):
        """Receivers this ``try`` releases in a handler or finally."""
        found = []
        rel_names = set()
        for rels in list(_POOL_ACQ.values()) + list(_SLOT_ACQ.values()):
            rel_names.update(rels)
        rel_names.add("close")
        bodies = [s for h in st.handlers for s in h.body] + list(st.finalbody)
        for s in bodies:
            for call in ast.walk(s):
                if (
                    isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr in rel_names
                ):
                    recv = _expr_text(call.func.value)
                    if recv:
                        found.append((None, None, None, recv, (call.func.attr,)))
        return found


def lint_source(src: str, path: str) -> list[Finding]:
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                PASS, "syntax-error", path, "", "parse",
                f"file does not parse: {e}", e.lineno or 0,
            )
        ]
    findings: list[Finding] = []

    def walk_scope(node, scope: str) -> None:
        for item in ast.iter_child_nodes(node):
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                sym = f"{scope}.{item.name}" if scope else item.name
                _FuncScan(path, sym, findings).scan(item)
            elif isinstance(item, ast.ClassDef):
                walk_scope(item, f"{scope}.{item.name}" if scope else item.name)

    walk_scope(tree, "")
    return findings


def lint_file(path: str, repo_root: str) -> list[Finding]:
    rel = os.path.relpath(path, repo_root)
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), rel)


def lint_paths(paths: list[str], repo_root: str) -> list[Finding]:
    findings: list[Finding] = list(_self_test())
    for p in paths:
        if os.path.isfile(p):
            findings.extend(lint_file(p, repo_root))
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [
                d for d in dirnames if d not in ("__pycache__", ".git")
            ]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    findings.extend(
                        lint_file(os.path.join(dirpath, fn), repo_root)
                    )
    return findings


# -- negative fixture (detector self-test, PR 15 pattern) -------------------

_LEAK_FIXTURE = '''
def admit(self, idx, n):
    blocks = self._pool.alloc(idx, n)      # acquire
    tokens = self.run_prefill(idx, blocks)  # can raise: leak on the way out
    self._pool.release(idx)
    return tokens
'''


def _self_test() -> list[Finding]:
    """Lint the seeded leak-on-exception fixture; no finding means the
    detector is broken and the PASS fails loudly rather than silently
    approving everything."""
    got = lint_source(_LEAK_FIXTURE, "<lifecycle-fixture>")
    if any(
        f.rule == "leak-on-exception" and f.detail == "pool.alloc"
        for f in got
    ):
        return []
    return [
        Finding(
            PASS,
            "detector-broken",
            "<lifecycle-fixture>",
            "admit",
            "no-finding",
            "seeded leak-on-exception fixture produced no finding — "
            "the lifecycle lint is not detecting leaks",
        )
    ]
