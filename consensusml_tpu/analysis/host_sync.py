"""Host-sync lint: JAX host/device hazards, found statically.

Two families of findings, from one AST walk per file:

**Inside traced code** (functions the linter can prove end up under
``jit`` / ``shard_map`` / ``lax.scan``-family tracing), the hazards that
either crash at trace time, silently capture a trace-time constant, or
fence the device pipeline on every call:

- ``sync-in-traced`` — ``jax.device_get`` / ``block_until_ready``
- ``numpy-in-traced`` — ``np.asarray`` / ``np.array`` on (potential)
  tracers: numpy computes at trace time on abstract values and raises —
  or worse, bakes a constant when fed a concrete side value
- ``item-in-traced`` — ``.item()`` / ``.tolist()``: concretization, a
  ``TracerError`` at best
- ``time-in-traced`` — ``time.time()``-family calls: traced code runs
  ONCE at trace time; the timestamp becomes a compile-time constant
- ``branch-on-traced`` — a Python ``if``/``while`` (or conditional
  expression) whose test references one of the traced function's own
  parameters as a VALUE. Parameters of a traced function are tracers;
  branching on one raises ``TracerBoolConversionError``. Static uses are
  excluded (``x is None``, ``x.attr``, ``isinstance/len/callable/
  hasattr/getattr/type(x)``, ``x`` in call position), so config-style
  branching on ``self``/closures never trips this.

**Anywhere in the package** (``host-sync`` rule): every call site of
``jax.device_get`` / ``block_until_ready``. These are legitimate at
checkpoint/eval/telemetry boundaries — the point of the rule is that
every one of them is either deliberate (baselined, with a comment saying
why) or a regression someone snuck onto a hot path. The checked-in
``.cml-check-baseline`` is the complete inventory of intentional syncs.

How traced-ness is established (a deliberately conservative heuristic —
it under-approximates, it does not guess):

1. decorated with ``jit``/``pjit``/``shard_map``/``checkpoint``/
   ``remat`` (bare, dotted, called, or via ``functools.partial``);
2. passed in a function position of a tracing caller: ``jax.jit(f)``,
   ``vmap``/``grad``/``value_and_grad``/``eval_shape``/``make_jaxpr``,
   ``shard_map(f, ...)``, ``lax.scan``/``while_loop``/``fori_loop``/
   ``cond``/``switch``/``associative_scan``/``map`` (including lambdas,
   ``functools.partial(f, ...)`` and ``self.f`` method references, and
   lists of branches);
3. lexically nested inside a traced function; or
4. called (as ``f(...)`` or ``self.f(...)``) from a traced function in
   the same module, transitively.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable

from consensusml_tpu.analysis.findings import Finding

__all__ = ["lint_source", "lint_file", "lint_paths"]

PASS = "host-sync"

# decorators that make the decorated function traced
_TRACE_DECOS = {"jit", "pjit", "shard_map", "checkpoint", "remat"}

# callee last-segment -> argument positions holding functions to be traced
_TRACE_CALLERS: dict[str, tuple[int, ...]] = {
    "jit": (0,),
    "pjit": (0,),
    "shard_map": (0,),
    "vmap": (0,),
    "pmap": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
    "checkpoint": (0,),
    "remat": (0,),
    "eval_shape": (0,),
    "make_jaxpr": (0,),
    "named_call": (0,),
    "scan": (0,),
    "associative_scan": (0,),
    "map": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2),
    "switch": (1,),
}

_SYNC_CALLS = {"device_get", "block_until_ready"}
_ITEM_CALLS = {"item", "tolist"}
_TIME_CALLS = {"time", "perf_counter", "monotonic", "process_time"}
_NUMPY_CALLS = {"asarray", "array"}
# Name roots whose attribute chains are considered static config, so
# branch-on-traced never fires through them
_STATIC_PARAMS = {"self", "cls"}
_STATIC_TEST_CALLS = {
    "isinstance", "len", "callable", "hasattr", "getattr", "type", "range",
}


def _last_segment(node: ast.AST) -> str | None:
    """``jax.lax.scan`` -> ``scan``; ``scan`` -> ``scan``; else None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class _Scope:
    def __init__(self, node, qualname: str, parent: "_Scope | None"):
        self.node = node
        self.qualname = qualname
        self.parent = parent


def _collect(tree: ast.Module):
    """One walk: (scopes by node, def-nodes by bare name, numpy/time
    aliases, call graph edges, traced roots)."""
    scopes: dict[ast.AST, _Scope] = {}
    by_name: dict[str, list[ast.AST]] = {}
    numpy_aliases: set[str] = set()
    time_aliases: set[str] = set()  # names bound by `from time import time`
    traced_roots: set[ast.AST] = set()
    # caller def-node -> set of bare callee names (same module)
    calls_out: dict[ast.AST, set[str]] = {}

    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                # only bare numpy: jnp inside traced code is fine
                if a.name == "numpy":
                    numpy_aliases.add(a.asname or "numpy")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "time":
                for a in node.names:
                    if a.name in _TIME_CALLS:
                        time_aliases.add(a.asname or a.name)

    def visit(node: ast.AST, scope: _Scope | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FuncNode):
                name = getattr(child, "name", "<lambda>")
                qual = f"{scope.qualname}.{name}" if scope else name
                s = _Scope(child, qual, scope)
                scopes[child] = s
                by_name.setdefault(name, []).append(child)
                if not isinstance(child, ast.Lambda) and _is_traced_by_deco(
                    child
                ):
                    traced_roots.add(child)
                visit(child, s)
            elif isinstance(child, ast.ClassDef):
                qual = (
                    f"{scope.qualname}.{child.name}" if scope else child.name
                )
                visit(child, _Scope(child, qual, scope))
            else:
                if isinstance(child, ast.Call) and scope is not None:
                    callee = _last_segment(child.func)
                    if callee:
                        calls_out.setdefault(scope.node, set()).add(callee)
                if isinstance(child, ast.Call):
                    for fn_node, fn_name in _trace_position_args(child):
                        if fn_node is not None:
                            traced_roots.add(fn_node)
                        if fn_name is not None:
                            for d in by_name.get(fn_name, []):
                                traced_roots.add(d)
                            # defs seen later still need marking: remember
                            # the name and resolve after the walk
                            deferred_names.add(fn_name)
                visit(child, scope)

    deferred_names: set[str] = set()
    visit(tree, None)
    for name in deferred_names:
        for d in by_name.get(name, []):
            traced_roots.add(d)
    return scopes, by_name, numpy_aliases, time_aliases, traced_roots, calls_out


def _is_traced_by_deco(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    for deco in fn.decorator_list:
        if (seg := _last_segment(deco)) in _TRACE_DECOS:
            return True
        if isinstance(deco, ast.Call):
            if (seg := _last_segment(deco.func)) in _TRACE_DECOS:
                return True
            if _last_segment(deco.func) == "partial" and deco.args:
                if _last_segment(deco.args[0]) in _TRACE_DECOS:
                    return True
    return False


def _unwrap_fn_ref(node: ast.AST):
    """A node in a function position -> (lambda node | None, name | None)."""
    if isinstance(node, ast.Lambda):
        return node, None
    if isinstance(node, (ast.Name, ast.Attribute)):
        return None, _last_segment(node)
    if isinstance(node, ast.Call) and _last_segment(node.func) == "partial":
        if node.args:
            return _unwrap_fn_ref(node.args[0])
    return None, None


# callee names that collide with common non-tracing APIs: only honored
# when dotted through `lax` (jax.lax.map traces; jax.tree.map does not)
_LAX_ONLY_CALLERS = {"map", "scan"}


def _trace_position_args(call: ast.Call):
    """Yield (lambda_node, bare_name) for every function-position argument
    of a tracing caller."""
    callee = _last_segment(call.func)
    positions = _TRACE_CALLERS.get(callee or "")
    if not positions:
        return
    if callee in _LAX_ONLY_CALLERS:
        if not (
            isinstance(call.func, ast.Attribute)
            and _last_segment(call.func.value) == "lax"
        ):
            return
    for pos in positions:
        if pos >= len(call.args):
            continue
        arg = call.args[pos]
        targets = (
            arg.elts if isinstance(arg, (ast.List, ast.Tuple)) else [arg]
        )
        for t in targets:
            yield _unwrap_fn_ref(t)


def _propagate(scopes, by_name, traced_roots, calls_out) -> set[ast.AST]:
    """Traced closure: nesting + same-module call graph, to fixpoint."""
    traced: set[ast.AST] = set(traced_roots)
    changed = True
    while changed:
        changed = False
        for node, scope in scopes.items():
            if node in traced:
                continue
            # nested inside a traced function
            p = scope.parent
            while p is not None:
                if isinstance(p.node, _FuncNode) and p.node in traced:
                    traced.add(node)
                    changed = True
                    break
                p = p.parent
        for caller in list(traced):
            for name in calls_out.get(caller, ()):
                for d in by_name.get(name, []):
                    if d not in traced:
                        traced.add(d)
                        changed = True
    return traced


def _param_names(fn) -> set[str]:
    a = fn.args
    names = {x.arg for x in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    return names - _STATIC_PARAMS


class _TestScan(ast.NodeVisitor):
    """Does a branch test reference a traced param as a VALUE?"""

    def __init__(self, params: set[str]):
        self.params = params
        self.hits: list[str] = []

    def visit_Attribute(self, node: ast.Attribute):
        # x.attr: shape/dtype/config access — static, don't descend into
        # the root name (but do scan subscripts etc. inside)
        if isinstance(node.value, ast.Name):
            return
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        if _last_segment(node.func) in _STATIC_TEST_CALLS:
            return  # len(x)/isinstance(x, ...)/... are static
        # the function being called is not a value use of a param tracer
        for arg in node.args:
            self.visit(arg)
        for kw in node.keywords:
            self.visit(kw.value)

    def visit_Compare(self, node: ast.Compare):
        # `x is None` / `x is not None` is a static presence check
        if (
            len(node.ops) == 1
            and isinstance(node.ops[0], (ast.Is, ast.IsNot))
            and any(
                isinstance(c, ast.Constant) and c.value is None
                for c in [node.left, *node.comparators]
            )
        ):
            return
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name):
        if isinstance(node.ctx, ast.Load) and node.id in self.params:
            self.hits.append(node.id)


def _scan_traced_body(
    fn, qualname: str, path: str, numpy_aliases, time_aliases
) -> Iterable[Finding]:
    """Hazards in one traced function's own body (nested defs excluded —
    they are traced themselves and scanned separately)."""
    params = _param_names(fn)
    body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FuncNode):
                continue
            yield child
            yield from walk(child)

    mk = lambda rule, detail, msg, line: Finding(
        PASS, rule, path, qualname, detail, msg, line
    )
    for node in walk(ast.Module(body=list(body), type_ignores=[])):
        if isinstance(node, ast.Call):
            seg = _last_segment(node.func)
            if seg in _SYNC_CALLS:
                yield mk(
                    "sync-in-traced", seg,
                    f"{seg}() inside traced code fences the device "
                    "pipeline (or crashes on a tracer); hoist it out of "
                    "the jitted region",
                    node.lineno,
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _ITEM_CALLS
                and not node.args
            ):
                yield mk(
                    "item-in-traced", node.func.attr,
                    f".{node.func.attr}() concretizes a tracer "
                    "(TracerError at trace time); keep the value on "
                    "device",
                    node.lineno,
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in numpy_aliases
                and node.func.attr in _NUMPY_CALLS
            ):
                yield mk(
                    "numpy-in-traced", f"np.{node.func.attr}",
                    "numpy call inside traced code computes at trace "
                    "time (TracerError on a tracer, or a baked-in "
                    "constant); use jnp",
                    node.lineno,
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "time"
                and node.func.attr in _TIME_CALLS
            ) or (
                isinstance(node.func, ast.Name)
                and node.func.id in time_aliases
            ):
                detail = (
                    node.func.attr
                    if isinstance(node.func, ast.Attribute)
                    else node.func.id
                )
                yield mk(
                    "time-in-traced", f"time.{detail}",
                    "wall-clock read inside traced code becomes a "
                    "compile-time constant (traced once, replayed "
                    "forever); time on the host side",
                    node.lineno,
                )
        elif isinstance(node, (ast.If, ast.While, ast.IfExp)):
            scan = _TestScan(params)
            scan.visit(node.test)
            for hit in sorted(set(scan.hits)):
                yield mk(
                    "branch-on-traced", hit,
                    f"Python branch on parameter {hit!r} of a traced "
                    "function (tracers have no truth value); use "
                    "jnp.where / lax.cond, or baseline if the argument "
                    "is statically known here",
                    node.lineno,
                )


def lint_source(src: str, path: str) -> list[Finding]:
    """Lint one file's source. ``path`` is the repo-relative name used in
    finding ids."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                PASS, "syntax-error", path, "", "parse",
                f"file does not parse: {e}", e.lineno or 0,
            )
        ]
    (
        scopes, by_name, numpy_aliases, time_aliases, roots, calls_out
    ) = _collect(tree)
    traced = _propagate(scopes, by_name, roots, calls_out)

    findings: list[Finding] = []
    for node in traced:
        scope = scopes.get(node)
        if scope is None:
            continue
        findings.extend(
            _scan_traced_body(
                node, scope.qualname, path, numpy_aliases, time_aliases
            )
        )

    # package-wide sync inventory (rule "host-sync"): every device_get /
    # block_until_ready call OUTSIDE traced code — deliberate ones are
    # baselined, new ones are presumed hot-path regressions
    traced_ranges = [
        (n.lineno, max(n.lineno, getattr(n, "end_lineno", n.lineno) or 0))
        for n in traced
    ]

    def in_traced(line: int) -> bool:
        return any(lo <= line <= hi for lo, hi in traced_ranges)

    class _SyncScan(ast.NodeVisitor):
        def __init__(self):
            self.stack: list[str] = []

        def _named(self, node):
            self.stack.append(getattr(node, "name", "<lambda>"))
            self.generic_visit(node)
            self.stack.pop()

        visit_FunctionDef = visit_AsyncFunctionDef = visit_ClassDef = _named

        def visit_Call(self, node: ast.Call):
            seg = _last_segment(node.func)
            if seg in _SYNC_CALLS and not in_traced(node.lineno):
                findings.append(
                    Finding(
                        PASS, "host-sync", path, ".".join(self.stack), seg,
                        f"host sync {seg}() — fine at checkpoint/eval/"
                        "telemetry boundaries, a regression on a hot "
                        "path; fix it or baseline it with a comment",
                        node.lineno,
                    )
                )
            self.generic_visit(node)

    _SyncScan().visit(tree)
    return findings


def lint_file(path: str, repo_root: str) -> list[Finding]:
    rel = os.path.relpath(path, repo_root)
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), rel)


def lint_paths(paths: list[str], repo_root: str) -> list[Finding]:
    """Lint every ``.py`` file under ``paths`` (files or directories)."""
    findings: list[Finding] = []
    for p in paths:
        if os.path.isfile(p):
            findings.extend(lint_file(p, repo_root))
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [
                d for d in dirnames if d not in ("__pycache__", ".git")
            ]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    findings.extend(
                        lint_file(os.path.join(dirpath, fn), repo_root)
                    )
    return findings
