"""Jaxpr contract checks: invariants of the traced train program.

The gossip stack's perf story rests on properties of the COMPILED round
program that no unit test of the math can see: the round must not call
back into the host (a callback serializes the device pipeline every
round), must not silently promote to f64 (4x wire + HBM on a path sized
in f32), must issue exactly the collectives the schedule verifier
proved, and must hit the jit cache on every round after the first (a
signature that drifts between consecutive rounds recompiles every
round — minutes per round at pod scale, the classic "why is round 2 as
slow as round 1" regression).

For each config in :mod:`consensusml_tpu.configs` (smoke scale, CPU):

- ``host-callback`` — no callback/debug primitives anywhere in the
  train-step jaxpr (checked recursively through scan/cond/pjit bodies);
- ``f64-promotion`` — no float64/complex128 intermediate anywhere;
- ``collective-count`` — the gossip round, traced per-worker under
  ``shard_map`` on the config's topology, contains exactly as many
  ``ppermute`` equations as the schedule materializer predicts from the
  topology + bucket plan (and none at all for psum topologies). This
  ties the PROVEN schedule to the TRACED program: if the engine ever
  issues a collective the verifier did not model, this contract fails
  rather than the verifier silently passing;
- ``recompile`` — tracing the train step with the output shapes of
  round r as the input of round r+1 yields a byte-identical canonical
  jaxpr: two consecutive rounds share one compilation. Dtype drift
  (e.g. a weak-type f32 scalar hardening), shape drift, or a
  config-dependent branch on the round counter all fail this.

Everything traces abstractly (``jax.make_jaxpr`` / ``jax.eval_shape``):
no parameters are materialized, no program executes, no TPU is needed.
The train-step contracts run on the simulated backend (identical round
semantics, cross-validated by tests); the collective-count contract
traces the collective engine itself under ``shard_map`` on the CPU
mesh.
"""

from __future__ import annotations

import hashlib
from typing import Any

from consensusml_tpu.analysis.findings import Finding

__all__ = [
    "check_config",
    "check_all_configs",
    "check_fused_wire",
    "count_primitives",
]

PASS = "jaxpr"

_CALLBACK_PRIMS = {
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "host_callback_call", "debug_print",
}
_BAD_DTYPES = {"float64", "complex128"}


def _iter_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in v if isinstance(v, (list, tuple)) else [v]:
                if hasattr(sub, "eqns"):
                    yield from _iter_eqns(sub)
                elif hasattr(sub, "jaxpr"):
                    yield from _iter_eqns(sub.jaxpr)


def count_primitives(jaxpr) -> dict[str, int]:
    """Recursive primitive histogram of a (closed) jaxpr."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    counts: dict[str, int] = {}
    for eqn in _iter_eqns(jaxpr):
        counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1
    return counts


def _shard_map_fn():
    import jax

    try:
        return jax.shard_map
    except AttributeError:  # jax < 0.5 keeps shard_map under experimental
        from jax.experimental.shard_map import shard_map

        return shard_map


def _canonical_hash(closed_jaxpr) -> str:
    """Hash of the jaxpr's canonical printed form. Var names in jax's
    printer are assigned in traversal order, so two traces of the same
    program print identically — and any structural difference (extra
    op, dtype change, different constant) changes the text."""
    text = closed_jaxpr.pretty_print() if hasattr(
        closed_jaxpr, "pretty_print"
    ) else str(closed_jaxpr)
    return hashlib.sha256(text.encode()).hexdigest()


def _shape_only(tree: Any):
    import jax

    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree
    )


def _stacked_state_and_batch(bundle):
    """Abstract stacked TrainState + one concrete round batch (smoke
    data is procedural and tiny; the state is never materialized)."""
    import jax

    from consensusml_tpu.train import init_stacked_state

    state = jax.eval_shape(
        lambda rng: init_stacked_state(
            bundle.cfg, bundle.init_params, rng, bundle.world_size
        ),
        jax.random.key(0),
    )
    batch = next(iter(bundle.batches(1, 0)))
    return state, _shape_only(batch)


def _callback_f64_findings(closed, mk, what: str) -> list[Finding]:
    """The two program-purity contracts shared by the train step and the
    serving decode step: no host callbacks, no f64/complex128."""
    findings: list[Finding] = []
    counts = count_primitives(closed)
    for prim in sorted(set(counts) & _CALLBACK_PRIMS):
        findings.append(
            mk(
                "host-callback", prim,
                f"{what} traces a host callback ({prim} x"
                f"{counts[prim]}): every round would fence the device "
                "pipeline on the host",
            )
        )
    bad = set()
    for eqn in _iter_eqns(closed.jaxpr):
        for var in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(var, "aval", None)
            dt = str(getattr(aval, "dtype", ""))
            if dt in _BAD_DTYPES:
                bad.add((eqn.primitive.name, dt))
    for prim, dt in sorted(bad):
        findings.append(
            mk(
                "f64-promotion", f"{prim}:{dt}",
                f"{what} computes in {dt} (via {prim}): doubles "
                "wire and HBM on a path budgeted in f32 — find the "
                "promoting op (python float op on a traced value, "
                "np.float64 constant, ...)",
            )
        )
    return findings


def _check_step_jaxpr(name: str, bundle) -> list[Finding]:
    import jax

    from consensusml_tpu.train import make_simulated_train_step

    mk = lambda rule, detail, msg: Finding(
        PASS, rule, f"configs:{name}", "train_step", detail, msg
    )
    step = make_simulated_train_step(bundle.cfg, bundle.loss_fn)
    state, batch = _stacked_state_and_batch(bundle)
    closed = jax.make_jaxpr(step)(state, batch)
    findings = _callback_f64_findings(closed, mk, "train step")

    # recompile contract: round r's OUTPUT shapes, fed back as round
    # r+1's input, must retrace to the identical program
    out_state_shapes, _metrics = jax.eval_shape(step, state, batch)
    h1 = _canonical_hash(closed)
    h2 = _canonical_hash(jax.make_jaxpr(step)(out_state_shapes, batch))
    if h1 != h2:
        findings.append(
            mk(
                "recompile", "signature-hash",
                "round r+1 (fed round r's output state) traces to a "
                "DIFFERENT program than round r — the jit cache misses "
                "every round; diff the two jaxprs for the drifting "
                "dtype/shape/weak-type",
            )
        )
    # ... and the state must be shape-stable outright, or the donated
    # buffers cannot be reused
    in_flat = jax.tree.leaves(_shape_only(state))
    out_flat = jax.tree.leaves(out_state_shapes)
    drift = [
        (a.shape, a.dtype, b.shape, b.dtype)
        for a, b in zip(in_flat, out_flat)
        if a.shape != b.shape or a.dtype != b.dtype
    ]
    if len(in_flat) != len(out_flat) or drift:
        findings.append(
            mk(
                "recompile", "state-drift",
                f"TrainState changes structure across a round "
                f"({len(in_flat)} -> {len(out_flat)} leaves, "
                f"{len(drift)} leaf shape/dtype changes): donation and "
                "the jit cache both break",
            )
        )
    return findings


def _check_collective_count(name: str, bundle) -> list[Finding]:
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from consensusml_tpu.analysis import schedule as sched
    from consensusml_tpu.train.local_sgd import _gossiped

    findings: list[Finding] = []
    engine = bundle.cfg.engine()
    cfg = engine.config
    topo = engine.topology
    mk = lambda rule, detail, msg: Finding(
        PASS, rule, f"configs:{name}", "gossip_round", detail, msg
    )
    if (
        cfg.push_sum_enabled
        or cfg.overlap
        or cfg.faults is not None
        or cfg.codec_warmup_rounds > 0
        or cfg.codec_refresh_every > 0
        or topo.is_time_varying
    ):
        # cond/switch trace BOTH wire layouts into one jaxpr; a static
        # per-round count is not defined there
        return findings
    if len(jax.devices()) < topo.world_size:
        return [
            mk(
                "collective-count", "no-mesh",
                f"cannot trace: {topo.world_size} workers but only "
                f"{len(jax.devices())} devices "
                "(set XLA_FLAGS=--xla_force_host_platform_device_count)",
            )
        ]

    from consensusml_tpu.comm import WorkerMesh

    # per-worker gossiped-tree shapes (params + model_state)
    probe = jax.eval_shape(bundle.init_params, jax.random.key(0))
    if isinstance(probe, tuple) and len(probe) == 2:
        params, model_state = probe
    else:
        params, model_state = probe, {}
    tree = _gossiped(params, model_state)
    world = topo.world_size
    stacked = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct((world,) + tuple(x.shape), x.dtype),
        tree,
    )
    wmesh = WorkerMesh.create(topo, platform="cpu")

    def round_fn(t):
        st = engine.init_state(t)
        out, _ = engine.round_collective(t, st, step=np.int32(0))
        return out

    f = _shard_map_fn()(
        round_fn,
        mesh=wmesh.mesh,
        in_specs=P(*topo.axis_names),
        out_specs=P(*topo.axis_names),
    )
    counts = count_primitives(jax.make_jaxpr(f)(stacked))
    traced = counts.get("ppermute", 0)
    predicted = sum(
        1
        for op in sched.materialize_schedules(engine, tree)[0]
        if op.kind == "ppermute"
    )
    if traced != predicted:
        findings.append(
            mk(
                "collective-count", "ppermute",
                f"gossip round traces {traced} ppermutes but the "
                f"verified schedule models {predicted} — the engine "
                "issues collectives the schedule verifier never "
                "checked (or the wire layout regressed); update "
                "analysis/schedule.py alongside the engine",
            )
        )
    if topo.uses_psum and traced != 0:
        findings.append(
            mk(
                "collective-count", "psum-topology-ppermute",
                f"dense (psum) topology traces {traced} ppermutes; the "
                "dense wire must stay a single reduction",
            )
        )
    return findings


def _shard_map_no_check(fn, *, mesh, in_specs, out_specs):
    """``shard_map`` with the per-output replication check disabled:
    ``pallas_call`` has no replication rule (jax 0.4.x ``check_rep`` /
    newer ``check_vma``), and for a TRACE-ONLY contract the check adds
    nothing — the schedule verifier already proves the collective
    structure this pass counts."""
    sm = _shard_map_fn()
    for kw in ({"check_vma": False}, {"check_rep": False}, {}):
        try:
            return sm(
                fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
            )
        except TypeError:  # this jax spells the kwarg differently
            continue
    raise RuntimeError("unreachable: bare shard_map always constructs")


def check_fused_wire(world: int = 8) -> list[Finding]:
    """Contracts of the FUSED one-pass gossip wire (ROADMAP item 5 /
    docs/gossip_bucketing.md "Fused wire"): trace ``round_collective``
    for a representative fused engine per topology class and assert, on
    the traced program itself:

    - ``fused-active`` — the engine engages the fused wire at all
      (bucketed transport + a codec advertising fused kernels under
      ``fused_wire="auto"``); a silent fallback to the two-step path
      would pass every other contract while fusing nothing;
    - ``kernel-count`` — exactly ONE ``pallas_call`` per bucket per
      kernel stage per innovation exchange: encode + decode per bucket
      on ppermute topologies, encode only on psum topologies (the dense
      receive decodes in plain ops under the reduction). More means a
      stage un-fused (extra HBM round-trips — the regression this wire
      exists to prevent); fewer means a bucket fell off the kernel path;
    - ``collective-count`` — the fused program's traced ppermute count
      still equals the schedule verifier's model (fusion changes HBM
      traffic, never the wire: same payload leaves, same collectives);
    - the shared purity contracts (no host callbacks, no f64).

    Traced with the codec's ``interpret`` impl so the kernels appear as
    ``pallas_call`` equations on any host — the compiled TPU program has
    the same jaxpr modulo lowering.
    """
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from consensusml_tpu.analysis import schedule as sched
    from consensusml_tpu.comm import WorkerMesh
    from consensusml_tpu.compress import PallasInt8Compressor
    from consensusml_tpu.consensus import ConsensusEngine, GossipConfig
    from consensusml_tpu.topology import DenseTopology, RingTopology

    findings: list[Finding] = []
    if len(jax.devices()) < world:
        return [
            Finding(
                PASS, "kernel-count", "fused-wire", "gossip_round",
                "no-mesh",
                f"cannot trace the fused wire: {world} workers but only "
                f"{len(jax.devices())} devices "
                "(set XLA_FLAGS=--xla_force_host_platform_device_count)",
            )
        ]
    comp = PallasInt8Compressor(chunk=128, impl="interpret")
    # two f32 leaves sized to split into multiple buckets at a small cap,
    # exercising the per-bucket (not per-round) kernel accounting
    tree = {
        "w": jax.ShapeDtypeStruct((4096, 16), jax.numpy.float32),
        "b": jax.ShapeDtypeStruct((513,), jax.numpy.float32),
    }
    for topo in (RingTopology(world), DenseTopology(world)):
        tag = type(topo).__name__.removesuffix("Topology").lower()
        mk = lambda rule, detail, msg, tag=tag: Finding(
            PASS, rule, f"fused-wire:{tag}", "gossip_round", detail, msg
        )
        engine = ConsensusEngine(
            GossipConfig(
                topology=topo, compressor=comp, gamma=0.5,
                bucket_bytes=64 * 1024,
            )
        )
        if not engine.fused_wire_active:
            findings.append(
                mk(
                    "fused-active", "two-step-fallback",
                    "a bucketed engine with a fused-capable codec "
                    "(PallasInt8) does not engage the fused wire under "
                    "fused_wire='auto' — the one-pass kernels silently "
                    "fell back to the two-step path",
                )
            )
            continue
        plan = engine.bucket_plan(tree)
        stages = 1 if topo.uses_psum else 2  # psum decodes in plain ops
        expected = stages * plan.num_buckets * engine.config.gossip_steps
        stacked = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                (world,) + tuple(x.shape), x.dtype
            ),
            tree,
        )
        wmesh = WorkerMesh.create(topo, platform="cpu")

        def round_fn(t, engine=engine):
            st = engine.init_state(t)
            out, _ = engine.round_collective(t, st, step=np.int32(0))
            return out

        f = _shard_map_no_check(
            round_fn,
            mesh=wmesh.mesh,
            in_specs=P(*topo.axis_names),
            out_specs=P(*topo.axis_names),
        )
        closed = jax.make_jaxpr(f)(stacked)
        findings += _callback_f64_findings(
            closed, mk, f"fused {tag} gossip round"
        )
        counts = count_primitives(closed)
        traced_kernels = counts.get("pallas_call", 0)
        if traced_kernels != expected:
            findings.append(
                mk(
                    "kernel-count", "pallas_call",
                    f"fused {tag} round traces {traced_kernels} "
                    f"pallas_call(s) but the one-pass wire contract is "
                    f"{expected} ({stages} stage(s) x {plan.num_buckets} "
                    f"buckets x {engine.config.gossip_steps} gossip "
                    "step(s)) — a stage un-fused (extra HBM round-trips) "
                    "or a bucket fell off the kernel path",
                )
            )
        traced = counts.get("ppermute", 0)
        predicted = sum(
            1
            for op in sched.materialize_schedules(engine, tree)[0]
            if op.kind == "ppermute"
        )
        if traced != predicted:
            findings.append(
                mk(
                    "collective-count", "ppermute",
                    f"fused {tag} round traces {traced} ppermutes but the "
                    f"verified schedule models {predicted} — fusion must "
                    "change HBM traffic, never the wire (same payload "
                    "leaves, same collectives); update "
                    "analysis/schedule.py alongside the fused wire",
                )
            )
    return findings


def _check_decode_jaxpr(name: str, bundle) -> list[Finding]:
    """Serving decode-step contracts (causal-LM configs only).

    Steady-state serving lives and dies by the same compiled-program
    invariants as training: a host callback inside the decode step
    fences the device once PER TOKEN, f64 doubles the KV cache, and a
    program whose signature drifts between consecutive decode steps
    recompiles mid-request — the serving engine's zero-recompile
    contract (docs/serving.md). Traced abstractly on the exact jit the
    engine runs (:func:`consensusml_tpu.serve.decode.make_decode_fn`).
    """
    import jax
    import jax.numpy as jnp

    from consensusml_tpu.serve import decode as D

    if bundle.model is None or not D.supports_decode(bundle.model):
        return []
    mk = lambda rule, detail, msg: Finding(
        PASS, rule, f"configs:{name}", "decode_step", detail, msg
    )
    dm = D.DecodeModel.wrap(bundle.model)
    slots, max_len = 4, min(dm.max_len, 32)
    probe = jax.eval_shape(bundle.init_params, jax.random.key(0))
    params = probe[0] if isinstance(probe, tuple) and len(probe) == 2 else probe
    cache = jax.eval_shape(lambda: D.init_cache(dm, slots, max_len))
    tokens = jax.ShapeDtypeStruct((slots,), jnp.int32)
    positions = jax.ShapeDtypeStruct((slots,), jnp.int32)
    samp = _sampling_structs(slots)
    decode = D.make_decode_fn(dm)
    closed = jax.make_jaxpr(decode)(params, cache, tokens, positions, *samp)
    findings = _callback_f64_findings(closed, mk, "decode step")

    # recompile contract: step r's OUTPUT cache, fed back as step r+1's
    # input (exactly what the engine loop does every token), must trace
    # to the byte-identical program — zero recompiles across decode
    # steps at ANY slot occupancy / length / greedy-vs-sampled mix
    # (fill level AND sampling parameters are data)
    out_tokens, out_cache = jax.eval_shape(
        decode, params, cache, tokens, positions, *samp
    )
    findings += _hash_stable(
        mk, decode, closed,
        (params, out_cache, out_tokens, positions, *samp),
        "decode", "signature-hash",
    )
    findings += _cache_drift(
        mk, cache, out_cache, "the KV cache", "cache-drift",
        "donation and the jit cache both break",
    )
    return findings


def _sampling_structs(slots: int | None = None):
    """Abstract per-slot sampling triple ``(temperature, top_p, seeds)``
    — scalars when ``slots`` is None (the prefill signature)."""
    import jax
    import jax.numpy as jnp

    shape = () if slots is None else (slots,)
    return (
        jax.ShapeDtypeStruct(shape, jnp.float32),
        jax.ShapeDtypeStruct(shape, jnp.float32),
        jax.ShapeDtypeStruct(shape, jnp.uint32),
    )


def _hash_stable(mk, fn, closed, out_args, what: str, detail: str) -> list[Finding]:
    """Step-over-step recompile contract for one jitted serving stage:
    feeding step r's OUTPUT arrays back as step r+1's input must retrace
    to the byte-identical canonical jaxpr (one compile serves steady
    state). ``closed`` is step r's ALREADY-traced jaxpr — every caller
    holds it from the callback/f64 pass, so only step r+1 traces here."""
    import jax

    h1 = _canonical_hash(closed)
    h2 = _canonical_hash(jax.make_jaxpr(fn)(*out_args))
    if h1 != h2:
        return [
            mk(
                "recompile", detail,
                f"{what} step r+1 (fed step r's outputs) traces to a "
                "DIFFERENT program than step r — the engine recompiles "
                "in steady state; diff the two jaxprs for the drifting "
                "dtype/shape/weak-type",
            )
        ]
    return []


def _cache_drift(
    mk, cache_in, cache_out, what: str, detail: str, tail: str
) -> list[Finding]:
    """Structure/shape/dtype stability of a serving cache pytree across
    one step (the other half of the recompile contract: donation and the
    jit cache both key on it)."""
    import jax

    in_flat = jax.tree.leaves(cache_in)
    out_flat = jax.tree.leaves(cache_out)
    drift = [
        1
        for a, b in zip(in_flat, out_flat)
        if a.shape != b.shape or a.dtype != b.dtype
    ]
    if len(in_flat) != len(out_flat) or drift:
        return [
            mk(
                "recompile", detail,
                f"{what} changes structure across a step "
                f"({len(in_flat)} -> {len(out_flat)} leaves, "
                f"{len(drift)} leaf shape/dtype changes): {tail}",
            )
        ]
    return []


def _check_paged_stage_jaxprs(name: str, bundle) -> list[Finding]:
    """Paged serving-stage contracts (causal-LM configs only).

    The pool engine (``serve/pool/``) runs THREE separately-jitted
    stages — full prefill, prefix-suffix prefill (the prefix cache's
    unshared-suffix admission, including its in-trace copy-on-write),
    and decode; each carries the full contract set INDEPENDENTLY — a
    clean decode jaxpr does not excuse a host callback in the prefill
    scatter:

    - no host callbacks anywhere, in particular not in the block-index
      computation (``physical = table[s, p // bs]`` must stay on device
      — a host round-trip there fences the pipeline once per token) and
      not in the prefix path's COW copy (divergence is resolved
      HOST-side at planning time; the jit only ever sees two block ids);
    - no f64/complex128 (block indices are int32; KV pages are the
      model's compute dtype);
    - step-over-step canonical-jaxpr hash stable PER STAGE: prefill's
      output pages feed the next prefill, decode's output pages feed the
      next decode — both must retrace byte-identically, and the page
      pytree must be structure/shape/dtype-stable (donation depends on
      it). The prefix stage keys on the SUFFIX bucket alone — one
      executable per bucket regardless of how an admission splits into
      matched prefix + computed suffix, which is what keeps the
      zero-recompile contract intact under any hit pattern.
    """
    import jax
    import jax.numpy as jnp

    from consensusml_tpu.serve import decode as D
    from consensusml_tpu.serve import pool as P

    if bundle.model is None or not D.supports_decode(bundle.model):
        return []
    findings: list[Finding] = []
    dm = D.DecodeModel.wrap(bundle.model)
    slots, max_len, bs = 4, min(dm.max_len, 32), 8
    blocks_per_slot = max_len // bs
    num_blocks = slots * blocks_per_slot + 1
    probe = jax.eval_shape(bundle.init_params, jax.random.key(0))
    params = probe[0] if isinstance(probe, tuple) and len(probe) == 2 else probe
    pages = jax.eval_shape(lambda: P.init_pages(dm, num_blocks, bs))

    # -- prefill stage (traced at one representative bucket) ---------------
    mkp = lambda rule, detail, msg: Finding(
        PASS, rule, f"configs:{name}", "paged_prefill", detail, msg
    )
    prefill = P.make_paged_prefill_fn(dm)
    ids = jax.ShapeDtypeStruct((1, max_len), jnp.int32)
    length = jax.ShapeDtypeStruct((), jnp.int32)
    block_row = jax.ShapeDtypeStruct((blocks_per_slot,), jnp.int32)
    samp1 = _sampling_structs(None)
    closed = jax.make_jaxpr(prefill)(
        params, pages, ids, length, block_row, *samp1
    )
    findings += _callback_f64_findings(closed, mkp, "paged prefill stage")
    _tok, _logits, prefill_pages = jax.eval_shape(
        prefill, params, pages, ids, length, block_row, *samp1
    )
    findings += _hash_stable(
        mkp, prefill, closed,
        (params, prefill_pages, ids, length, block_row, *samp1),
        "paged prefill", "signature-hash",
    )

    # -- prefix-suffix prefill stage (traced at the same bucket) -----------
    mkx = lambda rule, detail, msg: Finding(
        PASS, rule, f"configs:{name}", "paged_prefix_prefill", detail, msg
    )
    prefix_prefill = P.make_prefix_prefill_fn(dm)
    pargs = P.prefix_prefill_cost_args(max_len, bs, blocks_per_slot)
    closed = jax.make_jaxpr(prefix_prefill)(params, pages, *pargs)
    findings += _callback_f64_findings(closed, mkx, "paged prefix-prefill stage")
    _tok, _logits, prefix_pages = jax.eval_shape(
        prefix_prefill, params, pages, *pargs
    )
    findings += _hash_stable(
        mkx, prefix_prefill, closed,
        (params, prefix_pages, *pargs),
        "paged prefix prefill", "signature-hash",
    )

    # -- decode stage ------------------------------------------------------
    mkd = lambda rule, detail, msg: Finding(
        PASS, rule, f"configs:{name}", "paged_decode", detail, msg
    )
    decode = P.make_paged_decode_fn(dm)
    table = jax.ShapeDtypeStruct((slots, blocks_per_slot), jnp.int32)
    tokens = jax.ShapeDtypeStruct((slots,), jnp.int32)
    positions = jax.ShapeDtypeStruct((slots,), jnp.int32)
    samp = _sampling_structs(slots)
    closed = jax.make_jaxpr(decode)(
        params, pages, table, tokens, positions, *samp
    )
    findings += _callback_f64_findings(closed, mkd, "paged decode stage")
    out_tokens, out_pages = jax.eval_shape(
        decode, params, pages, table, tokens, positions, *samp
    )
    findings += _hash_stable(
        mkd, decode, closed,
        (params, out_pages, table, out_tokens, positions, *samp),
        "paged decode", "signature-hash",
    )
    for stage, mk, out in (
        ("prefill", mkp, prefill_pages),
        ("prefix prefill", mkx, prefix_pages),
        ("decode", mkd, out_pages),
    ):
        findings += _cache_drift(
            mk, pages, out, f"the paged {stage} stage's page pytree",
            "pages-drift",
            "the pool is one fixed allocation for the engine's life — "
            "donation and the jit cache both break",
        )
    return findings


def _check_spec_stage_jaxprs(name: str, bundle) -> list[Finding]:
    """Speculative-decode stage contracts (causal-LM configs only).

    The spec pipeline (``serve/pool/spec.py``) adds TWO executables —
    the draft's k-step propose scan and the target's one fused k-verify
    — and each carries the full contract set independently: no host
    callbacks anywhere (the per-slot PRNG fold, the acceptance uniforms,
    and the residual re-draw all live in-trace), no f64/complex128 (the
    distributions are explicit f32), and the step-over-step canonical
    hash stable — propose's output draft pages feed the next propose,
    verify's output target pages feed the next verify, so the engine's
    zero-recompile contract extends to every sampled speculative tick.
    Traced with the config's own model standing in as its draft (the
    contracts pin program SHAPE; the engine accepts any same-vocab
    draft)."""
    import jax
    import jax.numpy as jnp

    from consensusml_tpu.serve import decode as D
    from consensusml_tpu.serve import pool as P

    if bundle.model is None or not D.supports_decode(bundle.model):
        return []
    findings: list[Finding] = []
    dm = D.DecodeModel.wrap(bundle.model)
    slots, max_len, bs, k = 4, min(dm.max_len, 32), 8, 2
    blocks_per_slot = max_len // bs
    cols = P.spec_table_cols(blocks_per_slot, bs, k)
    num_blocks = slots * blocks_per_slot + 1
    probe = jax.eval_shape(bundle.init_params, jax.random.key(0))
    params = probe[0] if isinstance(probe, tuple) and len(probe) == 2 else probe
    pages = jax.eval_shape(lambda: P.init_pages(dm, num_blocks, bs))
    table = jax.ShapeDtypeStruct((slots, cols), jnp.int32)
    tokens = jax.ShapeDtypeStruct((slots,), jnp.int32)
    positions = jax.ShapeDtypeStruct((slots,), jnp.int32)
    samp = _sampling_structs(slots)

    # -- draft propose scan ------------------------------------------------
    mkp = lambda rule, detail, msg: Finding(
        PASS, rule, f"configs:{name}", "spec_propose", detail, msg
    )
    propose = P.make_draft_propose_fn(dm, k)
    closed = jax.make_jaxpr(propose)(
        params, pages, table, tokens, positions, *samp
    )
    findings += _callback_f64_findings(closed, mkp, "spec propose stage")
    props, q_sel, q_probs, out_dpages = jax.eval_shape(
        propose, params, pages, table, tokens, positions, *samp
    )
    findings += _hash_stable(
        mkp, propose, closed,
        (params, out_dpages, table, tokens, positions, *samp),
        "spec propose", "signature-hash",
    )

    # -- fused k-verify ----------------------------------------------------
    mkv = lambda rule, detail, msg: Finding(
        PASS, rule, f"configs:{name}", "spec_verify", detail, msg
    )
    verify = P.make_verify_fn(dm, k)
    closed = jax.make_jaxpr(verify)(
        params, pages, table, tokens, props, q_sel, q_probs, positions,
        *samp,
    )
    findings += _callback_f64_findings(closed, mkv, "spec verify stage")
    _n, _y, out_pages = jax.eval_shape(
        verify, params, pages, table, tokens, props, q_sel, q_probs,
        positions, *samp,
    )
    findings += _hash_stable(
        mkv, verify, closed,
        (params, out_pages, table, tokens, props, q_sel, q_probs,
         positions, *samp),
        "spec verify", "signature-hash",
    )
    for stage, mk, out in (
        ("propose", mkp, out_dpages),
        ("verify", mkv, out_pages),
    ):
        findings += _cache_drift(
            mk, pages, out, f"the spec {stage} stage's page pytree",
            "pages-drift",
            "the pool is one fixed allocation for the engine's life — "
            "donation and the jit cache both break",
        )
    return findings


def _check_fused_attention_jaxprs(name: str, bundle) -> list[Finding]:
    """Fused paged-attention kernel-tier contracts (causal-LM configs).

    The kernel tier (``models/paged_attention.py``) replaces the
    two-step gather + dense attention in the paged decode step and the
    spec k-verify window with ONE pallas pass per layer. The contract
    set, per stage, traced on the exact jit the engine would run under
    ``attn_impl="interpret"`` (same jaxpr as the compiled TPU program
    modulo lowering):

    - ``fused-active`` — the traced program contains ``pallas_call``
      equations at all: an impl that silently composed the gather
      reference would pass every numeric parity pin while fusing
      nothing (the regression the tier exists to prevent). A NEGATIVE
      fixture rides along: the gather impl of the same stage must trace
      to ZERO ``pallas_call``s — if it doesn't, the detector can no
      longer distinguish fused from unfused and its PASSes are vacuous;
    - ``kernel-count`` — exactly ONE ``pallas_call`` per layer per
      stage. More means a layer split its pass (extra HBM round-trips);
      fewer means a layer fell back to the gather path;
    - the shared purity contracts (no host callbacks, no f64) and the
      step-over-step canonical-hash stability — the fused stages
      inherit the zero-recompile contract unchanged.
    """
    import jax
    import jax.numpy as jnp

    from consensusml_tpu.serve import decode as D
    from consensusml_tpu.serve import pool as P

    if bundle.model is None or not D.supports_decode(bundle.model):
        return []
    findings: list[Finding] = []
    dm = D.DecodeModel.wrap(bundle.model)
    layers = dm.model.config.layers
    slots, max_len, bs, k = 4, min(dm.max_len, 32), 8, 2
    blocks_per_slot = max_len // bs
    num_blocks = slots * blocks_per_slot + 1
    cols = P.spec_table_cols(blocks_per_slot, bs, k)
    probe = jax.eval_shape(bundle.init_params, jax.random.key(0))
    params = probe[0] if isinstance(probe, tuple) and len(probe) == 2 else probe
    pages = jax.eval_shape(lambda: P.init_pages(dm, num_blocks, bs))
    tokens = jax.ShapeDtypeStruct((slots,), jnp.int32)
    positions = jax.ShapeDtypeStruct((slots,), jnp.int32)
    samp = _sampling_structs(slots)

    def _kernel_findings(mk, closed, what):
        n = count_primitives(closed).get("pallas_call", 0)
        if n == 0:
            return [
                mk(
                    "fused-active", "two-step-fallback",
                    f"{what} under attn_impl='interpret' traces ZERO "
                    "pallas_calls — the kernel tier silently composed "
                    "the gather reference instead of fusing",
                )
            ]
        if n != layers:
            return [
                mk(
                    "kernel-count", "pallas_call",
                    f"{what} traces {n} pallas_call(s) but the fused "
                    f"contract is exactly one per layer ({layers}): "
                    "more = a layer's pass split (extra HBM "
                    "round-trips), fewer = a layer off the kernel path",
                )
            ]
        return []

    # -- fused decode step -------------------------------------------------
    mkd = lambda rule, detail, msg: Finding(
        PASS, rule, f"configs:{name}", "fused_paged_decode", detail, msg
    )
    dec_table = jax.ShapeDtypeStruct((slots, blocks_per_slot), jnp.int32)
    decode = P.make_paged_decode_fn(dm, attn_impl="interpret")
    closed = jax.make_jaxpr(decode)(
        params, pages, dec_table, tokens, positions, *samp
    )
    findings += _kernel_findings(mkd, closed, "the fused paged decode step")
    findings += _callback_f64_findings(closed, mkd, "fused paged decode stage")
    out_tokens, out_pages = jax.eval_shape(
        decode, params, pages, dec_table, tokens, positions, *samp
    )
    findings += _hash_stable(
        mkd, decode, closed,
        (params, out_pages, dec_table, out_tokens, positions, *samp),
        "fused paged decode", "signature-hash",
    )
    # negative fixture: the gather impl of the SAME stage must fuse
    # nothing, or the fused-active detector above proves nothing
    gather_decode = P.make_paged_decode_fn(dm, attn_impl="gather")
    unfused = count_primitives(
        jax.make_jaxpr(gather_decode)(
            params, pages, dec_table, tokens, positions, *samp
        )
    ).get("pallas_call", 0)
    if unfused != 0:
        findings.append(
            mkd(
                "fused-active", "negative-fixture",
                f"the GATHER decode stage traces {unfused} "
                "pallas_call(s); the fused-active detector can no "
                "longer tell fused from unfused apart",
            )
        )

    # -- fused spec k-verify window ----------------------------------------
    mkv = lambda rule, detail, msg: Finding(
        PASS, rule, f"configs:{name}", "fused_spec_verify", detail, msg
    )
    spec_table = jax.ShapeDtypeStruct((slots, cols), jnp.int32)
    props, q_sel, q_probs, _dp = jax.eval_shape(
        P.make_draft_propose_fn(dm, k),
        params, pages, spec_table, tokens, positions, *samp,
    )
    verify = P.make_verify_fn(dm, k, attn_impl="interpret")
    closed = jax.make_jaxpr(verify)(
        params, pages, spec_table, tokens, props, q_sel, q_probs,
        positions, *samp,
    )
    findings += _kernel_findings(mkv, closed, "the fused spec verify window")
    findings += _callback_f64_findings(closed, mkv, "fused spec verify stage")
    _n, _y, v_pages = jax.eval_shape(
        verify, params, pages, spec_table, tokens, props, q_sel, q_probs,
        positions, *samp,
    )
    findings += _hash_stable(
        mkv, verify, closed,
        (params, v_pages, spec_table, tokens, props, q_sel, q_probs,
         positions, *samp),
        "fused spec verify", "signature-hash",
    )
    for stage, mk, out in (
        ("decode", mkd, out_pages),
        ("verify", mkv, v_pages),
    ):
        findings += _cache_drift(
            mk, pages, out, f"the fused {stage} stage's page pytree",
            "pages-drift",
            "the pool is one fixed allocation for the engine's life — "
            "donation and the jit cache both break",
        )
    return findings


def check_config(name: str, *, scale: str = "smoke") -> list[Finding]:
    """All jaxpr contracts for one config (incl. the serving decode
    step, BOTH paged serving stages, the speculative propose/verify
    pair, and the fused paged-attention kernel tier on causal-LM
    configs)."""
    from consensusml_tpu import configs

    bundle = configs.build(name, scale=scale)
    findings = _check_step_jaxpr(name, bundle)
    findings.extend(_check_collective_count(name, bundle))
    findings.extend(_check_decode_jaxpr(name, bundle))
    findings.extend(_check_paged_stage_jaxprs(name, bundle))
    findings.extend(_check_spec_stage_jaxprs(name, bundle))
    findings.extend(_check_fused_attention_jaxprs(name, bundle))
    return findings


def check_all_configs(*, scale: str = "smoke") -> list[Finding]:
    from consensusml_tpu import configs

    findings: list[Finding] = []
    for name in configs.names():
        try:
            findings.extend(check_config(name, scale=scale))
        except Exception as e:  # a config that cannot trace IS a finding
            findings.append(
                Finding(
                    PASS, "trace-error", f"configs:{name}", "", type(e).__name__,
                    f"tracing the {name} train step failed: {e}",
                )
            )
    # the fused one-pass wire is config-independent (engages per codec,
    # not per config); its contracts ride the same pass
    try:
        findings.extend(check_fused_wire())
    except Exception as e:
        findings.append(
            Finding(
                PASS, "trace-error", "fused-wire", "", type(e).__name__,
                f"tracing the fused gossip wire failed: {e}",
            )
        )
    return findings
