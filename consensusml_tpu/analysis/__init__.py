"""cml-check: JAX-aware static analysis for the gossip training stack.

Nine passes (CLI: ``tools/cml_check.py --all``; docs:
``docs/static_analysis.md``):

- :mod:`~consensusml_tpu.analysis.host_sync` — AST lint for host/device
  hazards: syncs and numpy/time/branching inside traced code, plus a
  baselined inventory of every intentional host sync in the package.
- :mod:`~consensusml_tpu.analysis.schedule` — statically materializes
  each topology's per-rank ppermute schedules from the engine's own
  bucket plans and proves bijectivity, cross-rank agreement and
  endpoint matching — the static deadlock check for the COLLECTIVE wire.
- :mod:`~consensusml_tpu.analysis.jaxpr_contracts` — traces each
  config's train step on CPU and asserts: no host callbacks, no f64
  promotion, collective counts match the verified schedule, and two
  consecutive rounds share one compilation.
- :mod:`~consensusml_tpu.analysis.locks` — lock-discipline race lint
  over :func:`guarded_by`-annotated classes (the threaded host side:
  prefetcher, native ring, metrics registry, watchdog, hot-swap
  watcher, serve front-end): unguarded access, bare acquire/release,
  and guarded-reference escape analysis.
- :mod:`~consensusml_tpu.analysis.threads` — thread-and-handler
  inventory: every ``threading.Thread``/``signal.signal``/excepthook
  site cross-checked against ``docs/threads.md``, plus thread-spawning
  classes whose lock contracts are undeclared.
- :mod:`~consensusml_tpu.analysis.lockorder` — static lock-ordering
  graph (nested ``with`` scopes composed through the call graph and
  typed attributes): cycles and plain-Lock self-re-entry are
  potential-deadlock findings — the static deadlock check for the
  THREADED host side, and the reference model for the opt-in runtime
  sanitizer :mod:`~consensusml_tpu.analysis.lockdep`.
- :mod:`~consensusml_tpu.analysis.docs_drift` — metric-schema drift:
  every ``consensusml_*`` family emitted in code must appear in
  ``docs/observability.md``, and doc entries no code emits are stale.
- :mod:`~consensusml_tpu.analysis.protocol_models` — bounded
  explicit-state model checking (engine in
  :mod:`~consensusml_tpu.analysis.model`) of the serving control-plane
  protocols: BlockPool/PrefixIndex refcounts, the request lifecycle
  composed with hot-swap generation flips, and membership epoch
  pin/advance — exhaustively over every interleaving of the abstract
  actors, with BFS-minimal counterexample traces and recorded-trace
  conformance (:mod:`~consensusml_tpu.analysis.conformance`) tying the
  abstractions back to the real classes.
- :mod:`~consensusml_tpu.analysis.lifecycle` — resource-lifecycle
  escape lint: every pool block acquisition, slot occupation and
  OS-handle open must dominate its release on all paths including
  exception edges; ownership transfer out of the function is the
  exemption.

This ``__init__`` stays import-light (annotations + findings only, no
jax): runtime modules import :func:`guarded_by` from here at module
load. The passes are imported as submodules by the CLI and tests.
"""

from consensusml_tpu.analysis.annotations import guarded_by  # noqa: F401
from consensusml_tpu.analysis.findings import (  # noqa: F401
    Baseline,
    Finding,
    load_baseline,
    render_report,
    split_suppressed,
    to_json,
)

__all__ = [
    "guarded_by",
    "Finding",
    "Baseline",
    "load_baseline",
    "split_suppressed",
    "render_report",
    "to_json",
]
