"""Docs-drift pass: the metric schema in code and in docs must agree.

``docs/observability.md`` is the contract for every ``consensusml_*``
Prometheus family the stack emits — dashboards, alerts, and the cluster
aggregator are written against it. Families drift in two directions and
both rot silently:

- ``undocumented-metric`` — a family registered in code
  (``registry.counter/gauge/histogram("consensusml_...")``) that the doc
  never mentions: invisible to anyone reading the schema;
- ``stale-doc-metric`` — a family the doc lists that no code emits any
  more: an alert written against it will simply never fire.

Detection is static: one AST walk over the package + the CLI entry
points collects every string literal passed as the metric name to a
``counter``/``gauge``/``histogram`` call (f-strings record their literal
PREFIX — ``f"consensusml_{k}"`` marks the whole prefix as dynamically
emitted, so doc entries under it are exempt from the stale rule only
when the prefix is more specific than the bare ``consensusml_``
namespace); the doc side is every ``consensusml_\\w+`` token in
``docs/observability.md``.

Same baseline mechanics as the host-sync lint: a deliberate exception
(a family documented as a wildcard row, e.g. the ``MetricsLogger``'s
per-field gauges) is suppressed by its finding id in
``.cml-check-baseline`` with a comment saying why, and stale baseline
entries are reported when the drift gets fixed for real.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable

from consensusml_tpu.analysis.findings import Finding

__all__ = ["emitted_families", "documented_families", "run", "check_repo"]

PASS = "docs-drift"
DOC_RELPATH = os.path.join("docs", "observability.md")
_METRIC_CALLS = {"counter", "gauge", "histogram"}
_NAME_RE = re.compile(r"consensusml_[a-z0-9_]*[a-z0-9]")


def _symbol_of(stack: list[str]) -> str:
    return ".".join(stack)


def emitted_families(
    py_files: Iterable[str], repo_root: str
) -> tuple[dict[str, tuple[str, str, int]], set[str]]:
    """Scan sources for metric registrations.

    Returns ``(families, dynamic_prefixes)``: ``families`` maps each
    literal family name to its first (repo-relative path, symbol, line)
    emission site; ``dynamic_prefixes`` holds the literal prefixes of
    f-string metric names (dynamically composed families the stale rule
    must not flag).
    """
    families: dict[str, tuple[str, str, int]] = {}
    dynamic: set[str] = set()
    for path in sorted(py_files):
        rel = os.path.relpath(os.path.abspath(path), repo_root)
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            continue

        stack: list[str] = []

        def visit(node: ast.AST) -> None:
            scoped = isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            )
            if scoped:
                stack.append(node.name)
            if isinstance(node, ast.Call):
                fn = node.func
                attr = (
                    fn.attr
                    if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None
                )
                if attr in _METRIC_CALLS and node.args:
                    arg = node.args[0]
                    if (
                        isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)
                        and arg.value.startswith("consensusml_")
                    ):
                        families.setdefault(
                            arg.value,
                            (rel, _symbol_of(stack), node.lineno),
                        )
                    elif isinstance(arg, ast.JoinedStr) and arg.values:
                        head = arg.values[0]
                        if (
                            isinstance(head, ast.Constant)
                            and isinstance(head.value, str)
                            and head.value.startswith("consensusml_")
                            # a bare f"consensusml_{k}" must not exempt
                            # the whole namespace from the stale rule
                            and len(head.value) > len("consensusml_")
                        ):
                            dynamic.add(head.value)
            # any f-string in the module whose head is a consensusml_
            # prefix marks dynamic composition even when the call passes
            # it through a variable (utils/logging.py's _PROM_SAFE path)
            if isinstance(node, ast.JoinedStr) and node.values:
                head = node.values[0]
                if (
                    isinstance(head, ast.Constant)
                    and isinstance(head.value, str)
                    and head.value.startswith("consensusml_")
                    and len(head.value) > len("consensusml_")
                ):
                    dynamic.add(head.value)
            for child in ast.iter_child_nodes(node):
                visit(child)
            if scoped:
                stack.pop()

        visit(tree)
    return families, dynamic


def documented_families(doc_path: str) -> set[str]:
    """Family names the doc commits to. Wildcard/namespace references —
    ``consensusml_serve_*`` prose, ``consensusml_tpu/obs`` module paths,
    ``consensusml_tpu.obs`` imports — are not family names and are
    skipped (the trailing ``*``/``/``/``.`` gives them away)."""
    try:
        with open(doc_path) as f:
            text = f.read()
    except OSError:
        return set()
    out: set[str] = set()
    for m in _NAME_RE.finditer(text):
        tail = text[m.end():m.end() + 2]
        if tail[:1] in ("*", "/", ".") or tail == "_*":
            continue
        out.add(m.group(0))
    return out


def default_sources(repo_root: str) -> list[str]:
    """The emitting surface: the package plus the CLI entry points that
    register families directly (train/bench/loadgen)."""
    out: list[str] = []
    pkg = os.path.join(repo_root, "consensusml_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        out.extend(
            os.path.join(dirpath, f)
            for f in filenames
            if f.endswith(".py")
        )
    for extra in ("train.py", "bench.py", "worker.py"):
        p = os.path.join(repo_root, extra)
        if os.path.exists(p):
            out.append(p)
    tools = os.path.join(repo_root, "tools")
    if os.path.isdir(tools):
        out.extend(
            os.path.join(tools, f)
            for f in os.listdir(tools)
            if f.endswith(".py")
        )
    return out


def run(
    repo_root: str,
    py_files: Iterable[str] | None = None,
    doc_path: str | None = None,
) -> list[Finding]:
    files = (
        list(py_files) if py_files is not None else default_sources(repo_root)
    )
    doc = (
        doc_path
        if doc_path is not None
        else os.path.join(repo_root, DOC_RELPATH)
    )
    emitted, dynamic = emitted_families(files, repo_root)
    documented = documented_families(doc)
    doc_rel = os.path.relpath(os.path.abspath(doc), repo_root)

    findings: list[Finding] = []
    for name in sorted(set(emitted) - documented):
        rel, symbol, line = emitted[name]
        findings.append(
            Finding(
                PASS,
                "undocumented-metric",
                rel,
                symbol,
                name,
                f"metric family {name!r} is emitted here but missing from "
                f"{doc_rel} — document it (kind + meaning)",
                line,
            )
        )
    # doc names with no literal emission: stale, unless a dynamic
    # f-string prefix covers them (e.g. consensusml_swarm_* composed at
    # runtime would be exempt under the "consensusml_swarm_" prefix)
    for name in sorted(documented - set(emitted)):
        if any(name.startswith(p) for p in dynamic):
            continue
        findings.append(
            Finding(
                PASS,
                "stale-doc-metric",
                doc_rel,
                "<doc>",
                name,
                f"{doc_rel} documents {name!r} but no code emits it — "
                "remove the entry or restore the metric",
                0,
            )
        )
    return findings


def check_repo(repo_root: str) -> list[Finding]:
    """CLI entry (tools/cml_check.py --docs)."""
    return run(repo_root)
