"""Abstract models of the serving control-plane protocols (pass 8).

Three hand-written models, each a faithful abstraction of one host-side
protocol, checked exhaustively by :mod:`.model` over every interleaving
of 2–4 abstract actors up to a depth bound:

- :class:`PoolModel` — BlockPool refcounts + PrefixIndex
  (``serve/pool/blocks.py`` / ``prefix.py``): begin/alloc/extend/adopt/
  pin/unpin/shrink/release with the reuse/cached hooks and COW.
  Invariants: free ∪ Σ owned-with-multiplicity ∪ pins partitions the
  physical blocks, no double-free, no leak, and an indexed refcount-0
  block is revived (adopt) or invalidated (fresh pop) but never both.
- :class:`RequestModel` — the request lifecycle
  (``serve/engine.py``): submit→defer→admit→prefill→decode→{complete,
  preempt-readmit, cancel} interleaved with hot-swap generation flips.
  Invariants: generations are monotone, resident slots never decode at
  a stale generation, no lost stream (a completed request emitted
  exactly its target; continuations never rewind emitted tokens), and
  a preempted stream re-admits exactly once per preemption.
- :class:`MembershipModel` — epoch pin/advance
  (``swarm/membership.py``): in-flight rounds complete against their
  pinned epoch (pinned views survive advance), and the metrics gauge
  never lands at an older epoch (the PR 13 ``_fed_epoch`` claim).

Every model doubles as the conformance oracle: recorded traces from the
real classes (``analysis/conformance.py``) replay through the same
``apply``/``invariant`` code with ``strict=False`` relaxations where
the recording is sequential but the modelled action is atomic
(hot-swap tag updates arrive one resident slot at a time).

Seeded-bug variants (:func:`fixture_specs`) re-introduce real bug
classes — a pre-refcount double-free release, a swap flip that leaves
resident slots at a stale generation tag, an unclaimed membership
gauge feed — and the pass *requires* each to yield a counterexample:
a fixture the checker cannot refute means the detector is broken
(the PR 15 negative-fixture pattern).
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from pathlib import Path
from typing import Iterable, Optional, Sequence

from .findings import Finding
from .model import CheckResult, IllegalAction, check_model

__all__ = [
    "PoolModel",
    "DoubleFreePoolModel",
    "NoInvalidatePoolModel",
    "RequestModel",
    "StaleSwapRequestModel",
    "MembershipModel",
    "UnclaimedGaugeMembershipModel",
    "ModelSpec",
    "builtin_specs",
    "fixture_specs",
    "run_builtin",
]


def _need(cond: bool, why: str) -> None:
    if not cond:
        raise IllegalAction(why)


# ---------------------------------------------------------------------------
# (a) BlockPool refcounts + PrefixIndex
# ---------------------------------------------------------------------------


class PoolModel:
    """Abstract BlockPool + PrefixIndex.

    State (all hashable)::

        free     LIFO stack of free physical ids (last = next pop)
        owned    per slot: None (closed) | tuple of held ids, in row order
        adopted  per slot: frozenset of ids acquired via adopt
        pinned   per block: pin count (COW sources)
        indexed  frozenset of ids the prefix index names
        stale    ghost: indexed ids whose bytes were overwritten without
                 invalidation (only buggy variants ever populate it)

    Refcounts are *derived* (Σ owned multiplicity + pins) so the
    invariant checks the partition itself, not a shadow counter.
    Labels carry concrete block ids when replayed from a recording, so
    replay also proves the model's LIFO pop order (including the
    ``cached_hook`` bottom-park) matches the real pool's.
    """

    name = "pool-refcount"
    subject = "consensusml_tpu/serve/pool/blocks.py"

    def __init__(
        self,
        num_slots: int = 2,
        usable_blocks: int = 3,
        blocks_per_slot: int = 2,
    ):
        self.num_slots = num_slots
        self.usable_blocks = usable_blocks
        self.blocks_per_slot = blocks_per_slot

    def initial(self):
        free = tuple(range(self.usable_blocks, 0, -1))  # pops 1, 2, 3, ...
        owned = (None,) * self.num_slots
        adopted = (frozenset(),) * self.num_slots
        pinned = (0,) * (self.usable_blocks + 1)
        return (free, owned, adopted, pinned, frozenset(), frozenset())

    # -- transition helpers -------------------------------------------------

    def _holders(self, owned, pinned) -> Counter:
        holders: Counter = Counter()
        for blocks in owned:
            if blocks:
                holders.update(blocks)
        for b, n in enumerate(pinned):
            if n:
                holders[b] += n
        return holders

    def _free_block(self, free: list, b: int, indexed: frozenset) -> None:
        """Return ``b`` to the free stack: bottom when the prefix index
        still names its bytes (``cached_hook``), top otherwise."""
        if b in indexed:
            free.insert(0, b)
        else:
            free.append(b)

    def _pop_fresh(self, free: list, indexed: set, stale: set) -> int:
        _need(bool(free), "no free blocks")
        b = free.pop()
        # reuse_hook: the index forgets the recycled bytes eagerly
        indexed.discard(b)
        stale.discard(b)
        return b

    def labels(self, state):
        free, owned, adopted, pinned, indexed, stale = state
        for s in range(self.num_slots):
            if owned[s] is None:
                yield ("begin", s)
                continue
            if len(owned[s]) < self.blocks_per_slot and free:
                yield ("extend", s)
            held = set(owned[s])
            for b in sorted(indexed):
                if b not in held and len(owned[s]) < self.blocks_per_slot:
                    yield ("adopt", s, (b,))
            if owned[s] and owned[s][0] not in indexed:
                yield ("index", s)
            if len(owned[s]) > 1:
                yield ("shrink", s, 1)
            yield ("release", s)
            for b in sorted(adopted[s]):
                if free:
                    yield ("cow", s, b)
        for b in sorted(indexed):
            if pinned[b] == 0:
                yield ("pin", b)
        for b in range(1, self.usable_blocks + 1):
            if pinned[b] > 0:
                yield ("unpin", b)

    def apply(self, state, label):
        free, owned, adopted, pinned, indexed, stale = state
        free = list(free)
        owned = list(owned)
        adopted = list(adopted)
        pinned = list(pinned)
        indexed = set(indexed)
        stale = set(stale)
        op = label[0]

        if op == "begin":
            s = label[1]
            _need(owned[s] is None, f"slot {s} already owns blocks")
            owned[s] = ()
            adopted[s] = frozenset()
        elif op in ("extend", "alloc"):
            s = label[1]
            want = label[2] if len(label) > 2 else None  # recorded ids
            if op == "alloc":
                _need(owned[s] is None, f"slot {s} already owns blocks")
                owned[s] = ()
                adopted[s] = frozenset()
            _need(owned[s] is not None, f"slot {s} owns nothing")
            n = len(want) if want is not None else 1
            _need(
                len(owned[s]) + n <= self.blocks_per_slot,
                f"slot {s} would exceed blocks_per_slot",
            )
            got = []
            for _ in range(n):
                got.append(self._pop_fresh(free, indexed, stale))
            if want is not None and tuple(got) != tuple(want):
                raise IllegalAction(
                    f"free-list order mismatch: model pops {tuple(got)}, "
                    f"recording saw {tuple(want)}"
                )
            owned[s] = owned[s] + tuple(got)
        elif op == "adopt":
            s, blocks = label[1], label[2]
            _need(owned[s] is not None, f"slot {s} owns nothing; begin first")
            _need(
                len(owned[s]) + len(blocks) <= self.blocks_per_slot,
                f"slot {s} would exceed blocks_per_slot",
            )
            holders = self._holders(owned, pinned)
            for b in blocks:
                _need(
                    b not in owned[s], f"slot {s} already holds block {b}"
                )
                # only live blocks or current indexed bytes are adoptable
                _need(
                    holders[b] > 0 or b in indexed,
                    f"block {b} is neither live nor indexed",
                )
                if holders[b] == 0:  # revive off the free list
                    _need(b in free, f"block {b} has no holder and no bytes")
                    free.remove(b)
                owned[s] = owned[s] + (b,)
                adopted[s] = adopted[s] | {b}
                holders[b] += 1
        elif op == "index":
            s = label[1]
            _need(bool(owned[s]), f"slot {s} owns nothing to index")
            indexed.add(owned[s][0])
        elif op == "pin":
            b = label[1]
            holders = self._holders(owned, pinned)
            _need(
                holders[b] > 0 or b in indexed,
                f"block {b} is neither live nor indexed",
            )
            if holders[b] == 0:
                _need(b in free, f"block {b} has no holder and no bytes")
                free.remove(b)
            pinned[b] += 1
        elif op == "unpin":
            b = label[1]
            _need(pinned[b] > 0, f"block {b} is not pinned")
            pinned[b] -= 1
            holders = self._holders(owned, pinned)
            if holders[b] == 0:
                self._free_block(free, b, indexed)
        elif op == "shrink":
            s, keep = label[1], label[2]
            _need(owned[s] is not None, f"slot {s} owns nothing")
            _need(keep >= 1, "keep_blocks must be >= 1")
            row = list(owned[s])
            ad = set(adopted[s])
            while len(row) > keep:
                b = row.pop()
                ad.discard(b)
                holders = self._holders([tuple(row)] + [
                    o for i, o in enumerate(owned) if i != s
                ], pinned)
                if holders[b] == 0:
                    self._free_block(free, b, indexed)
            owned[s] = tuple(row)
            adopted[s] = frozenset(ad)
        elif op == "release":
            s = label[1]
            _need(owned[s] is not None, f"slot {s} owns nothing (double-free)")
            row = list(owned[s])
            owned[s] = None
            adopted[s] = frozenset()
            self._do_release(free, owned, pinned, row, indexed)
        elif op == "cow":
            s, b = label[1], label[2]
            _need(owned[s] is not None, f"slot {s} owns nothing")
            _need(b in adopted[s], f"block {b} is not adopted by slot {s}")
            fresh = self._pop_fresh(free, indexed, stale)
            pos = owned[s].index(b)
            owned[s] = owned[s][:pos] + (fresh,) + owned[s][pos + 1 :]
            adopted[s] = adopted[s] - {b}
            holders = self._holders(owned, pinned)
            if holders[b] == 0:
                self._free_block(free, b, indexed)
        else:
            raise IllegalAction(f"unknown action {op!r}")

        return (
            tuple(free),
            tuple(owned),
            tuple(adopted),
            tuple(pinned),
            frozenset(indexed),
            frozenset(stale),
        )

    def _do_release(self, free, owned, pinned, row, indexed):
        """Release one slot's former holding ``row`` (already detached
        from ``owned``): each block returns to the free list only when
        its LAST holder lets go."""
        for i, b in enumerate(row):
            # remaining references: other slots + pins + the not-yet-
            # released tail of this row
            remaining = self._holders(owned, pinned)
            for later in row[i + 1 :]:
                remaining[later] += 1
            if remaining[b] == 0:
                self._free_block(free, b, indexed)

    def invariant(self, state) -> Optional[str]:
        free, owned, adopted, pinned, indexed, stale = state
        holders = self._holders(owned, pinned)
        for s in range(self.num_slots):
            blocks = owned[s]
            if blocks is None:
                continue
            if len(set(blocks)) != len(blocks):
                return f"aliasing: slot {s} holds a block twice: {blocks}"
            if len(blocks) > self.blocks_per_slot:
                return f"capacity: slot {s} exceeds blocks_per_slot"
            if not adopted[s] <= set(blocks):
                return f"aliasing: slot {s} adopted set escapes its owned list"
        if len(set(free)) != len(free):
            return f"double-free: duplicate entry on the free list: {free}"
        for b in free:
            if not 1 <= b <= self.usable_blocks:
                return f"partition: free list entry {b} out of range"
            if holders[b]:
                return f"double-free: block {b} is both free and held"
        free_set = set(free)
        for b in range(1, self.usable_blocks + 1):
            if holders[b] == 0 and b not in free_set:
                return f"leak: block {b} has no holder and is not free"
        if holders[0] or 0 in free_set:
            return "partition: trash block was allocated"
        both = indexed & stale
        if both:
            return (
                f"revive-invalidate: index entry for block {sorted(both)[0]} "
                "survived a fresh pop (bytes overwritten, entry live)"
            )
        return None


class DoubleFreePoolModel(PoolModel):
    """Seeded bug: release returns every block to the free list
    unconditionally — the pre-refcount behaviour. Two slots sharing a
    prefix block make the first release hand the shared block back
    while the second still decodes against it."""

    name = "pool-double-free"

    def _do_release(self, free, owned, pinned, row, indexed):
        for b in row:
            self._free_block(free, b, indexed)


class NoInvalidatePoolModel(PoolModel):
    """Seeded bug: a fresh pop skips ``reuse_hook`` — the prefix index
    keeps naming bytes that a new stream just overwrote, so a later
    admission adopts garbage (revive AND invalidate)."""

    name = "pool-stale-index"

    def _pop_fresh(self, free, indexed, stale):
        _need(bool(free), "no free blocks")
        b = free.pop()
        if b in indexed:  # entry survives the overwrite: now stale
            stale.add(b)
        return b


# ---------------------------------------------------------------------------
# (b) request lifecycle × hot-swap generation flips
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _Req:
    phase: str = "new"  # new|queued|admitted|decoding|done|cancelled
    slot: int = -1
    emitted: int = 0
    emitted_seen: int = 0  # ghost: high-water mark (no lost stream)
    preempts: int = 0
    readmits: int = 0
    deferred: bool = False


class RequestModel:
    """Abstract request state machine composed with hot-swap flips.

    ``strict=True`` is the bounded DFS configuration: per-request token
    targets, bounded preemptions/generations, and the atomic-flip
    invariant that every resident slot's generation tag equals the
    engine generation. ``strict=False`` is the conformance-replay
    configuration: recorded hot-swap events arrive one resident slot at
    a time, targets vary per request, and admissions carry the recorded
    ``continuation`` flag which must agree with the model's own
    preempt/readmit accounting.
    """

    name = "request-lifecycle"
    subject = "consensusml_tpu/serve/engine.py"

    def __init__(
        self,
        n_requests: int = 2,
        n_slots: int = 1,
        target_tokens: int = 2,
        max_generation: int = 2,
        max_preempts: int = 1,
        strict: bool = True,
    ):
        self.n_requests = n_requests
        self.n_slots = n_slots
        self.target_tokens = target_tokens
        self.max_generation = max_generation
        self.max_preempts = max_preempts
        self.strict = strict

    def initial(self):
        reqs = tuple(_Req() for _ in range(self.n_requests))
        tags = (0,) * self.n_slots
        # (reqs, engine_gen, staged, slot_tags, gen_seen)
        return (reqs, 0, -1, tags, 0)

    def _occupant(self, reqs, s: int) -> int:
        for i, r in enumerate(reqs):
            if r.slot == s and r.phase in ("admitted", "decoding"):
                return i
        return -1

    def labels(self, state):
        reqs, gen, staged, tags, _seen = state
        for i, r in enumerate(reqs):
            if r.phase == "new":
                yield ("submit", i)
            elif r.phase == "queued":
                if not r.deferred:
                    yield ("defer", i)
                for s in range(self.n_slots):
                    if self._occupant(reqs, s) < 0:
                        yield ("admit", i, s)
                yield ("cancel", i)
            elif r.phase == "admitted":
                yield ("prefill", i)
            elif r.phase == "decoding":
                if r.emitted < self.target_tokens:
                    yield ("tick", i)
                else:
                    yield ("complete", i)
                if r.preempts < self.max_preempts:
                    yield ("preempt", i)
        if staged < 0 and gen < self.max_generation:
            yield ("stage",)
        if staged >= 0:
            yield ("flip",)

    def apply(self, state, label):
        reqs, gen, staged, tags, seen = state
        reqs = list(reqs)
        tags = list(tags)
        op = label[0]

        def req(i) -> _Req:
            return reqs[i]

        if op == "submit":
            i = label[1]
            _need(req(i).phase == "new", "already submitted")
            reqs[i] = dataclasses.replace(req(i), phase="queued")
        elif op == "defer":
            i = label[1]
            _need(req(i).phase == "queued", "defer of a non-queued request")
            if self.strict:
                _need(not req(i).deferred, "defer bound reached")
            reqs[i] = dataclasses.replace(req(i), deferred=True)
        elif op == "admit":
            i, s = label[1], label[2]
            r = req(i)
            _need(r.phase == "queued", f"request {i} is not queued")
            _need(0 <= s < self.n_slots, f"slot {s} out of range")
            _need(self._occupant(reqs, s) < 0, f"slot {s} is occupied")
            continuation = r.preempts > r.readmits
            if len(label) > 3:  # recorded continuation flag: must agree
                _need(
                    bool(label[3]) == continuation,
                    f"request {i}: recorded continuation={label[3]} but "
                    f"model has preempts={r.preempts} readmits={r.readmits}",
                )
            reqs[i] = dataclasses.replace(
                r,
                phase="admitted",
                slot=s,
                readmits=r.readmits + (1 if continuation else 0),
            )
            tags[s] = gen
        elif op == "prefill":
            i = label[1]
            r = req(i)
            _need(r.phase == "admitted", f"request {i} was not admitted")
            emitted = r.emitted if r.emitted > 0 else 1
            reqs[i] = dataclasses.replace(r, phase="decoding", emitted=emitted)
        elif op == "tick":
            i = label[1]
            r = req(i)
            _need(r.phase == "decoding", f"request {i} is not decoding")
            if self.strict:
                _need(r.emitted < self.target_tokens, "target reached")
                reqs[i] = dataclasses.replace(r, emitted=r.emitted + 1)
            # replay: one recorded decode event stands for all ticks
        elif op == "complete":
            i = label[1]
            r = req(i)
            if self.strict:
                _need(r.phase == "decoding", f"request {i} is not decoding")
                _need(r.emitted >= self.target_tokens, "stream not finished")
            else:
                _need(
                    r.phase in ("admitted", "decoding"),
                    f"request {i} is not resident",
                )
            reqs[i] = dataclasses.replace(r, phase="done", slot=-1)
        elif op == "preempt":
            i = label[1]
            r = req(i)
            _need(r.phase == "decoding", f"request {i} is not decoding")
            if self.strict:
                _need(r.preempts < self.max_preempts, "preempt bound reached")
            reqs[i] = dataclasses.replace(
                r, phase="queued", slot=-1, preempts=r.preempts + 1
            )
        elif op == "cancel":
            i = label[1]
            r = req(i)
            _need(r.phase == "queued", f"request {i} is not queued")
            reqs[i] = dataclasses.replace(r, phase="cancelled", slot=-1)
        elif op == "stage":
            _need(staged < 0, "a generation is already staged")
            _need(gen < self.max_generation, "generation bound reached")
            staged = gen + 1
        elif op == "flip":
            _need(staged >= 0, "nothing staged")
            gen = staged
            staged = -1
            tags = self._flip_tags(reqs, tags, gen)
        elif op == "observe_swap":
            # replay form: hotswap events land one resident slot at a time
            i, g = label[1], label[2]
            r = req(i)
            _need(
                r.phase in ("admitted", "decoding"),
                f"request {i} observed a swap while not resident",
            )
            _need(
                g >= tags[r.slot],
                f"slot {r.slot} generation moved backwards: "
                f"{tags[r.slot]} -> {g}",
            )
            tags[r.slot] = g
            gen = max(gen, g)
        else:
            raise IllegalAction(f"unknown action {op!r}")

        reqs = tuple(
            dataclasses.replace(
                r, emitted_seen=max(r.emitted_seen, r.emitted)
            )
            for r in reqs
        )
        seen = max(seen, gen)
        return (reqs, gen, staged, tuple(tags), seen)

    def _flip_tags(self, reqs, tags, gen):
        """Atomic flip: every RESIDENT slot's tag follows the engine
        generation in the same step (``Engine._maybe_swap`` updates all
        live slots before the next decode dispatch)."""
        tags = list(tags)
        for s in range(self.n_slots):
            if self._occupant(reqs, s) >= 0:
                tags[s] = gen
        return tags

    def invariant(self, state) -> Optional[str]:
        reqs, gen, staged, tags, seen = state
        if gen < seen:
            return (
                f"generation-monotone: engine generation moved backwards "
                f"({seen} -> {gen})"
            )
        if staged >= 0 and staged != gen + 1 and self.strict:
            return f"stale-stage: staged generation {staged} vs engine {gen}"
        occupants: dict = {}
        for i, r in enumerate(reqs):
            if r.phase in ("admitted", "decoding"):
                if r.slot in occupants:
                    return (
                        f"slot-aliasing: requests {occupants[r.slot]} and "
                        f"{i} both resident in slot {r.slot}"
                    )
                occupants[r.slot] = i
                tag = tags[r.slot]
                if self.strict and tag != gen:
                    return (
                        f"stale-generation: slot {r.slot} decodes at "
                        f"generation {tag} after flip to {gen}"
                    )
                if tag > gen:
                    return (
                        f"stale-generation: slot {r.slot} tagged {tag} "
                        f"ahead of engine generation {gen}"
                    )
            if r.emitted < r.emitted_seen:
                return (
                    f"lost-stream: request {i} rewound emitted tokens "
                    f"({r.emitted_seen} -> {r.emitted})"
                )
            if self.strict and r.emitted > self.target_tokens:
                return (
                    f"lost-stream: request {i} emitted past its target "
                    f"({r.emitted} > {self.target_tokens})"
                )
            if self.strict and r.phase == "done":
                if r.emitted != self.target_tokens:
                    return (
                        f"lost-stream: request {i} completed with "
                        f"{r.emitted}/{self.target_tokens} tokens"
                    )
            if r.readmits > r.preempts:
                return (
                    f"readmit-accounting: request {i} re-admitted "
                    f"{r.readmits}x for {r.preempts} preemptions"
                )
            if r.phase == "done" and r.readmits != r.preempts:
                return (
                    f"readmit-accounting: request {i} completed with a "
                    f"preemption never re-admitted"
                )
        return None


class StaleSwapRequestModel(RequestModel):
    """Seeded bug: the flip updates the engine generation but leaves
    resident slots' generation tags untouched — a mid-stream request
    keeps decoding against the pre-swap parameters."""

    name = "request-stale-swap"

    def _flip_tags(self, reqs, tags, gen):
        return list(tags)


# ---------------------------------------------------------------------------
# (c) membership epoch pin/advance
# ---------------------------------------------------------------------------


class MembershipModel:
    """Abstract MembershipController: round actors pin the current
    epoch and complete against it; advancer actors advance the epoch
    and then feed the membership gauge from their (possibly stale)
    view — the feed claim (``_fed_epoch``) makes the gauge monotone
    no matter how feeds interleave with further advances."""

    name = "membership-epoch"
    subject = "consensusml_tpu/swarm/membership.py"

    def __init__(
        self,
        n_rounds: int = 2,
        n_advancers: int = 2,
        max_epoch: int = 3,
        claimed: bool = True,
    ):
        self.n_rounds = n_rounds
        self.n_advancers = n_advancers
        self.max_epoch = max_epoch
        self.claimed = claimed

    def initial(self):
        # (epoch, retained, pins, round_pin, pending_feed, fed, fed_seen)
        return (
            0,
            frozenset({0}),
            (),
            (-1,) * self.n_rounds,
            (-1,) * self.n_advancers,
            0,
            0,
        )

    def _pin_count(self, pins, e: int) -> int:
        return dict(pins).get(e, 0)

    def _with_pin(self, pins, e: int, delta: int):
        d = dict(pins)
        d[e] = d.get(e, 0) + delta
        if d[e] == 0:
            del d[e]
        return tuple(sorted(d.items()))

    def labels(self, state):
        epoch, retained, pins, round_pin, pending, fed, _seen = state
        for a in range(self.n_rounds):
            if round_pin[a] < 0:
                yield ("pin", a)
            else:
                yield ("complete", a)
        for v in range(self.n_advancers):
            if pending[v] < 0 and epoch < self.max_epoch:
                yield ("advance", v)
            if pending[v] >= 0:
                yield ("feed", v)

    def apply(self, state, label):
        epoch, retained, pins, round_pin, pending, fed, seen = state
        round_pin = list(round_pin)
        pending = list(pending)
        op = label[0]

        if op == "pin":
            a = label[1]
            _need(round_pin[a] < 0, f"round {a} already holds a pin")
            round_pin[a] = epoch
            pins = self._with_pin(pins, epoch, +1)
        elif op == "complete":
            a = label[1]
            e = round_pin[a]
            _need(e >= 0, f"round {a} holds no pin")
            round_pin[a] = -1
            pins = self._with_pin(pins, e, -1)
            if e != epoch and self._pin_count(pins, e) == 0:
                retained = retained - {e}
        elif op == "advance":
            v = label[1]
            _need(pending[v] < 0, f"advancer {v} has an unfed epoch")
            _need(epoch < self.max_epoch, "epoch bound reached")
            new = epoch + 1
            # retired views survive only while pinned
            retained = frozenset(
                {new} | {e for e in retained if self._pin_count(pins, e) > 0}
            )
            epoch = new
            pending[v] = new
        elif op == "feed":
            v = label[1]
            e = pending[v]
            _need(e >= 0, f"advancer {v} has nothing to feed")
            pending[v] = -1
            fed = self._feed(fed, e)
        else:
            raise IllegalAction(f"unknown action {op!r}")

        seen = max(seen, fed)
        return (
            epoch, retained, pins, tuple(round_pin), tuple(pending), fed, seen
        )

    def _feed(self, fed: int, e: int) -> int:
        # the _fed_epoch claim: only a >= epoch may land on the gauge
        return max(fed, e) if self.claimed else e

    def invariant(self, state) -> Optional[str]:
        epoch, retained, pins, round_pin, pending, fed, seen = state
        if fed < seen:
            return (
                f"gauge-regression: membership gauge fed at epoch {fed} "
                f"after already reporting {seen}"
            )
        if fed > epoch:
            return f"gauge-ahead: gauge epoch {fed} > current epoch {epoch}"
        if epoch not in retained:
            return f"retention: current epoch {epoch} not retained"
        for a, e in enumerate(round_pin):
            if e >= 0 and e not in retained:
                return (
                    f"pinned-view-pruned: round {a}'s pinned epoch {e} is "
                    "no longer retrievable"
                )
        return None


class UnclaimedGaugeMembershipModel(MembershipModel):
    """Seeded bug: the gauge feed skips the ``_fed_epoch`` claim, so
    two racing advances can land the OLDER epoch on the gauge last —
    the exact race PR 13 fixed in ``MembershipController._feed_metrics``."""

    name = "membership-stale-gauge"

    def __init__(self, **kw):
        kw.setdefault("claimed", False)
        super().__init__(**kw)


# ---------------------------------------------------------------------------
# the cml-check pass
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """One model in the pass: what to check and how deep.

    ``max_depth=None`` is full reachability: the shipped correct models
    all have FINITE state spaces at their shipped parameters, so the
    pass proves their invariants over the entire reachable space, not
    just a prefix of it (fixtures keep a finite depth to exercise the
    bounded semantics too).
    """

    model: object
    max_depth: Optional[int]
    max_states: int = 300_000
    # seeded-bug fixtures MUST fail; a fixture the checker cannot
    # refute means the detector is broken (PR 15 pattern)
    expect_violation: bool = False


def builtin_specs() -> list:
    """The shipped correct models, exhaustively explored."""
    return [
        ModelSpec(PoolModel(), max_depth=None),
        ModelSpec(RequestModel(), max_depth=None),
        ModelSpec(MembershipModel(), max_depth=None),
    ]


def fixture_specs() -> list:
    """Seeded-bug variants: each must yield a counterexample."""
    return [
        ModelSpec(DoubleFreePoolModel(), max_depth=8, expect_violation=True),
        ModelSpec(StaleSwapRequestModel(), max_depth=8, expect_violation=True),
        ModelSpec(
            UnclaimedGaugeMembershipModel(), max_depth=8,
            expect_violation=True,
        ),
    ]


def _subject_selected(subject: str, roots, repo_root) -> bool:
    if not roots:
        return True
    target = (Path(repo_root) / subject).resolve()
    for r in roots:
        rp = Path(r).resolve()
        if target == rp or rp in target.parents:
            return True
    return False


def run_builtin(
    roots: Optional[Sequence] = None,
    repo_root: Optional[Path] = None,
    stats: Optional[dict] = None,
) -> list:
    """Run pass 8: check every shipped model, then prove the detector
    still detects by requiring a counterexample from every seeded-bug
    fixture. ``roots`` restricts to models whose SUBJECT file lies
    under one of the given paths (the ``--paths`` contract); a
    fixture runs iff its subject is selected. ``stats``, when given,
    collects per-model state/transition counts for the bench row."""
    repo_root = repo_root or Path(__file__).resolve().parents[2]
    findings: list = []
    for spec in builtin_specs() + fixture_specs():
        m = spec.model
        if not _subject_selected(m.subject, roots, repo_root):
            continue
        try:
            res = check_model(
                m, max_depth=spec.max_depth, max_states=spec.max_states
            )
        except RuntimeError as e:
            findings.append(
                Finding(
                    pass_name="model",
                    rule="state-space-overflow",
                    path=m.subject,
                    symbol=m.name,
                    detail="overflow",
                    message=f"{m.name}: {e}",
                )
            )
            continue
        if stats is not None:
            stats[m.name] = {
                "states": res.states,
                "transitions": res.transitions,
                "depth": res.max_depth,
                "ok": res.ok,
            }
        if spec.expect_violation:
            if res.ok or not res.trace:
                findings.append(
                    Finding(
                        pass_name="model",
                        rule="detector-broken",
                        path=m.subject,
                        symbol=m.name,
                        detail="no-counterexample",
                        message=(
                            f"{m.name}: seeded-bug model produced no "
                            f"counterexample within depth {spec.max_depth} "
                            "— the model checker is not detecting "
                            "violations"
                        ),
                    )
                )
        elif not res.ok:
            slug = (res.violation or "violation").split(":", 1)[0].strip()
            findings.append(
                Finding(
                    pass_name="model",
                    rule="invariant-violated",
                    path=m.subject,
                    symbol=m.name,
                    detail=slug,
                    message=(
                        f"{m.name}: {res.violation} "
                        f"[trace: {res.format_trace()}]"
                    ),
                    counterexample=tuple(
                        _fmt(l) for l in res.trace
                    ),
                )
            )
    return findings


def _fmt(label) -> str:
    head = str(label[0])
    if len(label) == 1:
        return head
    return head + "(" + ", ".join(repr(a) for a in label[1:]) + ")"
