"""Finding model + suppression baseline for the cml-check passes.

Every pass reports :class:`Finding`s. A finding's ``id`` is built from
WHAT was found and WHERE (pass, rule, file, enclosing symbol, detail
token) but deliberately excludes the line number, so a baseline entry
survives unrelated edits to the same file. Two findings in the same
function that trip the same rule on the same callee share an id — a
suppression therefore covers both, which is the right granularity for
"this function intentionally syncs" style allowlisting.

The baseline file (``.cml-check-baseline`` at the repo root) is one
finding id per line; ``#`` starts a comment (inline or whole-line).
Workflow: a NEW finding either gets fixed or — when the sync/access is
intentional — its id is appended to the baseline with a comment saying
why. ``tools/cml_check.py --write-baseline`` regenerates the file from
the current findings; stale entries (baselined ids that no longer fire)
are reported so the allowlist never rots silently.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Iterable

__all__ = [
    "Finding",
    "Baseline",
    "load_baseline",
    "split_suppressed",
    "render_report",
    "to_json",
]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One static-analysis finding.

    ``symbol`` is the dotted path of the enclosing scope inside the file
    (``Class.method`` / ``function.<locals>.inner``); ``detail`` is the
    rule-specific token that makes the id precise (the callee name for a
    host-sync call, the attribute for a lock violation, the topology name
    for a schedule fault).
    """

    pass_name: str  # host-sync | locks | schedule | jaxpr
    rule: str  # e.g. sync-in-traced, unguarded-write, deadlock-op-mismatch
    path: str  # repo-relative file (or a symbolic source for non-file passes)
    symbol: str  # enclosing scope ("" for module level)
    detail: str  # rule-specific token
    message: str  # human sentence
    line: int = 0  # 1-based; 0 when not tied to a source line
    # model-pass findings carry the minimal action trace that reaches the
    # violation, so CI logs hold the repro without rerunning the checker
    counterexample: tuple[str, ...] = ()

    @property
    def id(self) -> str:
        return ":".join(
            (self.pass_name, self.rule, self.path, self.symbol or "<module>",
             self.detail)
        )

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["id"] = self.id
        if not self.counterexample:
            d.pop("counterexample", None)
        else:
            d["counterexample"] = list(self.counterexample)
        return d

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{loc}{sym}: {self.rule}: {self.message}\n    id: {self.id}"


@dataclasses.dataclass
class Baseline:
    """Parsed suppression file: ids plus provenance for stale reporting."""

    path: str | None
    ids: frozenset[str]

    def __contains__(self, finding_id: str) -> bool:
        return finding_id in self.ids


def load_baseline(path: str | None) -> Baseline:
    """Read a baseline file; a missing file is an empty baseline (the
    passes then report everything, which is what a fresh checkout of a
    new project wants)."""
    ids: set[str] = set()
    if path and os.path.exists(path):
        with open(path) as f:
            for raw in f:
                line = raw.split("#", 1)[0].strip()
                if line:
                    ids.add(line)
    return Baseline(path=path, ids=frozenset(ids))


def split_suppressed(
    findings: Iterable[Finding], baseline: Baseline
) -> tuple[list[Finding], list[Finding], list[str]]:
    """``(active, suppressed, stale_baseline_ids)``.

    ``active`` are the findings the run fails on; ``stale`` are baseline
    entries no current finding matches — reported (not fatal) so dead
    suppressions get pruned instead of hiding future regressions under
    an id that happens to match."""
    findings = list(findings)
    active = [f for f in findings if f.id not in baseline]
    suppressed = [f for f in findings if f.id in baseline]
    seen = {f.id for f in findings}
    stale = sorted(i for i in baseline.ids if i not in seen)
    return active, suppressed, stale


def render_report(
    active: list[Finding],
    suppressed: list[Finding],
    stale: list[str],
    *,
    passes_run: list[str],
) -> str:
    lines: list[str] = []
    by_pass: dict[str, list[Finding]] = {}
    for f in active:
        by_pass.setdefault(f.pass_name, []).append(f)
    for name in passes_run:
        fs = by_pass.get(name, [])
        status = "FAIL" if fs else "ok"
        lines.append(f"[{status}] {name}: {len(fs)} finding(s)")
        for f in sorted(fs, key=lambda f: (f.path, f.line, f.id)):
            lines.append("  " + f.render().replace("\n", "\n  "))
    if suppressed:
        lines.append(f"(suppressed by baseline: {len(suppressed)})")
    for sid in stale:
        lines.append(f"(stale baseline entry — prune it: {sid})")
    verdict = "FAILED" if active else "PASSED"
    lines.append(
        f"cml-check {verdict}: {len(active)} active finding(s), "
        f"{len(suppressed)} suppressed, {len(stale)} stale baseline entries"
    )
    return "\n".join(lines)


def to_json(
    active: list[Finding],
    suppressed: list[Finding],
    stale: list[str],
    *,
    passes_run: list[str],
    timings: dict[str, float] | None = None,
) -> str:
    doc = {
        "ok": not active,
        "passes": passes_run,
        "findings": [f.to_dict() for f in active],
        "suppressed": [f.to_dict() for f in suppressed],
        "stale_baseline": stale,
        "counts": {
            "active": len(active),
            "suppressed": len(suppressed),
            "stale": len(stale),
        },
    }
    if timings is not None:
        # per-pass wall seconds (bench_diff gates the AST-pass budgets)
        doc["pass_seconds"] = {k: round(v, 4) for k, v in timings.items()}
    return json.dumps(doc, indent=2)
